"""Per-task hop ledger — a compact monotonic event timeline.

"Why did this task take 4.2 s" has two answers today: grep four services'
logs, or stand up a span collector and hope every hop was sampled. The
ledger is the third, boring answer: every hop a task traverses —
gateway admission, broker publish, dispatcher pop, backend delivery,
batch cut, device phases, terminal transition, plus every shed / retry /
failover / expiry decision with its reason — stamps one tiny event onto
the task's timeline, the timeline rides the task record in the store
(beside the B3 headers that already cross process boundaries), and
``python -m ai4e_tpu trace --task-id … --url <control-plane>`` renders
it with per-hop deltas. No collector, no sampling, one command.

Event shape (compact keys — a task carries dozens of these):

- ``e``: event name (the vocabulary below);
- ``h``: hop that stamped it (``gateway``, ``dispatcher``, ``worker``,
  ``batcher``, ``device``, ``store``);
- ``t``: epoch seconds (wall clock — cross-process alignment is as good
  as the hosts' clocks, same contract as the span log);
- ``r``: optional reason/detail (shed reason, HTTP status, backend host,
  placement outcome);
- ``ms``: optional duration in milliseconds for phase events (h2d,
  execute, d2h — the device phases are intervals, not instants).

The ledger is **observability state, not durable truth**: it lives
beside the record in store memory, is NOT journaled, and is dropped
with the record at retention eviction. A control-plane restart loses
timelines, never tasks (docs/observability.md).
"""

from __future__ import annotations

import threading
import time

# -- event vocabulary (docs/observability.md keeps the operator table) -------

ADMITTED = "admitted"        # gateway accepted the request (task exists)
PUBLISHED = "published"      # task handed to the transport
POPPED = "popped"            # dispatcher received the queue message
PLACED = "placed"            # placement decision (r="outcome backend-host")
DELIVERED = "delivered"      # backend POST answered 2xx
BATCHED = "batched"          # batch cut: example left the pending queue
H2D = "h2d"                  # host→device transfer phase (ms=duration)
COMPILE = "compile"          # first-execution compile phase (ms=duration)
EXECUTE = "execute"          # device execute phase (ms=duration)
D2H = "d2h"                  # device→host fetch phase (ms=duration)
COMPLETED = "completed"      # terminal transition (r=canonical status)
SHED = "shed"                # refused under pressure/brownout (r=reason)
EXPIRED = "expired"          # deadline ran out (r=hop that dropped it)
RETRY = "retry"              # in-delivery retry, same backend (r=cause)
FAILOVER = "failover"        # retry switched backend (r=excluded backend)
PROBE = "probe"              # placement chose a recovery probe (r=backend)
BACKPRESSURE = "backpressure"  # backend saturated; message redelivers
DUPLICATE = "duplicate"      # redelivery suppressed (task already terminal)
DEAD_LETTER = "dead_letter"  # delivery budget exhausted
STAGE = "stage"              # pipeline stage boundary (r="name event" or
                             # "old-path -> new-path" on hop-to-hop handoff)
CHUNK = "chunk"              # streaming first token (ms=TTFT; one stamp
                             # per request — a 512-token stream must not
                             # eat the event cap)
ROLLOUT = "rollout"          # rollout transition (r="worker -> gen" /
                             # "canary weight N%" — the controller's
                             # evidence trail, docs/deployment.md)
ROLLBACK = "rollback"        # rollout aborted (r=breach reason; the
                             # canary burn/breaker trigger is in r)

# Hard cap on events per task: a pathological retry loop must not grow
# a record without bound. The overflow marker is itself an event, once.
MAX_EVENTS = 128
TRUNCATED = "truncated"


def ledger_event(event: str, hop: str, t: float | None = None,
                 reason: str | None = None,
                 ms: float | None = None) -> dict:
    """One timeline event. ``t`` defaults to now; pass an earlier stamp
    for events whose moment precedes the append (e.g. ``admitted`` is
    the request's arrival time, appended after the record exists)."""
    ev: dict = {"e": event, "h": hop,
                "t": time.time() if t is None else t}
    if reason is not None:
        ev["r"] = str(reason)
    if ms is not None:
        ev["ms"] = round(float(ms), 3)
    return ev


class HopLedger:
    """A per-request event buffer for hops that cannot reach the store
    mid-flight (the worker's batcher stamps device phases into one of
    these; the worker flushes it to the store in a single call at the
    end). Thread-safe: device phases are stamped from executor threads
    while the event loop owns the request."""

    __slots__ = ("_events", "_lock")

    def __init__(self):
        self._events: list[dict] = []
        self._lock = threading.Lock()

    def stamp(self, event: str, hop: str, t: float | None = None,
              reason: str | None = None, ms: float | None = None) -> None:
        ev = ledger_event(event, hop, t=t, reason=reason, ms=ms)
        with self._lock:
            if len(self._events) < MAX_EVENTS:
                self._events.append(ev)

    def events(self) -> list[dict]:
        with self._lock:
            return list(self._events)

    def drain(self) -> list[dict]:
        """Take the buffered events, leaving the buffer empty — the
        flush primitive: a second flush (e.g. a finally backstop after
        the success path already flushed) becomes a no-op instead of a
        duplicate timeline."""
        with self._lock:
            events, self._events = self._events, []
            return events


def validate_events(events) -> list[dict]:
    """Sanitize externally-supplied events (the HTTP append surface):
    keep only dicts with a string ``e``/``h`` and a numeric ``t``; the
    optional fields are coerced. Anything else is dropped, not an error
    — a malformed observability event must never fail a task write."""
    out: list[dict] = []
    for ev in events or ():
        if not isinstance(ev, dict):
            continue
        e, h, t = ev.get("e"), ev.get("h"), ev.get("t")
        if not (isinstance(e, str) and isinstance(h, str)
                and isinstance(t, (int, float))):
            continue
        clean: dict = {"e": e, "h": h, "t": float(t)}
        if "r" in ev:
            clean["r"] = str(ev["r"])
        if "ms" in ev:
            try:
                clean["ms"] = float(ev["ms"])
            except (TypeError, ValueError):
                pass
        out.append(clean)
    return out


def render_ledger(task_id: str, events: list[dict],
                  status: str | None = None) -> str:
    """Terminal rendering: header, then one line per event in time order
    with the offset from the first event and the delta from the previous
    one — the "where did the time go" column.

    ::

        task 3f… completed  9 events  412.7ms end-to-end
          +0.0ms               admitted        [gateway]
          +0.3ms    (+0.3ms)   published       [gateway]
          +1.9ms    (+1.6ms)   popped          [dispatcher]
          ...
    """
    if not events:
        return (f"task {task_id}: no ledger events "
                "(observability off, or the timeline was lost to a "
                "control-plane restart)")
    events = sorted(events, key=lambda ev: ev.get("t", 0.0))
    t0 = events[0].get("t", 0.0)
    t_end = max(ev.get("t", 0.0) + ev.get("ms", 0.0) / 1e3
                for ev in events)
    head = (f"task {task_id}"
            + (f"  {status}" if status else "")
            + f"  {len(events)} events"
            + f"  {(t_end - t0) * 1e3:.1f}ms end-to-end")
    lines = [head]
    prev = t0
    for ev in events:
        t = ev.get("t", 0.0)
        off = f"+{(t - t0) * 1e3:.1f}ms"
        delta = f"(+{(t - prev) * 1e3:.1f}ms)" if t > prev else ""
        prev = max(prev, t)
        label = ev.get("e", "?")
        if "ms" in ev:
            label += f" {ev['ms']:.1f}ms"
        if ev.get("r"):
            label += f"  {ev['r']}"
        lines.append(f"  {off:<12} {delta:<12} {label}  [{ev.get('h', '?')}]")
    return "\n".join(lines)
