"""Observability — distributed tracing keyed by TaskId, depth loggers,
the per-task hop ledger, the tail-sampled flight recorder, and the SLO
burn-rate engine (docs/observability.md).

The reference's three tracing mechanisms (SURVEY.md §5): OpenCensus spans
around every endpoint (``APIs/1.0/base-py/ai4e_service.py:158-178``), Istio
mixer x-b3 header mapping into App Insights
(``Cluster/monitoring/application-insights-istio-adapter/configuration.yaml:10-13``),
and ad-hoc Stopwatch latency (``CacheConnectorUpsert.cs:162-201``). Here one
tracer covers all three: in-process spans, x-b3 header propagation across the
gateway → dispatcher → service hops, and span durations exported as metrics —
every span carrying the TaskId so a task's life is one trace.
"""

from .tracing import (
    FanoutExporter,
    InMemoryExporter,
    JsonlExporter,
    LogExporter,
    Span,
    TRACE_HEADER,
    SPAN_HEADER,
    PARENT_HEADER,
    SAMPLED_HEADER,
    Tracer,
    configure_tracer,
    device_trace,
    get_tracer,
)
from .depth_logger import DepthLogger
from .flight import FlightRecorder
from .hub import RequestObservability
from .ledger import HopLedger, ledger_event, render_ledger
from .slo import SloEngine, SloObjective, parse_objectives

__all__ = [
    "DepthLogger",
    "FlightRecorder",
    "HopLedger",
    "RequestObservability",
    "SloEngine",
    "SloObjective",
    "ledger_event",
    "parse_objectives",
    "render_ledger",
    "FanoutExporter",
    "InMemoryExporter",
    "JsonlExporter",
    "LogExporter",
    "Span",
    "TRACE_HEADER",
    "SPAN_HEADER",
    "PARENT_HEADER",
    "SAMPLED_HEADER",
    "Tracer",
    "configure_tracer",
    "device_trace",
    "get_tracer",
]
