"""Observability — distributed tracing keyed by TaskId, depth loggers.

The reference's three tracing mechanisms (SURVEY.md §5): OpenCensus spans
around every endpoint (``APIs/1.0/base-py/ai4e_service.py:158-178``), Istio
mixer x-b3 header mapping into App Insights
(``Cluster/monitoring/application-insights-istio-adapter/configuration.yaml:10-13``),
and ad-hoc Stopwatch latency (``CacheConnectorUpsert.cs:162-201``). Here one
tracer covers all three: in-process spans, x-b3 header propagation across the
gateway → dispatcher → service hops, and span durations exported as metrics —
every span carrying the TaskId so a task's life is one trace.
"""

from .tracing import (
    FanoutExporter,
    InMemoryExporter,
    JsonlExporter,
    LogExporter,
    Span,
    TRACE_HEADER,
    SPAN_HEADER,
    PARENT_HEADER,
    SAMPLED_HEADER,
    Tracer,
    configure_tracer,
    device_trace,
    get_tracer,
)
from .depth_logger import DepthLogger

__all__ = [
    "DepthLogger",
    "FanoutExporter",
    "InMemoryExporter",
    "JsonlExporter",
    "LogExporter",
    "Span",
    "TRACE_HEADER",
    "SPAN_HEADER",
    "PARENT_HEADER",
    "SAMPLED_HEADER",
    "Tracer",
    "configure_tracer",
    "device_trace",
    "get_tracer",
]
