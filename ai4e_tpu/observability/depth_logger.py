"""Periodic queue-depth gauges — the autoscaling signal's source.

The reference runs two timer functions against the Redis status sets:
``TaskQueueLogger`` every 30 s logging each endpoint's ``_created`` depth
(tasks awaiting dispatch, ``ProcessManager/TaskProcessLogger/TaskQueueLogger.cs:19-27``)
and ``TaskProcessLogger`` every 5 min logging ``_running/_completed/_failed``
depths (``TaskProcessLogger.cs:21-31``), both via ``QueueLogger``'s scan of
``*_{status}`` keys (``ProcessManager/Libraries/QueueLogger.cs:21-47``). Those
metrics feed App Insights → the k8s metrics adapter → the HPA (§3.5).

Here both timers are one asyncio component writing to the in-process metrics
registry; the autoscaler (``runtime.autoscaler``) and the ``/metrics``
endpoints read the same gauges.
"""

from __future__ import annotations

import asyncio
import logging

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from ..taskstore import TaskStatus

log = logging.getLogger("ai4e_tpu.depth")


class DepthLogger:
    """Samples per-endpoint task depths from a store into gauges.

    ``queue_interval`` covers the awaiting (= ``created``) depth — the scaling
    signal needs to be fresh (30 s in the reference); ``process_interval``
    covers the running/completed/failed totals (5 min — they only trend).
    """

    def __init__(self, store, metrics: MetricsRegistry | None = None,
                 queue_interval: float = 30.0,
                 process_interval: float = 300.0):
        self.store = store
        self.metrics = metrics or DEFAULT_REGISTRY
        self.queue_interval = queue_interval
        self.process_interval = process_interval
        self._depth = self.metrics.gauge(
            "ai4e_task_depth", "Tasks per endpoint per status")
        # HA visibility (stores with a replica role — FollowerTaskStore):
        # alert on role flips and on a fencing epoch that disagrees across
        # the pair (split-brain would show as two role=1 or epoch skew).
        self._role = self.metrics.gauge(
            "ai4e_store_role", "1 when this replica is the primary")
        self._epoch = self.metrics.gauge(
            "ai4e_store_epoch", "Fencing epoch of this store's lineage")
        self._tasks: list[asyncio.Task] = []

    # -- sampling ----------------------------------------------------------

    def sample_queue_depth(self) -> dict[str, int]:
        """Awaiting-dispatch depth per endpoint (TaskQueueLogger.cs:20-27)."""
        out = {}
        for path, by_status in self.store.depths().items():
            n = by_status.get(TaskStatus.CREATED, 0)
            self._depth.set(float(n), endpoint=path, status=TaskStatus.CREATED)
            out[path] = n
        role = getattr(self.store, "role", None)
        if role is not None:
            self._role.set(1.0 if role == "primary" else 0.0)
            self._epoch.set(float(getattr(self.store, "epoch", 0)))
        return out

    def sample_process_depths(self) -> dict[str, dict[str, int]]:
        """Running/completed/failed depths (TaskProcessLogger.cs:22-31)."""
        all_depths = self.store.depths()
        for path, by_status in all_depths.items():
            for status in (TaskStatus.RUNNING, TaskStatus.COMPLETED,
                           TaskStatus.FAILED):
                self._depth.set(float(by_status.get(status, 0)),
                                endpoint=path, status=status)
        return all_depths

    # -- timers ------------------------------------------------------------

    async def start(self) -> None:
        loop = asyncio.get_running_loop()
        self._tasks = [
            loop.create_task(self._tick(self.queue_interval,
                                        self.sample_queue_depth)),
            loop.create_task(self._tick(self.process_interval,
                                        self.sample_process_depths)),
        ]

    async def stop(self) -> None:
        for t in self._tasks:
            t.cancel()
        await asyncio.gather(*self._tasks, return_exceptions=True)
        self._tasks = []

    async def _tick(self, interval: float, sample) -> None:
        while True:
            try:
                sample()
            except Exception:  # noqa: BLE001 — telemetry must not die
                log.exception("depth sample failed")
            await asyncio.sleep(interval)
