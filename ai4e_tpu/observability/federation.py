"""Fleet metrics federation — the live collector the rig verdict's
post-hoc scrape-merge grew into (docs/deployment.md, docs/observability.md).

The multi-process rig answered the one-assembly-one-registry question
with per-role registries merged once at teardown (``rig/verdict.py``) —
which means the fleet view only ever existed after the fleet was dead.
``FleetCollector`` promotes that merge to a live loop: it scrapes every
role's ``/metrics`` each ``interval_s``, keeps per-proc state (last
series, last-seen value for dead procs — a counter is monotonic, so the
last observation is a usable lower bound), and serves:

- ``snapshot()`` — the ``/v1/debug/fleet`` JSON: per-proc vitals/rates,
  fleet totals, and the conservation cross-check;
- ``render_merged()`` — one Prometheus exposition of every proc's
  series with bounded-cardinality ``role``/``proc`` labels (role =
  proc name stripped of instance digits, so the label space is the
  topology's role set, not its process count; procs beyond
  ``max_procs`` collapse into ``proc="other"``).

**The conservation cross-check** (admitted == terminal, fleet-wide):
scrapes are not atomic across processes, so naive ``terminal <=
admitted`` comparisons false-alarm (tasks admitted between the two
reads may already have terminated). The sound form compares across
ticks: every task terminal by scrape *k* was admitted before scrape
*k+1*, so ``terminal(k) <= admitted(k+1)`` must hold — a breach means
more terminal outcomes than admissions ever issued them: a duplicate
or phantom completion. One honesty caveat: a chaos-killed gateway takes
its tail of un-scraped admissions with it, so once any admitted-side
proc is lost the check keeps running but its breaches are recorded as
``confirmed: false`` (advisory) — the journal-reconciled verdict stays
the authoritative gate, exactly as docs/deployment.md documents.
"""

from __future__ import annotations

import asyncio
import json
import logging
import re
import time
import urllib.request

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry

log = logging.getLogger("ai4e_tpu.observability.federation")

_SERIES_RE = re.compile(
    r"^(?P<name>[a-zA-Z_:][a-zA-Z0-9_:]*)"
    r"(?:\{(?P<labels>[^}]*)\})?\s+(?P<value>[-+0-9.eE]+)$")

_ROLE_RE = re.compile(r"^[a-z_]+")

# Terminal outcomes of ai4e_request_outcomes_total that correspond to a
# finished TASK (the conservation check's terminal side). ``shed`` and
# ``client_error`` never had a task; sync outcomes carry no task either,
# but the rig's conservation surface is async-only.
TASK_TERMINAL_OUTCOMES = ("ok", "late", "expired", "failed")


def parse_prometheus(text: str) -> dict[tuple[str, str], float]:
    """{(metric, sorted-label-string): value} for one exposition page
    (same-key lines sum — histogram buckets keep their ``le``)."""
    out: dict[tuple[str, str], float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        m = _SERIES_RE.match(line)
        if m is None:
            continue
        labels = m.group("labels") or ""
        key = (m.group("name"),
               ",".join(sorted(p.strip() for p in labels.split(",") if p)))
        try:
            out[key] = out.get(key, 0.0) + float(m.group("value"))
        except ValueError:
            continue
    return out


def merge_series(per_proc: dict[str, dict[tuple[str, str], float]]
                 ) -> dict[tuple[str, str], float]:
    """Sum same-(name, labels) series across processes — the teardown
    merge's core, shared with the live collector."""
    merged: dict[tuple[str, str], float] = {}
    for series in per_proc.values():
        for key, value in series.items():
            merged[key] = merged.get(key, 0.0) + value
    return merged


def role_of(proc: str) -> str:
    """``gateway0`` → ``gateway``, ``store1r0`` → ``store``,
    ``dispatcher0.1`` → ``dispatcher`` — the bounded label."""
    m = _ROLE_RE.match(proc)
    return m.group(0) if m else "other"


def render_key(key: tuple[str, str]) -> str:
    name, labels = key
    return f"{name}{{{labels}}}" if labels else name


def _series_sum(series: dict[tuple[str, str], float], name: str,
                label_filter: dict[str, str] | None = None) -> float:
    """Sum of every sample of ``name`` whose labels include
    ``label_filter`` (labels are the sorted ``k="v"`` join)."""
    total = 0.0
    wanted = [f'{k}="{v}"' for k, v in (label_filter or {}).items()]
    for (n, labels), value in series.items():
        if n != name:
            continue
        if all(w in labels for w in wanted):
            total += value
    return total


def _scrape(url: str, timeout: float) -> dict[tuple[str, str], float]:
    with urllib.request.urlopen(url + "/metrics",
                                timeout=timeout) as resp:
        return parse_prometheus(resp.read().decode("utf-8", "replace"))


def fetch_json(url: str, timeout: float = 10.0) -> dict | None:
    """One JSON-over-HTTP GET, None on any transport/parse failure —
    the shared best-effort fetch the rig driver's observability sweep
    and the ``top`` dashboard both use (a dead node contributes
    nothing, which is itself recorded)."""
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read())
    except (OSError, ValueError):
        return None


class FleetCollector:
    """Live periodic scraper over ``targets`` (proc name → base URL).

    Synchronous-scrape-in-threads by design: the collector must keep
    observing a fleet whose event-loop health is one of the things it
    reports, and a hung target only blocks its own thread (bounded by
    ``timeout_s``), never the tick loop.
    """

    def __init__(self, targets: dict[str, str],
                 interval_s: float = 2.0, timeout_s: float = 3.0,
                 metrics: MetricsRegistry | None = None,
                 max_procs: int = 256, conservation: bool = True):
        """``conservation=False`` disables the cross-check (the fleet
        view still serves): its inputs are only sound on the rig's
        async-only surface — a deployment serving sync traffic or
        admission refusals feeds ok/failed/expired outcomes that never
        had a ``created`` admission, and the check would cry VIOLATED
        on a healthy platform. ``top --targets`` (ad-hoc, unknown
        surface) turns it off; the rig collector keeps it on."""
        if not targets:
            raise ValueError("FleetCollector needs at least one target")
        self.targets = dict(targets)
        self.interval_s = interval_s
        self.timeout_s = timeout_s
        self.max_procs = max_procs
        self.conservation = conservation
        self.metrics = metrics or DEFAULT_REGISTRY
        # proc -> {"series", "t", "up", "ever_up"} — series is the last
        # SUCCESSFUL scrape (the monotonic-counter lower bound for dead
        # procs).
        self._state: dict[str, dict] = {}
        self._lock = asyncio.Lock()
        self._task: asyncio.Task | None = None
        self._ticks = 0
        # Conservation state: terminal total at the PREVIOUS tick,
        # whether any admitted-side proc has ever been lost (flips
        # breaches to advisory), and each proc's last admitted value —
        # a DECREASE means the counter reset (supervisor restart with a
        # fresh registry), which loses history exactly like a kill.
        self._prev_terminal: float | None = None
        self._lost_admitted_side = False
        self._prev_admitted_by_proc: dict[str, float] = {}
        self._violations: list[dict] = []
        self._m_up = self.metrics.gauge(
            "ai4e_fleet_up", "Scrape target liveness (1 = last scrape ok)")
        self._m_errors = self.metrics.counter(
            "ai4e_fleet_scrape_errors_total", "Failed scrapes by proc")
        self._m_admitted = self.metrics.gauge(
            "ai4e_fleet_admitted",
            "Fleet-wide tasks admitted (gateway created outcomes; "
            "last-seen lower bound for dead procs)")
        self._m_terminal = self.metrics.gauge(
            "ai4e_fleet_terminal",
            "Fleet-wide terminal task outcomes (ok/late/expired/failed)")
        self._m_inflight = self.metrics.gauge(
            "ai4e_fleet_in_flight", "admitted - terminal at the last tick")
        self._m_violations = self.metrics.counter(
            "ai4e_fleet_conservation_violations_total",
            "Conservation breaches (terminal outran admitted) by "
            "confirmed=true/false — false = counters were lost with a "
            "killed proc, advisory only")

    # -- scraping ------------------------------------------------------------

    async def scrape_once(self) -> None:
        """One tick: scrape every target concurrently (threads), update
        state + conservation under the lock."""
        names = list(self.targets)
        results = await asyncio.gather(
            *(asyncio.to_thread(_scrape, self.targets[n], self.timeout_s)
              for n in names),
            return_exceptions=True)
        now = time.time()
        async with self._lock:
            self._ticks += 1
            for name, result in zip(names, results):
                entry = self._state.setdefault(
                    name, {"series": {}, "t": 0.0, "up": False,
                           "ever_up": False})
                if isinstance(result, BaseException):
                    if entry["up"] or not entry["ever_up"]:
                        log.debug("scrape of %s failed: %s", name, result)
                    if entry["ever_up"] and entry["up"] \
                            and role_of(name) == "gateway":
                        # An admitted-side proc just went dark with an
                        # un-scraped tail of admissions.
                        self._lost_admitted_side = True
                    entry["up"] = False
                    self._m_up.set(0, proc=name)
                    self._m_errors.inc(proc=name)
                    continue
                entry.update(series=result, t=now, up=True, ever_up=True)
                self._m_up.set(1, proc=name)
            self._check_conservation(now)

    def _check_conservation(self, now: float) -> None:
        admitted = 0.0
        terminal = 0.0
        for name, entry in self._state.items():
            series = entry["series"]
            proc_admitted = _series_sum(series,
                                        "ai4e_gateway_requests_total",
                                        {"outcome": "created"})
            prev = self._prev_admitted_by_proc.get(name)
            if prev is not None and proc_admitted < prev:
                # A monotonic counter went BACKWARD: the proc restarted
                # with a fresh registry (supervisor crash-restart — the
                # scrape can succeed against the replacement without
                # ever failing against the corpse, so the up→down
                # transition heuristic misses it). Its prior admissions
                # are lost history; breaches become advisory.
                self._lost_admitted_side = True
            self._prev_admitted_by_proc[name] = proc_admitted
            admitted += proc_admitted
            for outcome in TASK_TERMINAL_OUTCOMES:
                terminal += _series_sum(series,
                                        "ai4e_request_outcomes_total",
                                        {"outcome": outcome})
        self._m_admitted.set(admitted)
        self._m_terminal.set(terminal)
        self._m_inflight.set(admitted - terminal)
        if not self.conservation:
            self._prev_terminal = terminal
            return
        # Sound cross-tick bound: everything terminal by the PREVIOUS
        # tick was admitted before THIS tick's admitted read.
        if self._prev_terminal is not None \
                and self._prev_terminal > admitted:
            confirmed = not self._lost_admitted_side
            if len(self._violations) >= 200:
                self._violations.pop(0)  # bounded: newest 200 kept
            self._violations.append({
                "t": round(now, 2),
                "kind": "terminal_exceeds_admitted",
                "terminal_prev_tick": self._prev_terminal,
                "admitted": admitted,
                "confirmed": confirmed,
            })
            self._m_violations.inc(confirmed=str(confirmed).lower())
            log.warning(
                "fleet conservation breach (%s): %.0f terminal outcomes "
                "by the previous tick vs %.0f admissions ever issued",
                "confirmed" if confirmed else
                "advisory - admitted-side counters were lost",
                self._prev_terminal, admitted)
        self._prev_terminal = terminal

    # -- views ---------------------------------------------------------------

    def snapshot(self) -> dict:
        """The ``/v1/debug/fleet`` JSON: per-proc key stats + fleet
        totals + conservation verdict. Key stats only (the full merged
        exposition is the ``/v1/debug/fleet/metrics`` page) so a 1 Hz
        dashboard poll stays cheap."""
        per_proc = {}
        for name, entry in self._state.items():
            s = entry["series"]
            outcomes = {o: _series_sum(s, "ai4e_request_outcomes_total",
                                       {"outcome": o})
                        for o in TASK_TERMINAL_OUTCOMES + ("shed",)}
            burn = max((v for (n, _l), v in s.items()
                        if n == "ai4e_slo_burn_rate"), default=None)
            per_proc[name] = {
                "role": role_of(name),
                "up": entry["up"],
                "last_scrape": round(entry["t"], 2),
                "requests_total":
                    _series_sum(s, "ai4e_gateway_requests_total")
                    or _series_sum(s, "ai4e_balancer_requests_total")
                    or _series_sum(s, "ai4e_dispatch_total")
                    or _series_sum(s, "ai4e_rig_worker_requests_total"),
                "admitted": _series_sum(s, "ai4e_gateway_requests_total",
                                        {"outcome": "created"}),
                "outcomes": {k: v for k, v in outcomes.items() if v},
                "loop_lag_max_s":
                    _series_sum(s, "ai4e_process_loop_lag_max_seconds")
                    or None,
                "rss_bytes": _series_sum(s, "ai4e_process_rss_bytes")
                    or None,
                "open_fds": _series_sum(s, "ai4e_process_open_fds")
                    or None,
                "cpu_seconds":
                    _series_sum(s, "ai4e_process_cpu_seconds_total")
                    or None,
                "slo_burn_max": burn,
            }
        admitted = self._m_admitted.value()
        terminal = self._m_terminal.value()
        return {
            "t": round(time.time(), 2),
            "ticks": self._ticks,
            "targets": len(self.targets),
            "per_proc": per_proc,
            "fleet": {
                "admitted": admitted,
                "terminal": terminal,
                "in_flight": admitted - terminal,
                "up": sum(1 for e in self._state.values() if e["up"]),
            },
            "conservation": {
                "checked": self.conservation,
                "violations": list(self._violations),
                "confirmed_violations": [v for v in self._violations
                                         if v["confirmed"]],
                "degraded": self._lost_admitted_side,
                "ok": not any(v["confirmed"] for v in self._violations),
            },
        }

    def render_merged(self) -> str:
        """One exposition page of every proc's series with ``role`` and
        ``proc`` labels appended — what a Prometheus scraping only the
        collector sees of the whole fleet. Cardinality is bounded: role
        comes from the (fixed) role alphabet and procs beyond
        ``max_procs`` collapse into ``proc="other"``."""
        lines: list[str] = []
        overflow: dict[tuple[str, str], float] = {}
        for i, (name, entry) in enumerate(sorted(self._state.items())):
            if i >= self.max_procs:
                for key, value in entry["series"].items():
                    overflow[key] = overflow.get(key, 0.0) + value
                continue
            role = role_of(name)
            for (metric, labels), value in sorted(entry["series"].items()):
                extra = f'proc="{name}",role="{role}"'
                label_s = f"{labels},{extra}" if labels else extra
                lines.append(f"{metric}{{{label_s}}} {value}")
        for (metric, labels), value in sorted(overflow.items()):
            extra = 'proc="other",role="other"'
            label_s = f"{labels},{extra}" if labels else extra
            lines.append(f"{metric}{{{label_s}}} {value}")
        return "\n".join(lines) + "\n"

    def merged(self) -> dict[tuple[str, str], float]:
        return merge_series({n: e["series"]
                             for n, e in self._state.items()})

    # -- lifecycle -----------------------------------------------------------

    async def start(self) -> None:
        if self._task is not None:
            return
        self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        task, self._task = self._task, None
        if task is not None:
            task.cancel()
            try:
                await task
            except asyncio.CancelledError:
                pass

    async def _run(self) -> None:
        while True:
            try:
                await self.scrape_once()
            except asyncio.CancelledError:
                raise
            except Exception:  # noqa: BLE001 — one bad tick must not kill the collector; the next tick retries
                log.exception("fleet scrape tick failed")
            await asyncio.sleep(self.interval_s)
