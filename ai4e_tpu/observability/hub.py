"""Request-observability hub — the assembly-owned wiring for the hop
ledger, the flight recorder, and the per-route request telemetry the SLO
engine reads.

One object per platform (``PlatformConfig(observability=True)``),
shared by the gateway and every dispatcher the way the admission
controller and the health model already are. Everything here is
**fail-open**: a ledger stamp that cannot land (task evicted, store
failing over, follower replica) is dropped with a debug log — the
observability layer must never turn a serving success into an error.

Responsibilities:

- ``stamp(task_id, *events)`` — append hop-ledger events to the task's
  record in the store (``InMemoryTaskStore.append_ledger``); in-process
  and cheap for the gateway/dispatchers, which share the store's
  process;
- store listener — tracks each task's creation time per route, and on
  the terminal transition: stamps the ``completed`` ledger event,
  observes the end-to-end latency histogram
  (``ai4e_request_e2e_seconds{route}``, exemplar = task id), counts the
  outcome (``ai4e_request_outcomes_total{route,outcome}``: ``ok`` /
  ``late`` / ``expired`` / ``failed``), and offers the finished
  timeline to the flight recorder;
- ``record_refusal`` / ``observe_sync`` — the request shapes that never
  become tasks (gateway sheds, sync proxy calls) feed the same
  counters and the flight recorder directly.
"""

from __future__ import annotations

import logging
import threading
import time

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry
from .flight import FlightRecorder
from .ledger import COMPLETED, STAGE, ledger_event

log = logging.getLogger("ai4e_tpu.observability")

# In-flight creation-timestamp table bound: tasks that never reach a
# terminal state (a bug this layer exists to surface) must not grow the
# table forever — beyond the cap the OLDEST entries drop, and their
# terminal transition simply records no e2e sample.
_MAX_TRACKED = 65536

# In-flight fire-and-forget wire-stamp bound: against a wedged or
# failing-over shard each append coroutine can live through seconds of
# retries, and an uncapped create_task() on the serving hot path would
# accumulate live tasks/sockets without bound. Beyond the cap the stamp
# is DROPPED — the same fail-open contract as every other ledger path.
_MAX_WIRE_STAMPS = 1024


class RequestObservability:
    def __init__(self, store, metrics: MetricsRegistry | None = None,
                 flight: FlightRecorder | None = None):
        self.store = store
        self.metrics = metrics or DEFAULT_REGISTRY
        self.flight = flight
        self._lock = threading.Lock()
        # Strong refs to in-flight fire-and-forget wire stamps (the loop
        # holds tasks weakly; AIL004) — populated only when the store's
        # append_ledger is a coroutine function (the rig's ring client).
        self._wire_stamps: set = set()
        # task_id -> (created epoch seconds, route label, endpoint path)
        self._created: dict[str, tuple[float, str, str]] = {}
        # backend endpoint path -> published gateway prefix (map_route,
        # fed by the gateway). Task records carry the BACKEND endpoint;
        # without this map, async outcomes would count under the backend
        # path while sheds/sync calls count under the published prefix —
        # and an SLO objective on either label would see only half of
        # one route's traffic (goodput pinned at 0 or 1 during
        # shedding). Unmapped paths (internal pipeline stages,
        # direct-store tasks) keep their own path.
        self._route_map: dict[str, str] = {}
        self._e2e = self.metrics.histogram(
            "ai4e_request_e2e_seconds",
            "End-to-end request latency per route (async: create to "
            "terminal; sync: proxy wall time)")
        self._outcomes = self.metrics.counter(
            "ai4e_request_outcomes_total",
            "Terminal request outcomes per route: ok/late/expired/"
            "failed (tasks) and ok/failed/shed (sync)")
        self._ledger_events = self.metrics.counter(
            "ai4e_ledger_events_total", "Hop-ledger events stamped, by event")
        if hasattr(store, "add_listener"):
            store.add_listener(self._on_task_change)

    # -- route labeling ------------------------------------------------------

    def map_route(self, backend_path: str, public_prefix: str) -> None:
        """Register that tasks whose endpoint path is (or extends)
        ``backend_path`` belong to the published route
        ``public_prefix`` — the ONE label its SLO objectives, outcome
        counters, and e2e histogram all share."""
        with self._lock:
            self._route_map[backend_path] = public_prefix

    def _route_for(self, endpoint_path: str) -> str:
        with self._lock:
            mapped = self._route_map.get(endpoint_path)
            if mapped is not None:
                return mapped
            # Operation tails ('POST prefix/tail') extend the backend
            # path — longest mapped prefix wins, so tails neither
            # fragment the label space nor escape their route.
            best = None
            for backend, public in self._route_map.items():
                if endpoint_path.startswith(backend + "/"):
                    if best is None or len(backend) > len(best[0]):
                        best = (backend, public)
            return best[1] if best is not None else endpoint_path

    # -- ledger stamping -----------------------------------------------------

    def stamp(self, task_id: str, *events: dict) -> None:
        """Append events to the task's hop ledger; never raises. The
        fast path is one store call under the store's own lock. A store
        whose ``append_ledger`` is async (the rig's ring client — the
        timeline lives on the owning SHARD's process) gets a
        fire-and-forget task instead: a stamp must never block the
        serving path it documents, and the wire client already treats
        every failure as a droppable 0."""
        if not events:
            return
        try:
            result = self.store.append_ledger(task_id, list(events))
        except Exception:  # noqa: BLE001 — observability is fail-open: an evicted/failing-over task drops its stamp, serving is untouched
            log.debug("ledger stamp dropped for task %s", task_id,
                      exc_info=True)
            return
        if hasattr(result, "__await__"):
            import asyncio
            try:
                loop = asyncio.get_running_loop()
            except RuntimeError:
                result.close()  # no loop (teardown): drop the stamp
                return
            if len(self._wire_stamps) >= _MAX_WIRE_STAMPS:
                result.close()  # shard wedged: drop, never accumulate
                return
            task = loop.create_task(result)
            self._wire_stamps.add(task)
            task.add_done_callback(self._wire_stamps.discard)
        for ev in events:
            self._ledger_events.inc(event=ev.get("e", "?"))

    # -- store feed ----------------------------------------------------------

    def _on_task_change(self, task) -> None:
        from ..taskstore import TaskStatus
        status = task.canonical_status
        if status not in TaskStatus.TERMINAL:
            if task.status == TaskStatus.CREATED:
                # Stamped once at creation (requeues carry prose); the
                # route label resolves through the gateway's
                # backend→published map so async outcomes and edge
                # refusals share one SLO key.
                path = task.endpoint_path
                route = self._route_for(path)
                stage_from = None
                with self._lock:
                    entry = self._created.get(task.task_id)
                    if entry is None:
                        if len(self._created) >= _MAX_TRACKED:
                            self._created.pop(next(iter(self._created)))
                        self._created[task.task_id] = (time.time(), route,
                                                       path)
                    elif entry[2] != path:
                        # Pipeline handoff: the task was rewritten to
                        # `created` with a NEW endpoint (AddPipelineTask,
                        # service/task_manager.py). Keep the original
                        # creation time + route label (the e2e metric
                        # covers the whole composite) but remember the new
                        # stage path — and stamp the boundary below, so
                        # `trace` shows WHERE one stage ended and the next
                        # began instead of an indistinguishable `created`.
                        self._created[task.task_id] = (entry[0], entry[1],
                                                       path)
                        stage_from = entry[2]
                if stage_from is not None:
                    self.stamp(task.task_id,
                               ledger_event(STAGE, "store",
                                            reason=f"{stage_from} -> "
                                                   f"{path}"))
            return
        now = time.time()
        with self._lock:
            created = self._created.pop(task.task_id, None)
        # completed/failed/expired — one terminal stamp with the
        # canonical bucket as the reason (duplicate terminal transitions
        # are the chaos invariant's job, not the ledger's: re-stamps
        # just add a second completed event, visibly).
        self.stamp(task.task_id,
                   ledger_event(COMPLETED, "store", t=now, reason=status))
        route = (created[1] if created
                 else self._route_for(task.endpoint_path))
        duration_ms = None
        if created is not None:
            duration_s = max(0.0, now - created[0])
            duration_ms = duration_s * 1e3
            self._e2e.observe(duration_s, route=route,
                              exemplar={"task_id": task.task_id})
        deadline_at = getattr(task, "deadline_at", 0.0)
        if status == TaskStatus.COMPLETED:
            outcome = ("late" if deadline_at and now > deadline_at
                       else "ok")
        else:
            outcome = status  # failed | expired
        self._outcomes.inc(route=route, outcome=outcome)
        if self.flight is not None:
            events = []
            getter = getattr(self.store, "get_ledger", None)
            if getter is not None:
                try:
                    events = getter(task.task_id)
                except Exception:  # noqa: BLE001; ai4e: noqa[AIL005] — fail-open: a racing eviction loses the timeline, not the recording
                    events = []
                if hasattr(events, "__await__"):
                    # Wire store: this listener is synchronous; record
                    # the entry without the remote timeline (the shard
                    # node's own flight recorder keeps the full one).
                    events.close()
                    events = []
            self.flight.record(task.task_id, route, status=task.status,
                               duration_ms=duration_ms, events=events,
                               priority=getattr(task, "priority", None))

    # -- request shapes without a task record --------------------------------

    def record_refusal(self, route: str, reason: str,
                       priority: int | None = None) -> None:
        """A gateway shed/expiry that never created a task: counted as a
        terminal outcome for the route and always kept by the flight
        recorder (refusals are interesting by definition)."""
        outcome = "expired" if reason == "expired" else "shed"
        self._outcomes.inc(route=route, outcome=outcome)
        if self.flight is not None:
            self.flight.record(None, route, refusal=reason,
                               priority=priority)

    def observe_sync(self, route: str, duration_s: float,
                     status: int) -> None:
        """One sync-proxy round trip: e2e latency + outcome for the SLO
        engine; slow/failed/shed ones reach the flight recorder.

        Outcome classification mirrors the dispatcher's: 5xx (and the
        proxy's own 502) is a platform failure, 429 is the platform
        refusing (``shed`` — overload SHOULD burn the error budget),
        but any other 4xx is the CLIENT's error — one misbehaving
        client looping malformed POSTs must not page the route's SLO
        or feed brownout evidence (``client_error`` is not in the
        engine's bad set)."""
        self._e2e.observe(duration_s, route=route)
        if 200 <= status < 400:
            outcome = "ok"
        elif status == 429:
            outcome = "shed"
        elif 400 <= status < 500:
            outcome = "client_error"
        else:
            outcome = "failed"
        self._outcomes.inc(route=route, outcome=outcome)
        if self.flight is not None:
            self.flight.record(None, route,
                               status=("ok" if outcome == "ok"
                                       else f"{outcome} - HTTP {status}"),
                               duration_ms=duration_s * 1e3)
