"""Tail-sampled flight recorder — the last N interesting request timelines.

The span log answers "show me this task"; the flight recorder answers
"show me what has been going WRONG lately" without knowing a TaskId:
a bounded ring of recent request timelines that keeps **100 % of the
interesting ones** — slow, failed, expired, shed, refused, failovered —
and a small deterministic sample of the boring rest (so a healthy
baseline is always present for comparison). Dumpable at
``GET /v1/debug/flight`` on the gateway, and dumped automatically by the
chaos harness when an invariant trips (``chaos/invariants.py``), so a
red seeded CI run ships its own evidence.

Tail sampling, not head sampling: the keep/drop decision happens at the
END of the request, when the outcome is known — exactly what a
rate-limited head sampler cannot do (it has already dropped the slow
request's trace by the time it turns out slow).
"""

from __future__ import annotations

import threading
import time
from collections import deque

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry

# Entry reasons, in evaluation order: the first matching reason is the
# one recorded (a failed request that was also slow records "failed").
REASON_FAILED = "failed"
REASON_EXPIRED = "expired"
REASON_SHED = "shed"
REASON_FAILOVER = "failover"
REASON_BACKPRESSURE = "backpressure"
REASON_SLOW = "slow"
REASON_SAMPLED = "sampled"

# Ledger events that make a request interesting, each under its OWN
# reason — an operator filtering reason="failover" must not receive
# saturation (backpressure) noise.
_EVENT_REASONS = {
    "shed": REASON_SHED,
    "expired": REASON_EXPIRED,
    "retry": REASON_FAILOVER,
    "failover": REASON_FAILOVER,
    "backpressure": REASON_BACKPRESSURE,
    "dead_letter": REASON_FAILED,
}


class FlightRecorder:
    """Bounded ring of request timelines with tail-sampling.

    ``capacity``: ring size (oldest entries fall off).
    ``sample``: fraction of UNINTERESTING requests kept (deterministic
    counter stride, not RNG — a seeded chaos run replays identically).
    ``slow_ms``: end-to-end latency at or above which a request is
    interesting regardless of outcome.
    """

    def __init__(self, capacity: int = 512, sample: float = 0.05,
                 slow_ms: float = 1000.0,
                 metrics: MetricsRegistry | None = None):
        if capacity < 1:
            raise ValueError(f"flight capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.sample = min(1.0, max(0.0, sample))
        self.slow_ms = slow_ms
        self.metrics = metrics or DEFAULT_REGISTRY
        self._ring: deque[dict] = deque(maxlen=capacity)
        self._lock = threading.Lock()
        self._seen = 0
        self._boring_seen = 0
        self._kept_boring = 0
        self._recorded = self.metrics.counter(
            "ai4e_flight_recorded_total",
            "Flight-recorder entries kept, by reason")
        self._entries_gauge = self.metrics.gauge(
            "ai4e_flight_entries", "Flight-recorder ring occupancy")

    # -- classification ------------------------------------------------------

    def classify(self, status: str | None, duration_ms: float | None,
                 events: list[dict] | None,
                 refusal: str | None = None) -> str | None:
        """The keep-reason for this request, or None to (maybe-)sample.
        ``refusal`` marks requests that never became tasks (gateway
        sheds/expiries) — always interesting."""
        if refusal is not None:
            return REASON_EXPIRED if refusal == "expired" else REASON_SHED
        s = (status or "").lower()
        if "failed" in s:
            return REASON_FAILED
        if "expired" in s:
            return REASON_EXPIRED
        if s.startswith("shed"):
            # The sync proxy's 429 outcome ("shed - HTTP 429"); prefix
            # match, not substring — "finished" contains "shed".
            return REASON_SHED
        for ev in events or ():
            reason = _EVENT_REASONS.get(ev.get("e"))
            if reason is not None:
                return reason
        if duration_ms is not None and duration_ms >= self.slow_ms:
            return REASON_SLOW
        return None

    # -- recording -----------------------------------------------------------

    def record(self, task_id: str | None, route: str,
               status: str | None = None,
               duration_ms: float | None = None,
               events: list[dict] | None = None,
               trace_id: str | None = None,
               refusal: str | None = None,
               priority: int | None = None) -> bool:
        """Offer one finished request to the ring; returns True if kept.
        Interesting requests always keep; the rest keep at the sample
        stride (every ``1/sample``-th boring request)."""
        reason = self.classify(status, duration_ms, events, refusal=refusal)
        with self._lock:
            self._seen += 1
            if reason is None:
                # Deterministic stride over BORING requests only: boring
                # request k keeps iff floor(k*s) advanced — exactly a
                # ``sample`` fraction of uninteresting traffic,
                # replayable under a seeded chaos run. Striding over ALL
                # requests would inflate the boring keep-rate exactly
                # when most traffic is interesting (an incident), and
                # the sampled baseline would churn the very timelines
                # the ring exists to preserve.
                self._boring_seen += 1
                if self.sample <= 0.0:
                    return False
                kept_target = int(self._boring_seen * self.sample)
                if kept_target <= self._kept_boring:
                    return False
                self._kept_boring = kept_target
                reason = REASON_SAMPLED
            entry = {"ts": time.time(), "reason": reason, "route": route}
            if task_id:
                entry["task_id"] = task_id
            if trace_id:
                entry["trace_id"] = trace_id
            if status is not None:
                entry["status"] = status
            if duration_ms is not None:
                entry["duration_ms"] = round(duration_ms, 3)
            if refusal is not None:
                entry["refusal"] = refusal
            if priority is not None:
                entry["priority"] = priority
            if events:
                entry["events"] = list(events)
            self._ring.append(entry)
            self._entries_gauge.set(len(self._ring))
        self._recorded.inc(reason=reason)
        return True

    # -- dumping -------------------------------------------------------------

    def dump(self) -> dict:
        """The whole ring, newest last, plus accounting — the
        ``/v1/debug/flight`` payload and what the chaos harness writes
        on an invariant violation."""
        with self._lock:
            entries = list(self._ring)
            seen = self._seen
        by_reason: dict[str, int] = {}
        for e in entries:
            by_reason[e["reason"]] = by_reason.get(e["reason"], 0) + 1
        return {"capacity": self.capacity, "sample": self.sample,
                "slow_ms": self.slow_ms, "seen": seen,
                "entries": entries, "by_reason": by_reason}

    def entries(self, reason: str | None = None,
                task_id: str | None = None) -> list[dict]:
        with self._lock:
            out = list(self._ring)
        if reason is not None:
            out = [e for e in out if e["reason"] == reason]
        if task_id is not None:
            out = [e for e in out if e.get("task_id") == task_id]
        return out
