"""SLO objectives + multi-window burn-rate engine.

The platform emits latency histograms and outcome counters; an operator
still has to decide "is this fine". An SLO makes that decision a
declared number: per-route objectives live in ``PlatformConfig``
(``slo_objectives``), the engine periodically snapshots the registry's
own histograms/counters, and exports **burn rate** — how many times
faster than sustainable the error budget is being spent — over a fast
and a slow window (the classic multi-window multi-burn alert shape:
page when BOTH burn, so a blip doesn't page and a slow leak doesn't
hide). Optionally (``slo_ladder``) a sustained breach feeds the PR 7
degradation ladder as an additional miss-evidence source, so the
brownout machinery reacts to SLO burn, not only to deadline-miss
predictions.

Objective grammar (``AI4E_PLATFORM_SLO_OBJECTIVES``)::

    "<route>=<latency_ms>:<target_pct>[,...]"   latency objective
    "<route>=goodput:<target_pct>[,...]"        goodput objective

e.g. ``/v1/echo-async=250:99,/v1/echo=goodput:99.9`` — 99 % of
``/v1/echo-async`` requests end-to-end under 250 ms, and 99.9 % of
``/v1/echo`` requests reach a good terminal outcome.

Sources (both maintained by ``hub.RequestObservability``):

- latency: ``ai4e_request_e2e_seconds{route}`` bucket counts — "good"
  is the cumulative count at the smallest bucket edge >= the threshold
  (the bucket-edge approximation every Prometheus SLO recording rule
  makes; pick thresholds on bucket edges for exactness);
- goodput: ``ai4e_request_outcomes_total{route,outcome}`` — good is
  ``ok``, bad is ``late`` / ``expired`` / ``failed`` / ``shed``.

Burn math: with target t, the error budget is ``1 - t``; over a window
with g good of n total events, ``bad_ratio = 1 - g/n`` and
``burn_rate = bad_ratio / (1 - t)``. Burn 1.0 = spending the budget
exactly as fast as the SLO allows; 14.4 over 5 m is the classic page.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque
from dataclasses import dataclass

from ..metrics import DEFAULT_REGISTRY, MetricsRegistry

log = logging.getLogger("ai4e_tpu.slo")

E2E_HISTOGRAM = "ai4e_request_e2e_seconds"
OUTCOMES_COUNTER = "ai4e_request_outcomes_total"
BAD_OUTCOMES = ("late", "expired", "failed", "shed")


@dataclass(frozen=True)
class SloObjective:
    route: str
    kind: str                  # "latency" | "goodput"
    target: float              # good fraction, e.g. 0.99
    latency_s: float = 0.0     # latency objectives only

    @property
    def budget(self) -> float:
        return max(1e-9, 1.0 - self.target)


def parse_objectives(spec: str | None) -> list[SloObjective]:
    """Parse the config grammar; raises ValueError with the offending
    clause — a malformed objective must fail at assembly, not silently
    monitor nothing."""
    out: list[SloObjective] = []
    for clause in (spec or "").split(","):
        clause = clause.strip()
        if not clause:
            continue
        route, sep, rhs = clause.partition("=")
        route = route.strip()
        if not sep or not route.startswith("/"):
            raise ValueError(
                f"bad SLO objective {clause!r}: expected "
                "'/route=<latency_ms>:<target_pct>' or "
                "'/route=goodput:<target_pct>'")
        what, sep2, pct = rhs.partition(":")
        if not sep2:
            raise ValueError(
                f"bad SLO objective {clause!r}: missing ':<target_pct>'")
        try:
            target = float(pct) / 100.0
        except ValueError as exc:
            raise ValueError(
                f"bad SLO objective {clause!r}: target {pct!r} is not a "
                "number") from exc
        if not (0.0 < target < 1.0):
            raise ValueError(
                f"bad SLO objective {clause!r}: target must be in "
                "(0, 100) percent exclusive")
        if what.strip().lower() == "goodput":
            out.append(SloObjective(route=route, kind="goodput",
                                    target=target))
            continue
        try:
            latency_ms = float(what)
        except ValueError as exc:
            raise ValueError(
                f"bad SLO objective {clause!r}: {what!r} is neither a "
                "latency in ms nor 'goodput'") from exc
        if latency_ms <= 0:
            raise ValueError(
                f"bad SLO objective {clause!r}: latency must be > 0 ms")
        out.append(SloObjective(route=route, kind="latency", target=target,
                                latency_s=latency_ms / 1000.0))
    seen: set[tuple[str, str]] = set()
    for obj in out:
        key = (obj.route, obj.kind)
        if key in seen:
            # The engine keys its snapshot rings and gauges by
            # (route, kind): a second objective of the same kind on one
            # route would silently share a ring (mixed-threshold
            # baselines → bogus burn) and flap the gauge per tick.
            raise ValueError(
                f"duplicate SLO objective for route {obj.route!r} kind "
                f"{obj.kind!r}: one objective per (route, kind)")
        seen.add(key)
    return out


class SloEngine:
    """Snapshots the registry on a tick, keeps a bounded ring of
    snapshots, and exposes windowed burn rates as ``ai4e_slo_*``
    gauges. No background task of its own — the platform assembly owns
    the tick loop (``start()``/``stop()``), and tests drive ``tick(now)``
    with an injected clock."""

    def __init__(self, objectives: list[SloObjective],
                 metrics: MetricsRegistry | None = None,
                 fast_window_s: float = 300.0,
                 slow_window_s: float = 3600.0,
                 tick_s: float = 5.0,
                 clock=time.monotonic):
        if not objectives:
            raise ValueError("SloEngine needs at least one objective")
        if not (0 < fast_window_s <= slow_window_s):
            raise ValueError(
                f"SLO windows need 0 < fast <= slow, got "
                f"{fast_window_s}/{slow_window_s}")
        self.objectives = list(objectives)
        self.metrics = metrics or DEFAULT_REGISTRY
        self.fast_window_s = fast_window_s
        self.slow_window_s = slow_window_s
        self.tick_s = max(0.05, tick_s)
        self._clock = clock
        self._ladder = None
        # Per objective: ring of (now, good, total) cumulative snapshots
        # covering at least the slow window. One ring per (route, kind)
        # — duplicates would silently share it (parse_objectives
        # refuses them; this guards direct constructions too).
        keep = int(slow_window_s / self.tick_s) + 2
        self._snaps: dict[tuple[str, str], deque] = {}
        for o in objectives:
            key = (o.route, o.kind)
            if key in self._snaps:
                raise ValueError(
                    f"duplicate SLO objective for route {o.route!r} "
                    f"kind {o.kind!r}")
            self._snaps[key] = deque(maxlen=keep)
        self._task: asyncio.Task | None = None
        self._burn = self.metrics.gauge(
            "ai4e_slo_burn_rate",
            "Error-budget burn rate per objective and window "
            "(1.0 = spending exactly the budget)")
        self._bad = self.metrics.gauge(
            "ai4e_slo_bad_ratio",
            "Windowed bad-event fraction per objective")
        self._breaches = self.metrics.counter(
            "ai4e_slo_breaches_total",
            "Ticks on which fast AND slow windows both burned > 1")

    def attach_ladder(self, ladder) -> None:
        """Feed sustained breaches to the degradation ladder as miss
        evidence (opt-in; requires orchestration — the assembly wires
        it). Each tick contributes one evidence unit per objective with
        traffic, miss = both windows burning — so SLO burn and deadline
        predictions share one pressure scale."""
        self._ladder = ladder

    # -- snapshot sources ----------------------------------------------------

    def _cumulative(self, objective: SloObjective) -> tuple[float, float]:
        """(good, total) cumulative counts for the objective right now."""
        if objective.kind == "latency":
            hist = self.metrics.histogram(E2E_HISTOGRAM, "")
            good = total = 0.0
            for _kind, _name, labels, data in hist.collect():
                if labels.get("route") != objective.route:
                    continue
                total += data["count"]
                for edge, count in _cumulative_buckets(data["buckets"]):
                    if edge >= objective.latency_s:
                        good += count
                        break
            return good, total
        counter = self.metrics.counter(OUTCOMES_COUNTER, "")
        good = bad = 0.0
        for _kind, _name, labels, value in counter.collect():
            if labels.get("route") != objective.route:
                continue
            if labels.get("outcome") == "ok":
                good += value
            elif labels.get("outcome") in BAD_OUTCOMES:
                bad += value
        return good, good + bad

    # -- ticking -------------------------------------------------------------

    def tick(self, now: float | None = None) -> dict:
        """One evaluation pass; returns {(route, kind): {window: burn}}
        for tests/introspection."""
        now = self._clock() if now is None else now
        out: dict = {}
        for obj in self.objectives:
            key = (obj.route, obj.kind)
            good, total = self._cumulative(obj)
            snaps = self._snaps[key]
            snaps.append((now, good, total))
            burns = {}
            for window_name, window_s in (("fast", self.fast_window_s),
                                          ("slow", self.slow_window_s)):
                base = _snapshot_at(snaps, now - window_s)
                d_good = good - base[1]
                d_total = total - base[2]
                if d_total <= 0:
                    bad_ratio = 0.0
                else:
                    bad_ratio = min(1.0, max(0.0, 1.0 - d_good / d_total))
                burn = bad_ratio / obj.budget
                labels = dict(route=obj.route, kind=obj.kind,
                              window=window_name)
                self._burn.set(burn, **labels)
                self._bad.set(bad_ratio, **labels)
                burns[window_name] = burn
            out[key] = burns
            breached = burns["fast"] > 1.0 and burns["slow"] > 1.0
            if breached:
                self._breaches.inc(route=obj.route, kind=obj.kind)
            if self._ladder is not None:
                # Evidence scaled to the TICK's event count (the delta
                # since the previous snapshot): one bare note per
                # multi-second tick would decay below the ladder's
                # min_rate evidence floor and never move it, and would
                # be diluted to nothing against per-request placement
                # notes. An idle route contributes zero either way.
                prev_total = snaps[-2][2] if len(snaps) >= 2 else 0.0
                tick_events = total - prev_total
                if tick_events > 0:
                    self._ladder.note(miss=breached, n=tick_events)
        return out

    # -- lifecycle (assembly-owned loop) ------------------------------------

    async def start(self) -> None:
        if self._task is None:
            self._task = asyncio.get_running_loop().create_task(self._run())

    async def stop(self) -> None:
        if self._task is not None:
            self._task.cancel()
            try:
                await self._task
            except asyncio.CancelledError:
                pass
            self._task = None

    async def _run(self) -> None:
        while True:
            await asyncio.sleep(self.tick_s)
            try:
                self.tick()
            except Exception:  # noqa: BLE001 — a bad tick must not kill the loop
                log.exception("SLO tick failed")


def _cumulative_buckets(buckets):
    """[(edge, cumulative_count)] from the registry's per-bucket counts."""
    cum = 0
    for edge, count in buckets:
        cum += count
        yield edge, cum


def _snapshot_at(snaps, t: float) -> tuple[float, float, float]:
    """The newest snapshot at or before ``t`` — the window baseline.
    With no snapshot that old (the engine just started), the baseline is
    ZERO: the window is effectively "since start", so an engine brought
    up mid-incident reports the incident instead of a blank first
    window. The snapshot ring is sized past the slow window, so once
    history covers a window this branch never fires again."""
    base = None
    for snap in snaps:
        if snap[0] <= t:
            base = snap
        else:
            break
    return base if base is not None else (t, 0.0, 0.0)
