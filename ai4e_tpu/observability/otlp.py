"""OTLP/HTTP trace exporter — the deployable trace sink.

The reference lands its mesh spans in Application Insights through the Istio
mixer adapter (``Cluster/monitoring/application-insights-istio-adapter/
configuration.yaml:9-84`` + its deployment); without that leg, spans exist
only in-process and evaporate. This module is the same leg for this platform:
spans go to an OpenTelemetry collector over OTLP/HTTP JSON
(``POST {endpoint}`` with an ``ExportTraceServiceRequest`` body), and the
collector fans out to Cloud Trace / Jaeger / anything
(``deploy/charts/otel-collector.yaml``).

Design constraints, in order:
- **Telemetry must never block serving**: ``export`` is an O(1) enqueue; a
  background thread batches and ships. On overflow the OLDEST spans drop
  (newest context survives) and a counter says so.
- **No OTLP SDK dependency**: the wire format is plain JSON over HTTP
  (stdlib urllib); span/trace ids here are already the right widths
  (32/16 hex chars) for OTLP.
"""

from __future__ import annotations

import json
import logging
import threading
import urllib.request
from collections import deque

from .tracing import Span

log = logging.getLogger("ai4e_tpu.trace.otlp")

_STATUS_OK = 1
_STATUS_ERROR = 2


def _hex_id(value: str, width: int) -> str:
    """Normalize an id to exactly ``width`` lowercase hex chars — OTLP
    requires 32/16 and rejects the WHOLE batch otherwise. Inbound B3 headers
    are client-supplied: a 64-bit (16-hex) B3 trace id zero-pads, anything
    malformed maps through a hash so correlation within the trace is kept
    without poisoning the batch."""
    v = (value or "").lower()
    if len(v) <= width:
        try:
            int(v or "0", 16)
            return v.rjust(width, "0")
        except ValueError:
            pass
    import hashlib
    return hashlib.md5(v.encode()).hexdigest()[:width]


def span_to_otlp(span: Span) -> dict:
    """One tracing.Span → one OTLP JSON span."""
    attrs = [{"key": "service.component",
              "value": {"stringValue": span.service}}]
    if span.task_id:
        # TaskId is THE correlation key of this platform (every reference
        # log line carries it, AppInsightsLogger.cs:43-55).
        attrs.append({"key": "ai4e.task_id",
                      "value": {"stringValue": span.task_id}})
    for k, v in span.attrs.items():
        attrs.append({"key": str(k), "value": {"stringValue": str(v)}})
    start_ns = int(span.start * 1e9)
    out = {
        "traceId": _hex_id(span.trace_id, 32),
        "spanId": _hex_id(span.span_id, 16),
        "name": span.name,
        "kind": 1,  # SPAN_KIND_INTERNAL
        "startTimeUnixNano": str(start_ns),
        "endTimeUnixNano": str(start_ns + int(span.duration * 1e9)),
        "attributes": attrs,
        "status": ({"code": _STATUS_ERROR, "message": span.error or ""}
                   if span.status == "error" else {"code": _STATUS_OK}),
    }
    if span.parent_id:
        out["parentSpanId"] = _hex_id(span.parent_id, 16)
    return out


def spans_to_request(spans: list[Span]) -> dict:
    """Batch → ExportTraceServiceRequest JSON, grouped by service name (one
    OTLP resource per service so the collector attributes spans correctly)."""
    by_service: dict[str, list[dict]] = {}
    for span in spans:
        by_service.setdefault(span.service, []).append(span_to_otlp(span))
    return {"resourceSpans": [
        {
            "resource": {"attributes": [
                {"key": "service.name", "value": {"stringValue": service}}]},
            "scopeSpans": [{"scope": {"name": "ai4e_tpu"},
                            "spans": otlp_spans}],
        }
        for service, otlp_spans in by_service.items()]}


class OtlpHttpExporter:
    """Batching OTLP/HTTP JSON exporter.

    ``endpoint`` is the full traces URL (e.g.
    ``http://ai4e-otel-collector:4318/v1/traces``).
    """

    def __init__(self, endpoint: str, flush_interval: float = 2.0,
                 max_batch: int = 512, max_queue: int = 4096,
                 timeout: float = 10.0):
        self.endpoint = endpoint
        self.flush_interval = flush_interval
        self.max_batch = max_batch
        self.max_queue = max_queue
        self.timeout = timeout
        self.dropped = 0          # overflow drops (oldest first)
        self.export_errors = 0    # failed POST batches (spans lost)
        self.exported = 0         # spans successfully shipped
        self._queue: deque[Span] = deque()
        self._cond = threading.Condition()
        self._closed = False
        self._thread = threading.Thread(target=self._run,
                                        name="ai4e-otlp-export", daemon=True)
        self._thread.start()

    def export(self, span: Span) -> None:
        with self._cond:
            if self._closed:
                return
            if len(self._queue) >= self.max_queue:
                self._queue.popleft()
                self.dropped += 1
            self._queue.append(span)
            if len(self._queue) >= self.max_batch:
                self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                if not self._closed and len(self._queue) < self.max_batch:
                    self._cond.wait(self.flush_interval)
                batch = [self._queue.popleft()
                         for _ in range(min(len(self._queue),
                                            self.max_batch))]
                closed = self._closed
            if batch:
                self._post(batch)
            if closed:
                with self._cond:
                    if not self._queue:
                        return

    def _post(self, batch: list[Span]) -> None:
        body = json.dumps(spans_to_request(batch)).encode()
        req = urllib.request.Request(
            self.endpoint, data=body,
            headers={"Content-Type": "application/json"})
        try:
            with urllib.request.urlopen(req, timeout=self.timeout):
                pass
            self.exported += len(batch)
        except Exception as exc:  # noqa: BLE001 — telemetry must not break serving
            self.export_errors += 1
            # Drop the batch: retrying would back up behind a dead collector
            # and the queue bound would shed newer (more useful) spans.
            log.warning("OTLP export of %d spans to %s failed: %s",
                        len(batch), self.endpoint, exc)

    def close(self, timeout: float = 5.0) -> None:
        """Flush what's queued and stop the export thread."""
        with self._cond:
            self._closed = True
            self._cond.notify()
        self._thread.join(timeout)
