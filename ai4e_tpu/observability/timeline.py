"""Chrome-trace / Perfetto timeline export — an entire rig run as ONE
loadable file (docs/observability.md).

``trace --task-id`` answers one task; this module answers the run:
every hop-ledger timeline the driver swept off the shard nodes before
teardown, every measured phase (device h2d/compile/execute/d2h, the
echo worker's service time), every chaos verb at its actual fire time,
and every role's vitals curve (loop lag, RSS) — composed into the
Chrome trace-event JSON that https://ui.perfetto.dev (or
chrome://tracing) loads directly.

Track mapping:

- pid 1 ``chaos``            — instant events (scope ``g``: full-height
  lines) at each verb's fire time;
- pid 2 ``tasks``            — one complete (``X``) slice per task from
  its first to last ledger event, greedily packed into lanes so
  concurrent tasks stack instead of overlap;
- pid 10+ per hop            — ``gateway`` / ``dispatcher`` / ``worker``
  / ``store`` / ``batcher`` / ``device``: instants for point events on
  the task's lane, slices for events carrying ``ms`` durations;
- pid 100+ per proc          — vitals counter tracks
  (``loop_lag_ms`` / ``rss_mb``) and loadgen sample curves.

Timestamps are microseconds relative to the earliest event (Perfetto
renders epoch µs fine, but relative keeps the viewport sane). All
builder inputs are plain dicts — the rig driver feeds live fetches, the
``timeline`` CLI feeds the JSON files the driver wrote beside the
artifact, and both produce byte-identical output for identical input.
"""

from __future__ import annotations

import json

_CHAOS_PID = 1
_TASKS_PID = 2
_HOP_PID0 = 10
_PROC_PID0 = 100


def _lanes(intervals: list[tuple[float, float, str]]) -> dict[str, int]:
    """Greedy interval-graph coloring: task_id -> lane (tid) such that
    overlapping tasks get distinct lanes. Input: (start, end, id)."""
    lanes: dict[str, int] = {}
    busy_until: list[float] = []
    for start, end, tid in sorted(intervals):
        for lane, until in enumerate(busy_until):
            if until <= start:
                busy_until[lane] = end
                lanes[tid] = lane + 1
                break
        else:
            busy_until.append(end)
            lanes[tid] = len(busy_until)
    return lanes


def build_chrome_trace(ledgers: dict[str, list[dict]],
                       chaos: list[dict] | None = None,
                       vitals: dict[str, list[dict]] | None = None,
                       loadgen_samples: dict[str, list[dict]] | None = None
                       ) -> dict:
    """Compose the trace-event document. ``ledgers``: task_id → hop
    events (the ``{"e","h","t","r"?,"ms"?}`` vocabulary); ``chaos``:
    the rig timeline's fired events (``verb`` + wall-clock ``t``);
    ``vitals``: proc name → ``VitalsSampler.recent()`` rings;
    ``loadgen_samples``: loadgen name → 1 Hz accepted/terminal curves."""
    chaos = chaos or []
    vitals = vitals or {}
    loadgen_samples = loadgen_samples or {}

    # Epoch anchor: earliest timestamp anywhere (phases start ms early).
    stamps = [ev.get("t", 0.0) for evs in ledgers.values() for ev in evs]
    stamps += [e["t"] for e in chaos if e.get("t")]
    stamps += [s["t"] for ss in vitals.values() for s in ss if s.get("t")]
    stamps += [s["t"] for ss in loadgen_samples.values()
               for s in ss if s.get("t")]
    t0 = min(stamps) if stamps else 0.0

    def us(t: float) -> float:
        return round((t - t0) * 1e6, 1)

    events: list[dict] = []

    def meta(pid: int, name: str) -> None:
        events.append({"ph": "M", "name": "process_name", "pid": pid,
                       "tid": 0, "args": {"name": name}})

    meta(_CHAOS_PID, "chaos")
    meta(_TASKS_PID, "tasks")

    # -- hops (stable pid per hop name) --------------------------------------
    hops = sorted({ev.get("h", "?") for evs in ledgers.values()
                   for ev in evs})
    hop_pid = {h: _HOP_PID0 + i for i, h in enumerate(hops)}
    for h, pid in hop_pid.items():
        meta(pid, f"hop:{h}")

    # -- task lanes ----------------------------------------------------------
    spans = []
    for tid, evs in ledgers.items():
        if not evs:
            continue
        start = min(ev.get("t", 0.0) for ev in evs)
        end = max(ev.get("t", 0.0) + ev.get("ms", 0.0) / 1e3 for ev in evs)
        spans.append((start, max(end, start), tid))
    lane = _lanes(spans)

    for start, end, tid in spans:
        evs = sorted(ledgers[tid], key=lambda ev: ev.get("t", 0.0))
        terminal = next((ev.get("r") for ev in reversed(evs)
                         if ev.get("e") == "completed"), None)
        events.append({
            "ph": "X", "pid": _TASKS_PID, "tid": lane[tid],
            "ts": us(start), "dur": max(1.0, (end - start) * 1e6),
            "name": terminal or "in-flight",
            "args": {"task_id": tid, "events": len(evs)}})
        for ev in evs:
            pid = hop_pid.get(ev.get("h", "?"), _HOP_PID0)
            name = ev.get("e", "?")
            args = {"task_id": tid}
            if ev.get("r") is not None:
                args["r"] = ev["r"]
            if "ms" in ev:
                # A measured phase: a slice ENDING at the stamp+ms per
                # the ledger's t-is-start contract (render_ledger's
                # end-to-end math).
                events.append({
                    "ph": "X", "pid": pid, "tid": lane[tid],
                    "ts": us(ev.get("t", 0.0)),
                    "dur": max(1.0, ev["ms"] * 1e3),
                    "name": name, "args": args})
            else:
                events.append({
                    "ph": "i", "s": "t", "pid": pid, "tid": lane[tid],
                    "ts": us(ev.get("t", 0.0)),
                    "name": name, "args": args})

    # -- chaos verbs ---------------------------------------------------------
    for e in chaos:
        if not e.get("t"):
            continue  # never fired (cancelled timeline)
        events.append({
            "ph": "i", "s": "g", "pid": _CHAOS_PID, "tid": 0,
            "ts": us(e["t"]),
            "name": e.get("verb", "?"),
            "args": {k: v for k, v in e.items()
                     if k not in ("verb", "t")}})

    # -- vitals + loadgen counters -------------------------------------------
    proc_pid = {}
    for i, proc in enumerate(sorted(set(vitals) | set(loadgen_samples))):
        proc_pid[proc] = _PROC_PID0 + i
        meta(proc_pid[proc], f"proc:{proc}")
    for proc, samples in vitals.items():
        pid = proc_pid[proc]
        for s in samples:
            if "lag_s" in s:
                events.append({"ph": "C", "pid": pid, "tid": 0,
                               "ts": us(s["t"]), "name": "loop_lag_ms",
                               "args": {"lag": round(s["lag_s"] * 1e3,
                                                     3)}})
            if s.get("rss_bytes", -1) >= 0:
                events.append({"ph": "C", "pid": pid, "tid": 0,
                               "ts": us(s["t"]), "name": "rss_mb",
                               "args": {"rss": round(
                                   s["rss_bytes"] / 1048576.0, 1)}})
    for proc, samples in loadgen_samples.items():
        pid = proc_pid[proc]
        for s in samples:
            events.append({"ph": "C", "pid": pid, "tid": 0,
                           "ts": us(s["t"]), "name": "tasks",
                           "args": {"accepted": s.get("accepted", 0),
                                    "terminal": s.get("terminal", 0)}})

    return {"traceEvents": events, "displayTimeUnit": "ms",
            "otherData": {"source": "ai4e_tpu timeline",
                          "epoch_t0": t0,
                          "tasks": len(spans), "hops": hops,
                          "procs": sorted(proc_pid)}}


def build_from_rig_dir(rig_dir: str) -> dict:
    """Compose the timeline from a rig artifact directory — the files
    ``rig/run.py`` writes beside ``rig.json`` (``ledgers.json``,
    ``vitals.json``) plus the chaos timeline and loadgen sample curves
    already inside the artifact. The ``timeline`` CLI's one-call body."""
    import os

    def load(name: str, default):
        path = os.path.join(rig_dir, name)
        if not os.path.exists(path):
            return default
        with open(path, encoding="utf-8") as fh:
            return json.load(fh)

    rig = load("rig.json", {})
    ledgers = load("ledgers.json", {}).get("Ledgers", {})
    vitals = load("vitals.json", {})
    samples = {}
    for w in rig.get("verdict", {}).get("windows", ()):  # loadgen curves
        name = f"loadgen{w.get('loadgen', '?')}"
        if w.get("samples"):
            samples[name] = w["samples"]
    return build_chrome_trace(ledgers, chaos=rig.get("chaos"),
                              vitals=vitals, loadgen_samples=samples)
