"""Lane policy for the broker's weighted-fair (DRR) dequeue.

The mechanism lives in ``broker/queue.py`` (``EndpointQueue`` grows
per-tenant lanes and a deficit-round-robin ring when handed one of
these); this object is the *policy* half the queue consults per decision:

- ``lane_of(msg)`` — which lane a message parks in (its tenant id; ""
  is the shared default lane for tenantless traffic);
- ``quantum(lane)`` — the deficit credit a lane earns per ring visit,
  i.e. the tenant's live weight. Read per visit, not cached, so a weight
  update from ``TenantRegistry.update`` rebalances the very next pops
  without touching queue state (the queue-rebuild alternative is the
  lost-message race tests/test_race_regressions.py pins).

Keeping policy out of the queue keeps ``fair=None`` the true default:
the queue's hot path doesn't know tenants exist, it knows lane keys and
quanta.
"""

from __future__ import annotations

from .registry import TenantRegistry


class TenantLanes:
    def __init__(self, registry: TenantRegistry, min_quantum: float = 0.05):
        if min_quantum <= 0:
            raise ValueError("min_quantum must be > 0")
        self._registry = registry
        # Floor on the per-visit credit: a weight so small the lane would
        # take thousands of ring rotations per message is a configuration
        # foot-gun, not a policy (docs/tenancy.md quota math).
        self._min_quantum = min_quantum

    def lane_of(self, msg) -> str:
        return getattr(msg, "tenant", "") or ""

    def quantum(self, lane: str) -> float:
        return max(self._registry.weight(lane), self._min_quantum)
