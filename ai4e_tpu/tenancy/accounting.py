"""Per-tenant accounting: admissions, outcomes, placement cost, SLO burn.

Four series, all labeled through the registry's bounded mapper
(``tenant_label`` — top-N ids + ``other``, never raw keys; AIL013):

- ``ai4e_tenant_admissions_total{tenant, decision}`` — gateway-edge
  decisions: ``admitted`` vs ``quota_shed`` (the tenant bucket's 429s;
  priority/brownout sheds stay on the admission layer's own series —
  attribution follows the layer that refused);
- ``ai4e_tenant_outcomes_total{tenant, outcome}`` — terminal transitions
  from the task store's change feed: ``ok`` (completed in budget),
  ``late`` (completed past deadline), ``expired``, ``failed``;
- ``ai4e_tenant_cost_total{tenant}`` — placement cost charged by the
  dispatcher at delivery through the orchestration layer's cost model
  (the per-workload charge 2503.20074 argues admission must see);
- ``ai4e_tenant_slo_burn{tenant}`` — gauge: windowed bad fraction over
  the allowed error budget ``(1 - goodput_target)``; 1.0 = burning
  exactly at budget, the noisy-neighbor chaos scenario's flatness check
  reads this per victim tenant.

The burn windows are ``DecayingRate`` pairs (admission/controller.py) —
the same exponential-decay arithmetic the drain estimator uses, so
"window" means the same thing on every dashboard (docs/tenancy.md
residual-windows section covers the decay tail after an incident ends).
"""

from __future__ import annotations

import time

from ..admission import DecayingRate
from .registry import TenantRegistry


class TenantAccounting:
    def __init__(self, registry: TenantRegistry, metrics=None,
                 goodput_target: float = 0.99, burn_tau_s: float = 30.0):
        if not (0.0 < goodput_target < 1.0):
            raise ValueError("goodput_target must be in (0, 1)")
        self._registry = registry
        self._goodput_target = goodput_target
        self._burn_tau_s = burn_tau_s
        # label -> (good_rate, bad_rate); keyed by the BOUNDED label so
        # this dict inherits the top-N + other cap, same as the series.
        self._windows: dict[str, tuple[DecayingRate, DecayingRate]] = {}
        self._admissions = None
        self._outcomes = None
        self._cost = None
        self._burn = None
        if metrics is not None:
            self._admissions = metrics.counter(
                "ai4e_tenant_admissions_total",
                "Gateway-edge tenant decisions (admitted / quota_shed)")
            self._outcomes = metrics.counter(
                "ai4e_tenant_outcomes_total",
                "Terminal task outcomes per tenant (ok/late/expired/failed)")
            self._cost = metrics.counter(
                "ai4e_tenant_cost_total",
                "Placement cost charged to each tenant at delivery")
            self._burn = metrics.gauge(
                "ai4e_tenant_slo_burn",
                "Windowed SLO burn rate per tenant (1.0 = at error budget)")

    # -- gateway edge -------------------------------------------------------

    def note_admitted(self, tenant_id: str) -> None:
        if self._admissions is not None:
            self._admissions.inc(
                tenant=self._registry.tenant_label(tenant_id),
                decision="admitted")

    def note_quota_shed(self, tenant_id: str) -> None:
        if self._admissions is not None:
            self._admissions.inc(
                tenant=self._registry.tenant_label(tenant_id),
                decision="quota_shed")
        # A quota refusal burns the tenant's own budget, nobody else's —
        # that asymmetry is exactly what the chaos scenario asserts.
        self._note_burn(tenant_id, good=False)

    # -- dispatcher ---------------------------------------------------------

    def charge(self, tenant_id: str, cost: float) -> None:
        """Charge placement cost at delivery (dispatcher calls this with
        ``orchestration.cost_of(backend)`` after a successful dispatch)."""
        if self._cost is not None and cost > 0:
            self._cost.inc(cost, tenant=self._registry.tenant_label(tenant_id))

    # -- task store feed ----------------------------------------------------

    def attach_store(self, store) -> None:
        """Subscribe to the same change feed admission's goodput scorer
        rides; independent of the observability layer so per-tenant
        outcome series exist even when that layer is off."""
        from ..taskstore import TaskStatus

        def on_task_change(task) -> None:
            status = task.canonical_status
            if status not in TaskStatus.TERMINAL:
                return
            deadline_at = getattr(task, "deadline_at", 0.0)
            tenant_id = getattr(task, "tenant", "")
            if status == TaskStatus.COMPLETED:
                late = bool(deadline_at) and time.time() > deadline_at
                outcome = "late" if late else "ok"
            elif status == TaskStatus.EXPIRED:
                outcome = "expired"
            else:
                outcome = "failed"
            if self._outcomes is not None:
                self._outcomes.inc(
                    tenant=self._registry.tenant_label(tenant_id),
                    outcome=outcome)
            self._note_burn(tenant_id, good=(outcome == "ok"))

        store.add_listener(on_task_change)

    # -- burn windows -------------------------------------------------------

    def _note_burn(self, tenant_id: str, good: bool) -> None:
        label = self._registry.tenant_label(tenant_id)
        pair = self._windows.get(label)
        if pair is None:
            pair = (DecayingRate(tau_s=self._burn_tau_s),
                    DecayingRate(tau_s=self._burn_tau_s))
            self._windows[label] = pair
        pair[0 if good else 1].on_event()
        if self._burn is not None:
            self._burn.set(self.burn_rate(label), tenant=label)

    def burn_rate(self, label: str) -> float:
        """Bad fraction over the error budget: 0 = clean, 1 = burning at
        exactly ``1 - goodput_target``, >1 = eating into the budget faster
        than the SLO allows."""
        pair = self._windows.get(label)
        if pair is None:
            return 0.0
        good, bad = pair[0].rate(), pair[1].rate()
        total = good + bad
        if total <= 0:
            return 0.0
        return (bad / total) / (1.0 - self._goodput_target)
