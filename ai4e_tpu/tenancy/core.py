"""The ``Tenancy`` facade — one object the assembly threads everywhere.

Binds the four parts (registry, quota, lanes, accounting) so each
consumer takes exactly one handle:

- the gateway calls ``resolve`` / ``admit`` / ``note_admitted`` /
  ``note_quota_shed`` at the edge;
- the broker takes ``.lanes`` as its ``fair=`` policy;
- the dispatcher calls ``charge`` after a successful delivery;
- the assembly calls ``attach_store`` once for the outcome feed.

Construction is pure (no I/O, no task spawned), matching every other
opt-in layer: ``tenancy=False`` assemblies never instantiate this and
stay byte-identical (asserted in tests/test_tenancy.py).
"""

from __future__ import annotations

from .accounting import TenantAccounting
from .lanes import TenantLanes
from .quota import TenantQuota
from .registry import Tenant, TenantRegistry, parse_tenants


class Tenancy:
    def __init__(self, registry: TenantRegistry, metrics=None,
                 goodput_target: float = 0.99, min_quantum: float = 0.05):
        self.registry = registry
        self.quota = TenantQuota(registry)
        self.lanes = TenantLanes(registry, min_quantum=min_quantum)
        self.accounting = TenantAccounting(
            registry, metrics=metrics, goodput_target=goodput_target)

    @classmethod
    def from_spec(cls, spec: str | None, metrics=None,
                  default_weight: float = 1.0, default_rps: float = 0.0,
                  default_burst: float = 0.0, label_top_n: int = 8,
                  goodput_target: float = 0.99,
                  min_quantum: float = 0.05) -> "Tenancy":
        tenants = parse_tenants(spec or "", default_weight=default_weight,
                                default_rps=default_rps,
                                default_burst=default_burst)
        registry = TenantRegistry(tenants, default_weight=default_weight,
                                  default_rps=default_rps,
                                  default_burst=default_burst,
                                  label_top_n=label_top_n)
        return cls(registry, metrics=metrics, goodput_target=goodput_target,
                   min_quantum=min_quantum)

    # -- gateway edge (thin delegations so the router holds one handle) -----

    def resolve(self, key: str | None) -> Tenant:
        return self.registry.resolve(key)

    def admit(self, tenant_id: str) -> tuple[bool, float]:
        return self.quota.admit(tenant_id)

    def note_admitted(self, tenant_id: str) -> None:
        self.accounting.note_admitted(tenant_id)

    def note_quota_shed(self, tenant_id: str) -> None:
        self.accounting.note_quota_shed(tenant_id)

    # -- dispatcher ---------------------------------------------------------

    def charge(self, tenant_id: str, cost: float) -> None:
        self.accounting.charge(tenant_id, cost)

    # -- assembly -----------------------------------------------------------

    def attach_store(self, store) -> None:
        self.accounting.attach_store(store)

    def tenant_label(self, tenant_id: str) -> str:
        return self.registry.tenant_label(tenant_id)
