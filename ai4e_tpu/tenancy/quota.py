"""Per-tenant token-bucket quotas — the admission tier of tenant isolation.

This is the ``quota-by-key`` element of the reference's API-Management
product policy, lifted to the tenant scope: one bucket per *tenant* (all
of a customer's subscription keys draw from it), refilled at the tenant's
contracted ``rps``, capped at its ``burst``. It deliberately mirrors
``gateway/ratelimit.py``'s lazy-refill arithmetic — same burst default,
same retry-after derivation — so the two throttles compose predictably:
the per-key limiter protects the gateway from any single key, this bucket
enforces the *contract* across a tenant's whole key set.

Composition contract (docs/tenancy.md): the tenant bucket runs at the
gateway edge AFTER auth and the per-key limiter, BEFORE the admission
shedder. A refusal here is a 429 whose ``Retry-After`` is the max of the
bucket's own drain time and the admission controller's drain-derived
estimate — the client backs off for whichever bottleneck is slower. It
never *replaces* the priority shedder or brownout ladder: a tenant inside
its quota can still be shed by class when the platform is saturated.
"""

from __future__ import annotations

import threading
import time

from .registry import TenantRegistry


class TenantQuota:
    """Token buckets keyed by tenant id, policy read live from the
    registry on every decision so an operator's rps/burst update takes
    effect on the next request — no bucket rebuild, no restart."""

    def __init__(self, registry: TenantRegistry, now=time.monotonic):
        self._registry = registry
        self._now = now
        # tenant_id -> [tokens, last_refill]; created lazily on first
        # sight and pruned when full-and-idle so a churning key space
        # cannot grow this dict without bound.
        self._buckets: dict[str, list[float]] = {}
        self._lock = threading.Lock()
        self._last_prune = now()

    def admit(self, tenant_id: str) -> tuple[bool, float]:
        """Spend one token from the tenant's bucket.

        Returns ``(allowed, retry_after_seconds)``; ``retry_after`` is 0.0
        when allowed, else the time until one token has refilled — the
        same drain derivation the per-key limiter uses, so a client sees
        one coherent backoff story whichever throttle fired.
        """
        t = self._registry.get(tenant_id) or self._registry.resolve(None)
        rps = t.rps
        if rps <= 0:
            return True, 0.0  # unlimited tenant — quota-exempt by contract
        cap = t.bucket_capacity()
        now = self._now()
        with self._lock:
            bucket = self._buckets.get(tenant_id)
            if bucket is None:
                bucket = [cap, now]
                self._buckets[tenant_id] = bucket
            tokens, last = bucket
            tokens = min(cap, tokens + (now - last) * rps)
            if tokens >= 1.0:
                bucket[0] = tokens - 1.0
                bucket[1] = now
                self._maybe_prune(now)
                return True, 0.0
            bucket[0] = tokens
            bucket[1] = now
            return False, (1.0 - tokens) / rps

    def tokens(self, tenant_id: str) -> float:
        """Current (refilled) token count — introspection for tests and
        the bench per-tenant report, never on the request path."""
        t = self._registry.get(tenant_id)
        if t is None or t.rps <= 0:
            return float("inf")
        now = self._now()
        with self._lock:
            bucket = self._buckets.get(tenant_id)
            if bucket is None:
                return t.bucket_capacity()
            return min(t.bucket_capacity(), bucket[0] + (now - bucket[1]) * t.rps)

    def _maybe_prune(self, now: float, interval: float = 60.0) -> None:
        # Caller holds the lock. Drop buckets that have been idle long
        # enough to be full again — recreating one later is equivalent.
        if now - self._last_prune < interval:
            return
        self._last_prune = now
        for tid in list(self._buckets):
            t = self._registry.get(tid)
            if t is None or t.rps <= 0:
                del self._buckets[tid]
                continue
            tokens, last = self._buckets[tid]
            if tokens + (now - last) * t.rps >= t.bucket_capacity():
                del self._buckets[tid]
