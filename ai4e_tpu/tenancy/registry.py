"""Tenant registry — subscription key → (tenant id, weight, quota, burst).

The reference publishes every API behind an API-Management *product*
subscription: the key a caller presents IS its identity, and throttling/
quota policy hangs off the product, not the individual key
(``APIManagement/create_async_api_management_api.sh:52-80`` attaches each
API to a product whose policy XML carries the rate/quota elements). The
gateway's per-key token buckets (``gateway/ratelimit.py``) reproduce the
throttle but stop short of identity: every key is its own universe, so
nothing can say "these three keys are one customer" or "this customer is
entitled to 4× the scheduler share of that one".

This module is that missing identity tier. A ``Tenant`` bundles the
policy knobs every layer reads:

- ``weight`` — the deficit-round-robin quantum multiplier the broker's
  per-tenant lanes serve by (``broker/queue.py``; docs/tenancy.md);
- ``rps``/``burst`` — the admission token bucket (``tenancy/quota.py``);
  0 rps = unlimited (quota-exempt);

and the registry maps subscription keys onto tenants exactly once, at the
gateway edge — everything downstream (task record, broker message,
dispatcher, metrics) carries the resolved tenant id, never the key.

Cardinality policy: raw tenant ids are unbounded operator input and
subscription keys are secrets — neither may become a metric label. The
blessed mapper is ``tenant_label``: the first ``label_top_n`` registered
tenants keep their own id as the label, everything else (late
registrations included — the label set is FROZEN at construction so a
series never flips identity mid-scrape) collapses into ``other``. The
AIL013 analyzer rule enforces that identity-derived metric labels go
through this mapper (docs/analysis.md).
"""

from __future__ import annotations

from dataclasses import dataclass, field, replace

#: The label every tenant outside the frozen top-N set maps to — including
#: the anonymous/default tenant when it was not explicitly registered.
OTHER_LABEL = "other"

#: Tenant id used for traffic that resolved to no registered key (auth
#: off, unknown key, or a keyless internal caller).
DEFAULT_TENANT = "default"


@dataclass
class Tenant:
    """One tenant's policy row. Immutable by convention — live updates go
    through ``TenantRegistry.update`` with a *replacement* row, so readers
    racing an update see either the old or the new row, never a torn one
    (the explore_interleavings regression in tests/test_race_regressions.py
    holds this to account)."""

    tenant_id: str
    #: DRR quantum multiplier for the broker lanes (docs/tenancy.md).
    weight: float = 1.0
    #: Admission token-bucket refill rate (requests/second); 0 = unlimited.
    rps: float = 0.0
    #: Bucket capacity; 0 → ``max(2 * rps, 1)`` (the ``RateLimit``
    #: convention in gateway/ratelimit.py, kept identical so operators
    #: reason about one burst rule).
    burst: float = 0.0
    #: Subscription keys resolving to this tenant.
    keys: tuple = field(default_factory=tuple)

    def bucket_capacity(self) -> float:
        return self.burst if self.burst > 0 else max(2.0 * self.rps, 1.0)


def parse_tenants(spec: str, default_weight: float = 1.0,
                  default_rps: float = 0.0,
                  default_burst: float = 0.0) -> list[Tenant]:
    """``"alpha=key-a1|key-a2:4:50:100,beta=key-b:1:10"`` → tenants.

    Entry shape: ``name=key[|key...][:weight[:rps[:burst]]]`` — positional
    numeric fields after the key list, omitted ones fall back to the
    configured defaults. Keys may not contain ``,`` ``:`` ``|`` or ``=``
    (the spec's own separators). Malformed entries raise ``ValueError``
    loudly at assembly time, never silently mid-request.
    """
    tenants: list[Tenant] = []
    seen_ids: set[str] = set()
    seen_keys: set[str] = set()
    for entry in (spec or "").split(","):
        entry = entry.strip()
        if not entry:
            continue
        name, sep, rest = entry.partition("=")
        name = name.strip()
        if not sep or not name:
            raise ValueError(
                f"tenant entry {entry!r}: expected name=keys[:weight[:rps"
                f"[:burst]]]")
        if name in seen_ids:
            raise ValueError(f"tenant {name!r} declared twice")
        seen_ids.add(name)
        parts = rest.split(":")
        keys = tuple(k.strip() for k in parts[0].split("|") if k.strip())
        if not keys:
            raise ValueError(f"tenant {name!r}: no subscription keys")
        for k in keys:
            if k in seen_keys:
                raise ValueError(
                    f"subscription key {k!r} mapped to two tenants")
            seen_keys.add(k)
        numbers = []
        for raw in parts[1:4]:
            raw = raw.strip()
            try:
                numbers.append(float(raw)) if raw else numbers.append(None)
            except ValueError as e:
                raise ValueError(
                    f"tenant {name!r}: {raw!r} is not a number") from e
        weight = numbers[0] if len(numbers) > 0 and numbers[0] is not None \
            else default_weight
        rps = numbers[1] if len(numbers) > 1 and numbers[1] is not None \
            else default_rps
        burst = numbers[2] if len(numbers) > 2 and numbers[2] is not None \
            else default_burst
        if weight <= 0:
            raise ValueError(f"tenant {name!r}: weight must be > 0")
        tenants.append(Tenant(tenant_id=name, weight=weight, rps=rps,
                              burst=burst, keys=keys))
    return tenants


class TenantRegistry:
    """Key → tenant resolution plus the frozen bounded-cardinality label
    map. Reads are lock-free dict lookups (GIL-atomic); ``update``
    replaces whole rows with single assignments, so a dequeue racing a
    weight update reads either generation consistently."""

    def __init__(self, tenants: list[Tenant] | None = None,
                 default_weight: float = 1.0, default_rps: float = 0.0,
                 default_burst: float = 0.0, label_top_n: int = 8):
        self._tenants: dict[str, Tenant] = {}
        self._by_key: dict[str, str] = {}
        #: The fallback row for unresolved traffic; its id is DEFAULT_TENANT
        #: unless the spec registered a tenant named "default" explicitly.
        self._default = Tenant(DEFAULT_TENANT, weight=default_weight,
                               rps=default_rps, burst=default_burst)
        for t in tenants or ():
            self._tenants[t.tenant_id] = t
            for k in t.keys:
                self._by_key[k] = t.tenant_id
            if t.tenant_id == DEFAULT_TENANT:
                self._default = t
        # Frozen label set (see module docstring): declaration order, not
        # traffic order — a scrape series must never flip between a real
        # id and "other" as load shifts.
        self._labeled = frozenset(
            list(self._tenants)[:max(0, int(label_top_n))])

    # -- resolution ---------------------------------------------------------

    def resolve(self, key: str | None) -> Tenant:
        """The tenant a subscription key belongs to; the default tenant
        for None/unknown keys (auth-off deployments still get quota and a
        lane — one shared one)."""
        if key:
            tid = self._by_key.get(key)
            if tid is not None:
                t = self._tenants.get(tid)
                if t is not None:
                    return t
        return self._default

    def get(self, tenant_id: str) -> Tenant | None:
        if tenant_id == self._default.tenant_id:
            return self._tenants.get(tenant_id, self._default)
        return self._tenants.get(tenant_id)

    def tenant_ids(self) -> list[str]:
        return list(self._tenants)

    def weight(self, tenant_id: str) -> float:
        """Live DRR weight for a lane key ("" = the default lane). Read
        per dequeue decision so a quota/weight update takes effect on the
        very next pop — no queue rebuild (the rebuild variant is the race
        the explorer regression catches)."""
        t = self._tenants.get(tenant_id) if tenant_id else None
        return (t.weight if t is not None else self._default.weight)

    # -- live updates -------------------------------------------------------

    def update(self, tenant: Tenant) -> None:
        """Install a replacement policy row (weight/rps/burst changes take
        effect on the next decision that reads them). Key bindings are
        append-only here: a key can be added to a tenant live, never
        silently stolen from another."""
        for k in tenant.keys:
            owner = self._by_key.get(k)
            if owner is not None and owner != tenant.tenant_id:
                raise ValueError(
                    f"subscription key {k!r} already belongs to {owner!r}")
        self._tenants[tenant.tenant_id] = tenant
        for k in tenant.keys:
            self._by_key[k] = tenant.tenant_id
        if tenant.tenant_id == self._default.tenant_id:
            self._default = tenant

    def set_weight(self, tenant_id: str, weight: float) -> None:
        """Convenience live-reweight (the rebalance an operator performs
        mid-incident): whole-row replacement, same atomicity story as
        ``update``."""
        if weight <= 0:
            raise ValueError("weight must be > 0")
        t = self.get(tenant_id)
        if t is None:
            raise KeyError(tenant_id)
        self.update(replace(t, weight=weight))

    # -- bounded-cardinality label (the AIL013 blessed mapper) --------------

    def tenant_label(self, tenant_id: str) -> str:
        """THE bounded-cardinality metric label for a tenant id: its own
        id when inside the frozen top-N set, ``other`` for everything
        else — never a raw subscription key, never an unbounded value
        (docs/tenancy.md; enforced by analyzer rule AIL013)."""
        return tenant_id if tenant_id in self._labeled else OTHER_LABEL
