"""Multi-tenancy: per-tenant quotas, weighted-fair scheduling, isolation.

Opt-in via ``PlatformConfig(tenancy=True)`` / ``AI4E_TENANCY_ENABLED=1``
(docs/tenancy.md). Four parts behind one ``Tenancy`` facade:

- ``registry``   — subscription key → (tenant id, weight, rps, burst),
  resolved once at the gateway edge, plus the frozen bounded-cardinality
  ``tenant_label`` mapper (top-N + ``other``; AIL013's blessed path);
- ``quota``      — per-tenant token buckets at admission: 429 with a
  drain-derived ``Retry-After``, composed with (never replacing) the
  priority shedder and brownout ladder;
- ``lanes``      — the policy half of the broker's deficit-round-robin
  per-tenant lanes: a flooded tenant fills its own lane, never another's;
- ``accounting`` — per-tenant admissions/outcomes/cost/SLO-burn series.
"""

from .accounting import TenantAccounting
from .core import Tenancy
from .lanes import TenantLanes
from .quota import TenantQuota
from .registry import (DEFAULT_TENANT, OTHER_LABEL, Tenant, TenantRegistry,
                       parse_tenants)

__all__ = [
    "Tenancy", "TenantAccounting", "TenantLanes", "TenantQuota",
    "TenantRegistry", "Tenant", "parse_tenants", "DEFAULT_TENANT",
    "OTHER_LABEL",
]
