"""Multi-process integration: control plane and worker as SEPARATE OS
processes wired only by HTTP — the multi-host topology SURVEY.md §4 says the
reference never had a test for (its components only ever met in production
Azure). Worker task state flows through HttpTaskManager → task-store HTTP
surface; results through HttpResultStore."""

import io
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_http(url: str, timeout: float = 30.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2):
                return
        except Exception:
            time.sleep(0.2)
    raise TimeoutError(f"{url} never came up")


def http_json(url: str, data: bytes | None = None) -> dict:
    req = urllib.request.Request(url, data=data)
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


@pytest.fixture
def spec_dir(tmp_path):
    return tmp_path


class TestMultiProcess:
    def test_task_flows_across_processes(self, spec_dir):
        cp_port, wk_port = free_port(), free_port()
        cp_base = f"http://127.0.0.1:{cp_port}"
        wk_base = f"http://127.0.0.1:{wk_port}"

        models = {
            "service_name": "echo-worker",
            "prefix": "v1/echo",
            "taskstore": cp_base,
            "models": [{"family": "echo", "name": "echo", "size": 16,
                        "buckets": [4], "sync_path": "/run",
                        "async_path": "/run-async"}],
        }
        routes = {"apis": [
            {"prefix": "/v1/echo/run-async",
             "backend": f"{wk_base}/v1/echo/run-async",
             "concurrency": 2, "retry_delay": 0.1},
            {"prefix": "/v1/echo/run",
             "backend": f"{wk_base}/v1/echo/run", "mode": "sync"},
        ]}
        (spec_dir / "models.json").write_text(json.dumps(models))
        (spec_dir / "routes.json").write_text(json.dumps(routes))

        env = dict(os.environ,
                   AI4E_RUNTIME_PLATFORM="cpu",
                   AI4E_PLATFORM_RETRY_DELAY="0.1",
                   PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
        procs = []
        try:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "ai4e_tpu", "control-plane",
                 "--routes", str(spec_dir / "routes.json"),
                 "--port", str(cp_port)],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT))
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "ai4e_tpu", "worker",
                 "--models", str(spec_dir / "models.json"),
                 "--port", str(wk_port)],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT))

            wait_http(f"{cp_base}/healthz", timeout=60)
            # Generous: worker start pays jit warmup, and a loaded CI host
            # (parallel compile jobs) can stretch it well past 60s.
            wait_http(f"{wk_base}/v1/echo/", timeout=150)

            payload = io.BytesIO()
            np.save(payload, np.arange(16, dtype=np.float32))
            payload = payload.getvalue()

            # Sync across the gateway proxy → worker process.
            sync = http_json(f"{cp_base}/v1/echo/run", data=payload)
            assert sync["echo"][:3] == [0.0, 1.0, 2.0]

            # Async: gateway creates the task; dispatcher POSTs to the other
            # process; worker updates status over HTTP; result lands on the
            # control plane's store.
            task = http_json(f"{cp_base}/v1/echo/run-async", data=payload)
            task_id = task["TaskId"]
            final = http_json(
                f"{cp_base}/v1/taskmanagement/task/{task_id}?wait=30")
            assert "completed" in final["Status"], final

            with urllib.request.urlopen(
                    f"{cp_base}/v1/taskstore/result?taskId={task_id}",
                    timeout=10) as resp:
                result = json.loads(resp.read())
            assert result["echo"][:3] == [0.0, 1.0, 2.0]

            # Worker draining: SIGTERM → exits cleanly.
            procs[1].send_signal(signal.SIGTERM)
            assert procs[1].wait(timeout=15) == 0
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=10)


class TestLiveSplitBrainFencing:
    def test_partitioned_primary_is_fenced_across_processes(self, tmp_path):
        """Live 3-OS-process split-brain drive (VERDICT r4 #3): primary and
        standby control planes as real ``python -m ai4e_tpu control-plane``
        processes; this driver process holds the 'network' between them (a
        togglable proxy the standby replicates through). The primary is
        PARTITIONED — alive and serving — while the standby promotes; a
        write carrying the new epoch is rejected by the old primary
        (503 + X-Not-Primary) and it demotes; on heal it rejoins the new
        primary as a follower automatically."""
        import asyncio

        import aiohttp
        from aiohttp import web

        pri_port, stb_port, net_port = free_port(), free_port(), free_port()
        pri_base = f"http://127.0.0.1:{pri_port}"
        stb_base = f"http://127.0.0.1:{stb_port}"
        net_base = f"http://127.0.0.1:{net_port}"

        routes = {"apis": []}
        (tmp_path / "routes.json").write_text(json.dumps(routes))
        base_env = dict(os.environ,
                        AI4E_PLATFORM_RETRY_DELAY="0.1",
                        AI4E_PLATFORM_FAILOVER_INTERVAL="0.3",
                        AI4E_PLATFORM_FAILOVER_DOWN_AFTER="2",
                        PYTHONPATH=REPO + os.pathsep
                        + os.environ.get("PYTHONPATH", ""))
        pri_env = dict(base_env,
                       AI4E_PLATFORM_JOURNAL_PATH=str(tmp_path / "pri.jsonl"),
                       AI4E_PLATFORM_ADVERTISE_URL=pri_base)
        stb_env = dict(base_env,
                       AI4E_PLATFORM_JOURNAL_PATH=str(tmp_path / "stb.jsonl"),
                       AI4E_PLATFORM_REPLICATE_FROM=net_base,
                       AI4E_PLATFORM_ADVERTISE_URL=stb_base)

        async def main():
            procs = []
            net = {"up": True}
            session = aiohttp.ClientSession()

            async def forward(request: web.Request) -> web.Response:
                if not net["up"]:
                    return web.Response(status=503, text="partitioned")
                async with session.request(
                        request.method, pri_base + request.path_qs,
                        data=await request.read(),
                        headers={k: v for k, v in request.headers.items()
                                 if k.startswith("X-")}) as resp:
                    body = await resp.read()
                    headers = {k: v for k, v in resp.headers.items()
                               if k.startswith("X-")}
                    return web.Response(status=resp.status, body=body,
                                        headers=headers,
                                        content_type=resp.content_type)

            proxy = web.Application()
            proxy.router.add_route("*", "/{tail:.*}", forward)
            runner = web.AppRunner(proxy)
            await runner.setup()
            site = web.TCPSite(runner, "127.0.0.1", net_port)
            await site.start()

            async def get_json(url, **kw):
                async with session.get(url, **kw) as resp:
                    return await resp.json()

            async def wait_until(pred_coro, timeout=30.0):
                deadline = time.time() + timeout
                while time.time() < deadline:
                    try:
                        if await pred_coro():
                            return True
                    except Exception:
                        pass
                    await asyncio.sleep(0.2)
                return False

            try:
                for env in (pri_env, stb_env):
                    port = pri_port if env is pri_env else stb_port
                    procs.append(subprocess.Popen(  # noqa: ASYNC220  # test launches real control-plane processes
                        [sys.executable, "-m", "ai4e_tpu", "control-plane",
                         "--routes", str(tmp_path / "routes.json"),
                         "--port", str(port)],
                        env=env, stdout=subprocess.DEVNULL,
                        stderr=subprocess.STDOUT))
                await asyncio.to_thread(wait_http, f"{pri_base}/healthz", 60)
                await asyncio.to_thread(wait_http, f"{stb_base}/healthz", 60)

                # Seed a task on the primary; wait until the standby
                # mirrors it (replication through the proxy).
                async with session.post(
                        f"{pri_base}/v1/taskstore/upsert",
                        json={"Endpoint": "http://e/v1/x",
                              "Body": "tile"}) as resp:
                    assert resp.status == 200
                    task_id = (await resp.json())["TaskId"]

                async def mirrored():
                    async with session.get(
                            f"{stb_base}/v1/taskstore/task",
                            params={"taskId": task_id}) as resp:
                        return resp.status == 200
                assert await wait_until(mirrored)

                # Partition. The standby promotes; the primary stays up and
                # still believes it is primary — the dangerous window.
                net["up"] = False

                async def stb_promoted():
                    data = await get_json(f"{stb_base}/v1/taskstore/role")
                    return data["role"] == "primary" and data["epoch"] == 1
                assert await wait_until(stb_promoted)
                pri_role = await get_json(f"{pri_base}/v1/taskstore/role")
                assert pri_role["role"] == "primary"
                assert pri_role["epoch"] == 0

                # A write carrying the new epoch reaches the old primary:
                # REJECTED (fenced on contact), not silently accepted.
                async with session.post(
                        f"{pri_base}/v1/taskstore/upsert",
                        json={"Endpoint": "http://e/v1/x",
                              "Body": "doomed"},
                        headers={"X-Store-Epoch": "1"}) as resp:
                    assert resp.status == 503
                    assert resp.headers.get("X-Not-Primary") == "1"
                pri_role = await get_json(f"{pri_base}/v1/taskstore/role")
                assert pri_role["role"] == "follower"
                assert pri_role["epoch"] == 1

                # New-primary writes flow meanwhile.
                async with session.post(
                        f"{stb_base}/v1/taskstore/upsert",
                        json={"Endpoint": "http://e/v1/x",
                              "Body": "post-failover"}) as resp:
                    assert resp.status == 200
                    new_id = (await resp.json())["TaskId"]

                # Heal: the standby's fencing prober nudges the deposed
                # node to rejoin; it mirrors the new primary's lineage.
                net["up"] = True

                async def rejoined():
                    data = await get_json(f"{pri_base}/v1/taskstore/role")
                    if not (data["role"] == "follower"
                            and data.get("replicating")):
                        return False
                    async with session.get(
                            f"{pri_base}/v1/taskstore/task",
                            params={"taskId": new_id}) as resp:
                        return resp.status == 200
                assert await wait_until(rejoined)
            finally:
                for p in procs:
                    if p.poll() is None:
                        p.kill()
                        p.wait(timeout=10)
                await runner.cleanup()
                await session.close()

        asyncio.run(main())


class TestRedriveCLI:
    def test_redrive_verb_against_live_control_plane(self, spec_dir):
        """`python -m ai4e_tpu redrive` (the Service Bus Explorer resubmit
        workflow as a CLI verb) against a real control-plane process: a
        task dead-letters against a dead backend, the CLI sweeps it back
        to created, and the exact-match filter leaves it alone."""
        cp_port, dead_port = free_port(), free_port()
        cp_base = f"http://127.0.0.1:{cp_port}"
        routes = {"apis": [
            {"prefix": "/v1/echo/run-async",
             "backend": f"http://127.0.0.1:{dead_port}/v1/echo/run-async",
             "concurrency": 1, "retry_delay": 0.1},
        ]}
        (spec_dir / "routes.json").write_text(json.dumps(routes))
        env = dict(os.environ,
                   AI4E_RUNTIME_PLATFORM="cpu",
                   AI4E_PLATFORM_RETRY_DELAY="0.1",
                   AI4E_PLATFORM_MAX_DELIVERY_COUNT="1",
                   PYTHONPATH=REPO + os.pathsep + os.environ.get(
                       "PYTHONPATH", ""))
        proc = subprocess.Popen(
            [sys.executable, "-m", "ai4e_tpu", "control-plane",
             "--routes", str(spec_dir / "routes.json"),
             "--port", str(cp_port)],
            env=env, stdout=subprocess.DEVNULL, stderr=subprocess.STDOUT)
        try:
            wait_http(f"{cp_base}/healthz", timeout=60)
            task = http_json(f"{cp_base}/v1/echo/run-async", data=b"BODY")
            tid = task["TaskId"]
            deadline = time.time() + 30
            while time.time() < deadline:
                status = http_json(
                    f"{cp_base}/v1/taskmanagement/task/{tid}")["Status"]
                if "failed" in status:
                    break
                time.sleep(0.2)
            assert "delivery attempts exhausted" in status

            failed_at = http_json(
                f"{cp_base}/v1/taskmanagement/task/{tid}")["Timestamp"]

            # A non-matching filter redrives nothing.
            out = subprocess.run(
                [sys.executable, "-m", "ai4e_tpu", "redrive",
                 "--store", cp_base, "--contains", "no such prose"],
                env=env, capture_output=True, text=True, timeout=60)
            assert out.returncode == 0, out.stderr
            assert json.loads(out.stdout.splitlines()[-1])["redriven"] == 0

            # The default filter sweeps the dead-lettered task.
            out = subprocess.run(
                [sys.executable, "-m", "ai4e_tpu", "redrive",
                 "--store", cp_base],
                env=env, capture_output=True, text=True, timeout=60)
            assert out.returncode == 0, out.stderr
            swept = json.loads(out.stdout.splitlines()[-1])
            assert swept == {"redriven": 1, "task_ids": [tid]}
            # The republished task really re-entered the delivery loop:
            # the record's Timestamp moved past the pre-redrive failure
            # (its Status may read created, mid-backpressure-retry, or —
            # backend still dead at budget 1 — dead-lettered AGAIN).
            record = http_json(f"{cp_base}/v1/taskmanagement/task/{tid}")
            assert record["Timestamp"] > failed_at, record
        finally:
            if proc.poll() is None:
                proc.kill()
                proc.wait(timeout=10)
