"""Multi-process integration: control plane and worker as SEPARATE OS
processes wired only by HTTP — the multi-host topology SURVEY.md §4 says the
reference never had a test for (its components only ever met in production
Azure). Worker task state flows through HttpTaskManager → task-store HTTP
surface; results through HttpResultStore."""

import io
import json
import os
import signal
import socket
import subprocess
import sys
import time
import urllib.request

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def wait_http(url: str, timeout: float = 30.0) -> None:
    deadline = time.time() + timeout
    while time.time() < deadline:
        try:
            with urllib.request.urlopen(url, timeout=2):
                return
        except Exception:
            time.sleep(0.2)
    raise TimeoutError(f"{url} never came up")


def http_json(url: str, data: bytes | None = None) -> dict:
    req = urllib.request.Request(url, data=data)
    with urllib.request.urlopen(req, timeout=10) as resp:
        return json.loads(resp.read())


@pytest.fixture
def spec_dir(tmp_path):
    return tmp_path


class TestMultiProcess:
    def test_task_flows_across_processes(self, spec_dir):
        cp_port, wk_port = free_port(), free_port()
        cp_base = f"http://127.0.0.1:{cp_port}"
        wk_base = f"http://127.0.0.1:{wk_port}"

        models = {
            "service_name": "echo-worker",
            "prefix": "v1/echo",
            "taskstore": cp_base,
            "models": [{"family": "echo", "name": "echo", "size": 16,
                        "buckets": [4], "sync_path": "/run",
                        "async_path": "/run-async"}],
        }
        routes = {"apis": [
            {"prefix": "/v1/echo/run-async",
             "backend": f"{wk_base}/v1/echo/run-async",
             "concurrency": 2, "retry_delay": 0.1},
            {"prefix": "/v1/echo/run",
             "backend": f"{wk_base}/v1/echo/run", "mode": "sync"},
        ]}
        (spec_dir / "models.json").write_text(json.dumps(models))
        (spec_dir / "routes.json").write_text(json.dumps(routes))

        env = dict(os.environ,
                   AI4E_RUNTIME_PLATFORM="cpu",
                   AI4E_PLATFORM_RETRY_DELAY="0.1",
                   PYTHONPATH=REPO + os.pathsep + os.environ.get("PYTHONPATH", ""))
        procs = []
        try:
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "ai4e_tpu", "control-plane",
                 "--routes", str(spec_dir / "routes.json"),
                 "--port", str(cp_port)],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT))
            procs.append(subprocess.Popen(
                [sys.executable, "-m", "ai4e_tpu", "worker",
                 "--models", str(spec_dir / "models.json"),
                 "--port", str(wk_port)],
                env=env, stdout=subprocess.DEVNULL,
                stderr=subprocess.STDOUT))

            wait_http(f"{cp_base}/healthz", timeout=60)
            # Generous: worker start pays jit warmup, and a loaded CI host
            # (parallel compile jobs) can stretch it well past 60s.
            wait_http(f"{wk_base}/v1/echo/", timeout=150)

            payload = io.BytesIO()
            np.save(payload, np.arange(16, dtype=np.float32))
            payload = payload.getvalue()

            # Sync across the gateway proxy → worker process.
            sync = http_json(f"{cp_base}/v1/echo/run", data=payload)
            assert sync["echo"][:3] == [0.0, 1.0, 2.0]

            # Async: gateway creates the task; dispatcher POSTs to the other
            # process; worker updates status over HTTP; result lands on the
            # control plane's store.
            task = http_json(f"{cp_base}/v1/echo/run-async", data=payload)
            task_id = task["TaskId"]
            final = http_json(
                f"{cp_base}/v1/taskmanagement/task/{task_id}?wait=30")
            assert "completed" in final["Status"], final

            with urllib.request.urlopen(
                    f"{cp_base}/v1/taskstore/result?taskId={task_id}",
                    timeout=10) as resp:
                result = json.loads(resp.read())
            assert result["echo"][:3] == [0.0, 1.0, 2.0]

            # Worker draining: SIGTERM → exits cleanly.
            procs[1].send_signal(signal.SIGTERM)
            assert procs[1].wait(timeout=15) == 0
        finally:
            for p in procs:
                if p.poll() is None:
                    p.kill()
                    p.wait(timeout=10)
