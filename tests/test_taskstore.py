"""Unit tests for the task state machine — the test pyramid base SURVEY.md §4
says the reference lacks (created→running→completed/failed transitions +
sorted-set bookkeeping mirroring ``CacheConnectorUpsert.cs:133-142``)."""

import threading

import pytest

from ai4e_tpu.taskstore import (
    APITask,
    InMemoryTaskStore,
    JournaledTaskStore,
    TaskNotFound,
    TaskStatus,
)


def make_task(**kw):
    defaults = dict(endpoint="http://host/v1/landcover/classify", body=b'{"x":1}')
    defaults.update(kw)
    return APITask(**defaults)


class TestLifecycle:
    def test_create_assigns_id_and_created_status(self):
        store = InMemoryTaskStore()
        t = store.upsert(make_task())
        assert t.task_id
        got = store.get(t.task_id)
        assert got.status == TaskStatus.CREATED
        assert got.endpoint_path == "/v1/landcover/classify"

    def test_full_transition_chain(self):
        store = InMemoryTaskStore()
        t = store.upsert(make_task())
        path = t.endpoint_path
        assert store.set_members(path, "created") == [t.task_id]

        store.update_status(t.task_id, "running - model executing")
        assert store.set_len(path, "created") == 0
        assert store.set_members(path, "running") == [t.task_id]
        assert store.get(t.task_id).canonical_status == TaskStatus.RUNNING

        store.update_status(t.task_id, "completed - 3 animals found")
        assert store.set_len(path, "running") == 0
        assert store.set_members(path, "completed") == [t.task_id]

    def test_failure_transition(self):
        store = InMemoryTaskStore()
        t = store.upsert(make_task())
        store.update_status(t.task_id, "failed: boom")
        assert store.get(t.task_id).canonical_status == TaskStatus.FAILED
        assert store.set_len(t.endpoint_path, "failed") == 1

    def test_update_unknown_task_raises(self):
        with pytest.raises(TaskNotFound):
            InMemoryTaskStore().update_status("nope", "running")

    def test_get_unknown_task_raises(self):
        with pytest.raises(TaskNotFound):
            InMemoryTaskStore().get("nope")

    def test_status_canonicalisation(self):
        assert TaskStatus.canonical("Awaiting service availability") == "created"
        assert TaskStatus.canonical("task failed - oom") == "failed"
        assert TaskStatus.canonical("Completed.") == "completed"
        assert TaskStatus.canonical("running (batch 2/5)") == "running"


class TestSortedSets:
    def test_members_ordered_by_score(self):
        store = InMemoryTaskStore()
        ids = [store.upsert(make_task()).task_id for _ in range(5)]
        assert store.set_members("/v1/landcover/classify", "created") == ids

    def test_depths_per_endpoint(self):
        store = InMemoryTaskStore()
        store.upsert(make_task())
        t2 = store.upsert(make_task(endpoint="http://host/v1/detector"))
        store.update_status(t2.task_id, "running")
        d = store.depths()
        assert d["/v1/landcover/classify"]["created"] == 1
        assert d["/v1/detector"]["running"] == 1
        assert d["/v1/detector"]["created"] == 0


class TestPublish:
    def test_publish_true_invokes_publisher(self):
        published = []
        store = InMemoryTaskStore(publisher=published.append)
        t = store.upsert(make_task(publish=True))
        assert [p.task_id for p in published] == [t.task_id]

    def test_publish_false_does_not_invoke(self):
        published = []
        store = InMemoryTaskStore(publisher=published.append)
        store.upsert(make_task(publish=False))
        assert published == []

    def test_publish_failure_fails_task(self):
        # CacheConnectorUpsert.cs:183-199 — broker down must not lose the task
        # silently; it rolls to failed.
        def boom(_):
            raise RuntimeError("broker down")

        store = InMemoryTaskStore(publisher=boom)
        t = store.upsert(make_task(publish=True))
        assert store.get(t.task_id).canonical_status == TaskStatus.FAILED

    def test_pipeline_replays_original_body(self):
        # CacheConnectorUpsert.cs:144-176: empty body on a publishing upsert of
        # an existing task replays {taskId}_ORIG.
        published = []
        store = InMemoryTaskStore(publisher=published.append)
        t = store.upsert(make_task(body=b"ORIGINAL", publish=True))
        hop = APITask(
            task_id=t.task_id, endpoint="http://host/v1/classifier", body=b"", publish=True
        )
        store.upsert(hop)
        assert published[-1].body == b"ORIGINAL"
        assert store.get(t.task_id).endpoint_path == "/v1/classifier"

    def test_handoff_body_becomes_replay_body(self):
        # A handoff WITH a payload (detector passes crops to the classifier)
        # re-bases the replay body: a later empty-body requeue of the new
        # stage must get the stage's own input, not stage 1's.
        published = []
        store = InMemoryTaskStore(publisher=published.append)
        t = store.upsert(make_task(body=b"STAGE1-IMAGE", publish=True))
        store.upsert(APITask(task_id=t.task_id,
                             endpoint="http://host/v1/classifier",
                             body=b"CROPS", publish=True))
        store.upsert(APITask(task_id=t.task_id,
                             endpoint="http://host/v1/classifier",
                             body=b"", publish=True))
        assert published[-1].body == b"CROPS"


class TestConcurrency:
    def test_parallel_transitions_keep_sets_consistent(self):
        store = InMemoryTaskStore()
        tasks = [store.upsert(make_task()) for _ in range(50)]

        def flip(t):
            store.update_status(t.task_id, "running")
            store.update_status(t.task_id, "completed")

        threads = [threading.Thread(target=flip, args=(t,)) for t in tasks]
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        path = tasks[0].endpoint_path
        assert store.set_len(path, "created") == 0
        assert store.set_len(path, "running") == 0
        assert store.set_len(path, "completed") == 50


class TestJournal:
    def test_restart_replays_state(self, tmp_path):
        journal = str(tmp_path / "tasks.jsonl")
        store = JournaledTaskStore(journal)
        t1 = store.upsert(make_task(body=b"abc"))
        t2 = store.upsert(make_task())
        store.update_status(t1.task_id, "completed")
        store.close()

        revived = JournaledTaskStore(journal)
        assert revived.get(t1.task_id).canonical_status == TaskStatus.COMPLETED
        assert revived.get(t2.task_id).canonical_status == TaskStatus.CREATED
        assert revived.get_original_body(t1.task_id) == b"abc"
        path = t1.endpoint_path
        assert revived.set_len(path, "completed") == 1
        assert revived.set_len(path, "created") == 1


class TestContentTypeReplay:
    def test_pipeline_replay_restores_original_content_type(self):
        """A JPEG task republished with an empty body must replay both the
        original bytes AND image/jpeg — replaying as application/json would
        make the image preprocess undecodable downstream."""
        from ai4e_tpu.taskstore import APITask, InMemoryTaskStore

        store = InMemoryTaskStore()
        published = []
        store.set_publisher(lambda t: published.append(
            (t.body, t.content_type)))
        task = store.upsert(APITask(endpoint="/v1/detect", body=b"\xff\xd8JPG",
                                    content_type="image/jpeg", publish=True))
        # Pipeline republish (empty body): replay body + content type.
        store.upsert(APITask(task_id=task.task_id, endpoint="/v1/classify",
                             body=b"", publish=True))
        assert published[-1] == (b"\xff\xd8JPG", "image/jpeg")

    def test_unfinished_tasks_restore_content_type(self):
        from ai4e_tpu.taskstore import APITask, InMemoryTaskStore

        store = InMemoryTaskStore()
        task = store.upsert(APITask(endpoint="/v1/detect", body=b"IMG",
                                    content_type="image/png"))
        store.update_status(task.task_id, "running")
        # Simulate the journal-restore path (body emptied on the record).
        store._tasks[task.task_id].body = b""
        restored = store.unfinished_tasks()
        assert restored[0].body == b"IMG"
        assert restored[0].content_type == "image/png"

    def test_journal_round_trips_orig_content_type(self, tmp_path):
        import os

        from ai4e_tpu.taskstore import APITask, JournaledTaskStore

        path = os.path.join(str(tmp_path), "j.jsonl")
        store = JournaledTaskStore(path)
        task = store.upsert(APITask(endpoint="/v1/detect", body=b"RAWJPG",
                                    content_type="image/jpeg"))
        store.close()

        store2 = JournaledTaskStore(path)
        published = []
        store2.set_publisher(lambda t: published.append(
            (t.body, t.content_type)))
        store2.upsert(APITask(task_id=task.task_id, endpoint="/v1/next",
                              body=b"", publish=True))
        assert published == [(b"RAWJPG", "image/jpeg")]
        store2.close()


class TestJournalGrowth:
    def test_transitions_journal_slim_records(self, tmp_path):
        """Status transitions must not re-append the (hex-doubled) payload:
        a big-bodied task with many transitions journals its body exactly
        once."""
        import os

        journal = str(tmp_path / "slim.jsonl")
        store = JournaledTaskStore(journal)
        body = b"\xab" * 50_000
        t = store.upsert(make_task(body=body))
        base = os.path.getsize(journal)
        assert base > len(body)  # create record carries the body (hex)
        for i in range(10):
            store.update_status(t.task_id, f"running - step {i}")
        store.update_status(t.task_id, "completed")
        growth = os.path.getsize(journal) - base
        assert growth < 5_000, (
            f"transitions appended {growth}B — bodies are riding updates")
        store.close()

        revived = JournaledTaskStore(journal)
        assert revived.get(t.task_id).canonical_status == TaskStatus.COMPLETED
        assert revived.get_original_body(t.task_id) == body
        revived.close()

    def test_compaction_shrinks_and_preserves_state(self, tmp_path):
        import os

        journal = str(tmp_path / "compact.jsonl")
        store = JournaledTaskStore(journal)
        tasks = [store.upsert(make_task(body=b"payload-%d" % i))
                 for i in range(5)]
        for t in tasks:
            for k in range(20):
                store.update_status(t.task_id, f"running - {k}")
            store.update_status(t.task_id, "completed")
        before = os.path.getsize(journal)
        store.compact()
        after = os.path.getsize(journal)
        assert after < before
        # One record per live task.
        with open(journal) as f:
            assert sum(1 for line in f if line.strip()) == len(tasks)
        store.close()

        revived = JournaledTaskStore(journal)
        for i, t in enumerate(tasks):
            assert revived.get(t.task_id).canonical_status == "completed"
            assert revived.get_original_body(t.task_id) == b"payload-%d" % i
        revived.close()

    def test_auto_compaction_bounds_journal(self, tmp_path):
        journal = str(tmp_path / "auto.jsonl")
        store = JournaledTaskStore(journal, compact_every=50)
        t = store.upsert(make_task(body=b"x"))
        for i in range(300):
            store.update_status(t.task_id, f"running - {i}")
        # 300 transitions with compact_every=50: the journal was rewritten,
        # so record count stays far below the mutation count.
        with open(journal) as f:
            lines = sum(1 for line in f if line.strip())
        assert lines <= 60, lines
        store.close()

        revived = JournaledTaskStore(journal)
        assert "299" in revived.get(t.task_id).status
        revived.close()

    def test_replay_compacts_bloated_journal_at_open(self, tmp_path):
        import os

        journal = str(tmp_path / "open.jsonl")
        store = JournaledTaskStore(journal)  # default threshold: no runtime compaction
        t = store.upsert(make_task(body=b"y"))
        for i in range(40):
            store.update_status(t.task_id, f"running - {i}")
        store.close()
        bloated = os.path.getsize(journal)

        revived = JournaledTaskStore(journal)  # open-time compaction
        assert os.path.getsize(journal) < bloated
        assert "39" in revived.get(t.task_id).status
        assert revived.get_original_body(t.task_id) == b"y"
        revived.close()


class TestDurableResults:
    """VERDICT r2 #4: completed tasks must survive restart WITH their results,
    and large results must route to the object-store slot instead of store
    memory (the reference's blob-storage role,
    ``APIs/helpers/assign_storage_auth_to_aks.sh:9-17``)."""

    def test_results_survive_restart(self, tmp_path):
        journal = str(tmp_path / "r.jsonl")
        store = JournaledTaskStore(journal)
        t = store.upsert(make_task())
        store.update_status(t.task_id, "completed - done")
        store.set_result(t.task_id, b'{"animals": 3}')
        store.set_result(t.task_id, b"stage-out", stage="detector")
        store.close()

        revived = JournaledTaskStore(journal)
        assert revived.get(t.task_id).canonical_status == "completed"
        assert revived.get_result(t.task_id) == (b'{"animals": 3}',
                                                 "application/json")
        assert revived.get_result(t.task_id, stage="detector") == (
            b"stage-out", "application/json")
        revived.close()

    def test_large_result_offloads_to_backend(self, tmp_path):
        from ai4e_tpu.taskstore import FileResultBackend

        backend = FileResultBackend(str(tmp_path / "blobs"))
        store = InMemoryTaskStore(result_backend=backend,
                                  result_offload_threshold=1024)
        t = store.upsert(make_task())
        big = b"\x42" * 4096
        store.set_result(t.task_id, big, content_type="application/octet-stream")
        # Memory holds only the pointer; the payload is in the backend.
        assert store._results[t.task_id][0] is None
        assert backend.get(t.task_id) == (big, "application/octet-stream")
        # The read surface is unchanged.
        assert store.get_result(t.task_id) == (big, "application/octet-stream")

    def test_small_result_stays_inline(self, tmp_path):
        from ai4e_tpu.taskstore import FileResultBackend

        backend = FileResultBackend(str(tmp_path / "blobs"))
        store = InMemoryTaskStore(result_backend=backend,
                                  result_offload_threshold=1024)
        t = store.upsert(make_task())
        store.set_result(t.task_id, b"tiny")
        assert store._results[t.task_id][0] == b"tiny"
        assert backend.get(t.task_id) is None

    def test_offloaded_result_survives_restart(self, tmp_path):
        from ai4e_tpu.taskstore import FileResultBackend

        journal = str(tmp_path / "r.jsonl")
        blobs = str(tmp_path / "blobs")
        store = JournaledTaskStore(journal,
                                   result_backend=FileResultBackend(blobs),
                                   result_offload_threshold=1024)
        t = store.upsert(make_task())
        big = b"\x7f" * 8192
        store.set_result(t.task_id, big, content_type="image/png")
        store.close()
        # The journal holds a pointer, not the blob (no hex-doubling).
        import os
        assert os.path.getsize(journal) < 4096

        revived = JournaledTaskStore(journal,
                                     result_backend=FileResultBackend(blobs),
                                     result_offload_threshold=1024)
        assert revived.get_result(t.task_id) == (big, "image/png")
        revived.close()

    def test_compaction_preserves_results(self, tmp_path):
        journal = str(tmp_path / "c.jsonl")
        store = JournaledTaskStore(journal)
        t = store.upsert(make_task())
        store.set_result(t.task_id, b"keep me")
        for i in range(20):
            store.update_status(t.task_id, f"running - {i}")
        store.compact()
        store.close()

        revived = JournaledTaskStore(journal)
        assert revived.get_result(t.task_id) == (b"keep me",
                                                 "application/json")
        revived.close()

    def test_stage_key_is_filesystem_safe(self, tmp_path):
        from ai4e_tpu.taskstore import FileResultBackend

        backend = FileResultBackend(str(tmp_path / "blobs"))
        store = InMemoryTaskStore(result_backend=backend,
                                  result_offload_threshold=0)
        t = store.upsert(make_task())
        store.set_result(t.task_id, b"x" * 10, stage="v1/detect")
        assert store.get_result(t.task_id, stage="v1/detect") == (
            b"x" * 10, "application/json")

    def test_unknown_task_offload_leaves_no_orphan_blob(self, tmp_path):
        import os

        from ai4e_tpu.taskstore import FileResultBackend

        blobs = str(tmp_path / "blobs")
        store = InMemoryTaskStore(result_backend=FileResultBackend(blobs),
                                  result_offload_threshold=0)
        with pytest.raises(TaskNotFound):
            store.set_result("no-such-task", b"x" * 64)
        assert os.listdir(blobs) == []

    def test_distinct_stage_keys_do_not_collide(self, tmp_path):
        from ai4e_tpu.taskstore import FileResultBackend

        backend = FileResultBackend(str(tmp_path / "blobs"))
        store = InMemoryTaskStore(result_backend=backend,
                                  result_offload_threshold=0)
        t = store.upsert(make_task())
        store.set_result(t.task_id, b"slash", stage="x/y")
        store.set_result(t.task_id, b"under", stage="x_y")
        assert store.get_result(t.task_id, stage="x/y")[0] == b"slash"
        assert store.get_result(t.task_id, stage="x_y")[0] == b"under"

    def test_inline_rewrite_deletes_stale_blob(self, tmp_path):
        import os

        from ai4e_tpu.taskstore import FileResultBackend

        blobs = str(tmp_path / "blobs")
        store = InMemoryTaskStore(result_backend=FileResultBackend(blobs),
                                  result_offload_threshold=100)
        t = store.upsert(make_task())
        store.set_result(t.task_id, b"B" * 200)      # offloaded
        assert len(os.listdir(blobs)) == 2
        store.set_result(t.task_id, b"small")        # superseded inline
        assert os.listdir(blobs) == []
        assert store.get_result(t.task_id)[0] == b"small"

    def test_replay_of_offloaded_pointer_without_backend_fails_fast(
            self, tmp_path):
        from ai4e_tpu.taskstore import FileResultBackend

        journal = str(tmp_path / "j.jsonl")
        store = JournaledTaskStore(
            journal, result_backend=FileResultBackend(str(tmp_path / "b")),
            result_offload_threshold=0)
        t = store.upsert(make_task())
        store.set_result(t.task_id, b"blob-bytes")
        store.close()
        with pytest.raises(RuntimeError, match="offloaded result"):
            JournaledTaskStore(journal)  # no backend configured


class TestTerminalEviction:
    """Terminal-history retention: a long-running store must not grow
    forever with finished tasks (the Redis-expiry role the reference's
    store leans on)."""

    def _finish(self, store, body=b"payload", result=None):
        t = store.upsert(make_task(body=body))
        store.update_status(t.task_id, "completed - done")
        if result is not None:
            store.set_result(t.task_id, result)
        return t

    def test_evicts_old_terminal_keeps_young_and_running(self):
        import time as _time

        store = InMemoryTaskStore()
        old = self._finish(store, result=b"r1")
        running = store.upsert(make_task())
        store.update_status(running.task_id, "running - inference")
        # Age the finished task's set score artificially.
        path = old.endpoint_path
        store._sets[(path, "completed")][old.task_id] = _time.time() - 1000
        store._tasks[old.task_id].timestamp = _time.time() - 1000
        young = self._finish(store, result=b"r2")

        assert store.evict_terminal_older_than(500) == 1
        with pytest.raises(TaskNotFound):
            store.get(old.task_id)
        assert store.get_result(old.task_id) is None
        assert store.get_original_body(old.task_id) == b""
        assert store.set_len(path, "completed") == 1  # young survives
        assert store.get(young.task_id).canonical_status == "completed"
        assert store.get(running.task_id).canonical_status == "running"

    def test_eviction_deletes_offloaded_blobs(self, tmp_path):
        import os
        import time as _time

        from ai4e_tpu.taskstore import FileResultBackend

        blobs = str(tmp_path / "blobs")
        store = InMemoryTaskStore(result_backend=FileResultBackend(blobs),
                                  result_offload_threshold=0)
        t = self._finish(store, result=b"blob-bytes" * 10)
        assert len(os.listdir(blobs)) == 2
        store._sets[(t.endpoint_path, "completed")][t.task_id] = (
            _time.time() - 1000)
        assert store.evict_terminal_older_than(500) == 1
        assert os.listdir(blobs) == []

    def test_eviction_survives_restart_and_shrinks_journal(self, tmp_path):
        import os
        import time as _time

        journal = str(tmp_path / "e.jsonl")
        store = JournaledTaskStore(journal)
        tasks = [self._finish(store, body=b"x" * 500, result=b"y" * 500)
                 for _ in range(5)]
        for t in tasks[:4]:
            store._sets[(t.endpoint_path, "completed")][t.task_id] = (
                _time.time() - 1000)
        assert store.evict_terminal_older_than(500) == 4
        store.compact()
        compacted = os.path.getsize(journal)
        store.close()

        revived = JournaledTaskStore(journal)
        for t in tasks[:4]:
            with pytest.raises(TaskNotFound):
                revived.get(t.task_id)
        assert revived.get(tasks[4].task_id).canonical_status == "completed"
        assert revived.get_result(tasks[4].task_id) == (
            b"y" * 500, "application/json")
        # The journal holds ~1 task (~3.4 kB with hex-doubled body/orig/
        # result), not 5 (~17 kB).
        assert compacted < 6000, compacted
        revived.close()

    def test_evict_records_replay_without_compaction(self, tmp_path):
        import time as _time

        journal = str(tmp_path / "r.jsonl")
        store = JournaledTaskStore(journal)
        t = self._finish(store)
        store._sets[(t.endpoint_path, "completed")][t.task_id] = (
            _time.time() - 1000)
        assert store.evict_terminal_older_than(500) == 1
        store.close()  # no compaction: journal = upsert + slim + evict

        revived = JournaledTaskStore(journal)
        with pytest.raises(TaskNotFound):
            revived.get(t.task_id)
        revived.close()

    def test_reaper_drives_eviction(self):
        import time as _time

        from ai4e_tpu.taskstore.reaper import TaskReaper

        async def main():
            store = InMemoryTaskStore()
            t = self._finish(store)
            store._sets[(t.endpoint_path, "completed")][t.task_id] = (
                _time.time() - 1000)
            reaper = TaskReaper(store, running_timeout=None,
                                terminal_retention=500)
            acted = await reaper.sweep()
            assert acted == 1
            with pytest.raises(TaskNotFound):
                store.get(t.task_id)

        import asyncio
        asyncio.run(main())

    def test_eviction_is_order_independent(self, tmp_path):
        """Journal compaction rewrites tasks in CREATION order, so terminal
        sets are not score-monotone after a restart — an old task sitting
        behind a young one must still evict (review repro, r3)."""
        import time as _time

        journal = str(tmp_path / "o.jsonl")
        store = JournaledTaskStore(journal)
        a = self._finish(store)  # created first...
        b = self._finish(store)
        path = a.endpoint_path
        # ...but A completed recently while B completed long ago (age both
        # the set score and the record timestamp — compaction persists the
        # latter).
        store._sets[(path, "completed")][b.task_id] = _time.time() - 10000
        store._tasks[b.task_id].timestamp = _time.time() - 10000
        store.compact()  # rewrite in creation order: A (young) before B (old)
        store.close()

        revived = JournaledTaskStore(journal)
        assert revived.evict_terminal_older_than(5000) == 1
        with pytest.raises(TaskNotFound):
            revived.get(b.task_id)
        assert revived.get(a.task_id).canonical_status == "completed"
        revived.close()

    def test_native_store_with_retention_refused(self):
        from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
        with pytest.raises(ValueError, match="eviction"):
            LocalPlatform(PlatformConfig(native_store=True,
                                         reaper_terminal_retention=60.0))


class TestDirectToStorageResults:
    """The reference's containers write batch outputs straight to blob
    storage (assign_storage_auth_to_aks.sh) — here workers write the shared
    result mount and register only a pointer."""

    def test_ref_registers_existing_blob(self, tmp_path):
        from ai4e_tpu.taskstore import FileResultBackend

        backend = FileResultBackend(str(tmp_path / "blobs"))
        store = InMemoryTaskStore(result_backend=backend,
                                  result_offload_threshold=10**9)
        t = store.upsert(make_task())
        backend.put(t.task_id, b"worker-wrote-this", "application/json")
        store.set_result_ref(t.task_id)
        assert store.get_result(t.task_id) == (b"worker-wrote-this",
                                               "application/json")

    def test_ref_without_blob_refused(self, tmp_path):
        from ai4e_tpu.taskstore import FileResultBackend

        store = InMemoryTaskStore(
            result_backend=FileResultBackend(str(tmp_path / "b")))
        t = store.upsert(make_task())
        with pytest.raises(FileNotFoundError):
            store.set_result_ref(t.task_id)

    def test_ref_without_backend_refused(self):
        store = InMemoryTaskStore()
        t = store.upsert(make_task())
        with pytest.raises(RuntimeError, match="backend"):
            store.set_result_ref(t.task_id)

    def test_journaled_ref_survives_restart(self, tmp_path):
        from ai4e_tpu.taskstore import FileResultBackend

        journal = str(tmp_path / "j.jsonl")
        blobs = str(tmp_path / "blobs")
        backend = FileResultBackend(blobs)
        store = JournaledTaskStore(journal, result_backend=backend)
        t = store.upsert(make_task())
        backend.put(t.task_id, b"direct" * 100, "application/octet-stream")
        store.set_result_ref(t.task_id,
                             content_type="application/octet-stream")
        store.close()

        revived = JournaledTaskStore(journal,
                                     result_backend=FileResultBackend(blobs))
        assert revived.get_result(t.task_id) == (
            b"direct" * 100, "application/octet-stream")
        revived.close()


class TestEvictionScales:
    def test_bulk_eviction_is_linear_in_victims(self):
        """Eviction must be O(victims' results), not O(victims × all
        results): the 40-min soak wedged the control plane for minutes when
        ~6k victims each scanned ~190k result keys under the store lock
        (bench_results/r5-cpu/). 20k tasks-with-results evicted here in
        well under the old quadratic path's ~40 s."""
        import time as _time

        from ai4e_tpu.taskstore import InMemoryTaskStore
        from ai4e_tpu.taskstore.task import APITask

        store = InMemoryTaskStore()
        for i in range(20000):
            t = store.upsert(APITask(task_id=f"t{i}", endpoint="http://h/v1/x",
                                     body=b"b", status="completed",
                                     backend_status="completed"))
            store.set_result(t.task_id, b'{"ok":1}')
        t0 = _time.perf_counter()
        evicted = store.evict_terminal_older_than(0.0)
        elapsed = _time.perf_counter() - t0
        assert evicted == 20000
        assert not store._results and not store._result_keys
        assert elapsed < 10.0, f"bulk eviction took {elapsed:.1f}s"

    def test_colon_task_ids_rejected(self):
        """':' is the result-key stage separator — a client-supplied id
        carrying one would alias another task's result namespace (the
        eviction index derives the owner by splitting on ':'), so it is
        refused at every write boundary."""
        import pytest

        from ai4e_tpu.taskstore import InMemoryTaskStore
        from ai4e_tpu.taskstore.task import APITask

        store = InMemoryTaskStore()
        with pytest.raises(ValueError, match="must not contain"):
            store.upsert(APITask(task_id="job:7", endpoint="http://h/v1/x",
                                 body=b"b"))
