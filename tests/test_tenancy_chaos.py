"""Noisy-neighbor chaos acceptance for multi-tenancy
(``ai4e_tpu/tenancy/``, docs/tenancy.md):

Three tenants share one async route on a 2-shard store behind seeded
background fault noise (5xx bursts + duplicate deliveries). All three
hold the same quota and the same fair-share weight. The measured run
drives the ``noisy`` tenant at 10× its rated request rate while both
victims run exactly at rated; the baseline run is the identical seeded
workload with ``noisy`` also at rated.

The bar, per ISSUE 16:

- the victims never notice: each victim's within-deadline goodput holds
  within 15% of its own fault-free-neighbor baseline, its tenant-quota
  shed count is ZERO, and its SLO burn stays under budget;
- only the noisy tenant sheds: the 10× flood is refused at the edge
  with 429 + ``Retry-After`` (tenant-quota provenance), and the noisy
  tenant burns its OWN error budget, nobody else's;
- the ``InvariantChecker`` is clean — 0 lost tasks, 0 duplicate
  completions — globally AND per shard, in both runs.

Replays on the fixed ``AI4E_CHAOS_SEED`` CI pin (chaos-smoke job), and
in the multi-process rig via ``scripts/rig_noisy_neighbor.sh``.
"""

import asyncio
import os

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.chaos import (FaultInjector, InvariantChecker,
                            RestartableBackend, wrap_platform_http,
                            wrap_publish_duplicates)
from ai4e_tpu.metrics import MetricsRegistry
from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
from ai4e_tpu.taskstore import TaskStatus

SEED = int(os.environ.get("AI4E_CHAOS_SEED", "20260803"))

DEADLINE_MS = 5000.0
RATED_RPS = 40.0           # every tenant's contracted rate
VICTIM_REQUESTS = 30       # per victim, paced just under rated
NOISY_MULT = 10            # the flood: 10× volume at 10× pace

# noisy gets a tight burst so the flood sheds at the edge instead of
# parking an unbounded lane backlog; victims get a full-run burst so
# rated pacing never brushes their own bucket.
TENANT_SPEC = (f"noisy=key-noisy:1:{RATED_RPS:g}:20,"
               f"victim1=key-v1:1:{RATED_RPS:g}:{VICTIM_REQUESTS},"
               f"victim2=key-v2:1:{RATED_RPS:g}:{VICTIM_REQUESTS}")


def run(coro):
    return asyncio.run(coro)


async def serve(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


def _platform():
    return LocalPlatform(PlatformConfig(
        tenancy=True,
        tenancy_tenants=TENANT_SPEC,
        # One late straggler in 30 must not read as a burned budget:
        # 10% budget → burn 1.0 means >3 victim stragglers, noise-proof.
        tenancy_goodput_target=0.9,
        admission=True,          # composed with, not replaced by, quotas
        resilience=True,
        task_shards=2,
        retry_delay=0.01,
        lease_seconds=2.0,
        resilience_retry_base_s=0.001,
        resilience_failure_threshold=3,
        resilience_recovery_seconds=0.2), metrics=MetricsRegistry())


def _completing_app(platform) -> web.Application:
    """Fast worker: adopt then complete via conditional writes — the
    service-shell discipline an at-least-once transport requires."""
    async def handler(request):
        tid = request.headers["taskId"]
        body = await request.read()
        platform.store.update_status_if(tid, "created", "running",
                                        TaskStatus.RUNNING)
        platform.store.update_status_if(
            tid, "running", f"completed - scored {len(body)}",
            TaskStatus.COMPLETED)
        return web.Response(text="ok")

    app = web.Application()
    app.router.add_post("/v1/be/x", handler)
    return app


async def _warm_drain(gw, checker, n=30, timeout=30.0):
    """Warm the admission drain estimator with keyless (default-tenant,
    unmetered) traffic — identical in every run, so the baseline and the
    flood run compare apples-to-apples."""
    ids = []
    for _ in range(n):
        resp = await gw.post("/v1/pub/x", data=b"warm")
        assert resp.status == 200, resp.status
        tid = (await resp.json())["TaskId"]
        checker.note_accepted(tid)
        ids.append(tid)
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if all(t in checker.terminal for t in ids):
            return
        await asyncio.sleep(0.05)
    raise AssertionError("drain warm-up never completed")


async def _drive_tenant(gw, checker, key: str, requests: int, pace_s: float,
                        retry_non_quota: bool) -> dict:
    """Open-ish loop for one tenant. Victims (``retry_non_quota``) honor
    the platform's client contract for PRESSURE sheds (back off on 429
    and re-issue) but treat a tenant-quota 429 as a contract violation;
    the noisy tenant takes every shed and keeps flooding."""
    accepted = quota_shed = other_shed = 0
    for _ in range(requests):
        for attempt in range(40):
            resp = await gw.post(
                "/v1/pub/x", data=b"payload",
                headers={"Ocp-Apim-Subscription-Key": key,
                         "X-Deadline-Ms": str(int(DEADLINE_MS))})
            if resp.status == 200:
                checker.note_accepted((await resp.json())["TaskId"])
                accepted += 1
                break
            assert resp.status == 429, (key, resp.status)
            if "tenant-quota" in resp.headers.get("X-Shed-Reason", ""):
                assert int(resp.headers["Retry-After"]) >= 1
                quota_shed += 1
                break  # the tenant's own contract — no retry credit
            other_shed += 1
            if not retry_non_quota:
                break
            await asyncio.sleep(0.05)
        else:
            raise AssertionError(f"{key} refused for the whole retry budget")
        await asyncio.sleep(pace_s)
    return {"accepted": accepted, "quota_shed": quota_shed,
            "other_shed": other_shed}


async def _drive_noisy_neighbor(noisy: bool) -> dict:
    """One seeded run; ``noisy`` floods the noisy tenant at 10×."""
    platform = _platform()
    backends = []
    for _ in range(2):
        be = await RestartableBackend(_completing_app(platform)).start()
        backends.append(be)
    platform.publish_async_api(
        "/v1/pub/x", [(f"{be.url}/v1/be/x", 1.0) for be in backends])

    checker = InvariantChecker(
        shard_of=platform.store.shard_for).attach(platform.store)

    injector = FaultInjector(seed=SEED)
    injector.add_rule(error_rate=0.08, error_status=500)
    injector.add_rule(backend="/v1/be/x", duplicate_rate=0.05)
    wrap_platform_http(platform, injector)
    wrap_publish_duplicates(platform, injector)

    # Rated pacing sits just under the contracted rate; the flood runs
    # 10× the volume at 10× the pace.
    rated_pace = 1.25 / RATED_RPS
    gw = await serve(platform.gateway.app)
    await platform.start()
    try:
        await _warm_drain(gw, checker)
        results = await asyncio.gather(
            _drive_tenant(gw, checker, "key-noisy",
                          VICTIM_REQUESTS * (NOISY_MULT if noisy else 1),
                          rated_pace / (NOISY_MULT if noisy else 1),
                          retry_non_quota=False),
            _drive_tenant(gw, checker, "key-v1", VICTIM_REQUESTS,
                          rated_pace, retry_non_quota=True),
            _drive_tenant(gw, checker, "key-v2", VICTIM_REQUESTS,
                          rated_pace, retry_non_quota=True))
        by_tenant = dict(zip(("noisy", "victim1", "victim2"), results))

        # Drain: every accepted task terminal.
        deadline = asyncio.get_running_loop().time() + 40.0
        while asyncio.get_running_loop().time() < deadline:
            if all(t in checker.terminal for t in checker.accepted):
                break
            await asyncio.sleep(0.05)

        checker.assert_ok()
        for shard in range(2):
            checker.assert_shard_ok(shard)

        outcomes = platform.metrics.counter("ai4e_tenant_outcomes_total", "")
        admissions = platform.metrics.counter(
            "ai4e_tenant_admissions_total", "")
        return {
            "by_tenant": by_tenant,
            "ok": {t: outcomes.value(tenant=t, outcome="ok")
                   for t in ("noisy", "victim1", "victim2")},
            "edge_shed": {t: admissions.value(tenant=t, decision="quota_shed")
                          for t in ("noisy", "victim1", "victim2")},
            "burn": {t: platform.tenancy.accounting.burn_rate(t)
                     for t in ("noisy", "victim1", "victim2")},
            "by_shard": checker.by_shard(),
            "injected": injector.counts(),
        }
    finally:
        await platform.stop()
        await gw.close()
        for be in backends:
            await be.kill()


@pytest.mark.chaos
class TestNoisyNeighborIsolation:
    def test_victims_hold_flat_while_only_the_flooder_sheds(self):
        async def main():
            baseline = await _drive_noisy_neighbor(noisy=False)
            flooded = await _drive_noisy_neighbor(noisy=True)

            # At rated, nobody sheds — the quota is a ceiling, not a tax.
            for t in ("noisy", "victim1", "victim2"):
                assert baseline["by_tenant"][t]["quota_shed"] == 0, (
                    t, baseline["by_tenant"][t])

            # THE acceptance bar: each victim's within-deadline goodput
            # holds within 15% of its own baseline despite the 10× flood
            # next door.
            for t in ("victim1", "victim2"):
                assert baseline["ok"][t] > 0
                ratio = flooded["ok"][t] / baseline["ok"][t]
                assert ratio >= 0.85, (
                    f"{t} goodput collapsed under the flood: "
                    f"{flooded['ok'][t]} vs baseline {baseline['ok'][t]} "
                    f"({ratio:.2f})")
                # Every rated victim request was admitted, none on the
                # tenant-quota path.
                assert flooded["by_tenant"][t]["accepted"] == VICTIM_REQUESTS
                assert flooded["by_tenant"][t]["quota_shed"] == 0
                assert flooded["edge_shed"][t] == 0
                # The victims' SLO error budget is intact.
                assert flooded["burn"][t] < 1.0, (t, flooded["burn"][t])

            # Only the noisy tenant shed, and it shed hard: the flood is
            # 10× a bucket that holds ~rated, so most of it bounced.
            noisy = flooded["by_tenant"]["noisy"]
            assert noisy["quota_shed"] > VICTIM_REQUESTS, noisy
            assert flooded["edge_shed"]["noisy"] == noisy["quota_shed"]
            # ...into its OWN error budget.
            assert flooded["burn"]["noisy"] > 1.0, flooded["burn"]
            # What the flood DID get admitted still completed — shedding
            # is the only penalty, accepted work is never dropped.
            assert flooded["ok"]["noisy"] >= 1

            # The fault noise was real in both runs.
            assert flooded["injected"].get("error", 0) > 0

            # Per-shard verdicts came from both shards, each clean.
            assert set(flooded["by_shard"]) == {0, 1}
            for shard, stats in flooded["by_shard"].items():
                assert stats["terminal"] == stats["accepted"], (shard, stats)
                assert stats["duplicates"] == 0, (shard, stats)

        run(main())
