"""Pallas kernel correctness vs plain-XLA oracles (interpreter mode on CPU;
the same code compiles to Mosaic on TPU)."""

import jax.numpy as jnp
import numpy as np
import pytest

from ai4e_tpu.ops.pallas import (
    class_histogram,
    fused_seg_postprocess,
    normalize_image,
    segmentation_argmax,
)


class TestSegArgmax:
    def test_matches_jnp_argmax(self):
        rng = np.random.default_rng(0)
        logits = jnp.asarray(rng.standard_normal((2, 64, 128, 4)), jnp.float32)
        got = segmentation_argmax(logits, tile_h=32)
        expected = jnp.argmax(logits, axis=-1).astype(jnp.uint8)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))

    def test_bfloat16_logits(self):
        rng = np.random.default_rng(1)
        logits = jnp.asarray(rng.standard_normal((1, 32, 128, 7)),
                             jnp.bfloat16)
        got = segmentation_argmax(logits, tile_h=32)
        expected = jnp.argmax(logits, axis=-1).astype(jnp.uint8)
        np.testing.assert_array_equal(np.asarray(got), np.asarray(expected))

    def test_rejects_bad_tiling(self):
        with pytest.raises(ValueError):
            segmentation_argmax(jnp.zeros((1, 100, 128, 4)), tile_h=64)

    def test_full_postprocess_counts(self):
        rng = np.random.default_rng(2)
        logits = jnp.asarray(rng.standard_normal((2, 64, 128, 4)), jnp.float32)
        out = fused_seg_postprocess(logits)
        assert out["classmap"].shape == (2, 64, 128)
        assert out["counts"].shape == (2, 4)
        assert np.asarray(out["counts"]).sum() == 2 * 64 * 128

    def test_postprocess_counts_only(self):
        """with_classmap=False keeps the map on-device: counts must still
        match the full variant's, and the map key must be absent (nothing
        for run_batch's device_get to fetch)."""
        rng = np.random.default_rng(4)
        logits = jnp.asarray(rng.standard_normal((2, 64, 128, 4)), jnp.float32)
        full = fused_seg_postprocess(logits)
        slim = fused_seg_postprocess(logits, with_classmap=False)
        assert set(slim) == {"counts"}
        np.testing.assert_array_equal(np.asarray(slim["counts"]),
                                      np.asarray(full["counts"]))

    def test_unet_family_classmap_png_roundtrip(self):
        """return_classmap=True responses carry the classified tile as a
        lossless PNG whose pixels reproduce the histogram (the reference's
        land-cover APIs return classified tiles, not just statistics)."""
        import base64
        import io

        from PIL import Image

        from ai4e_tpu.runtime import build_servable

        servable = build_servable("unet", name="lc-png", tile=32,
                                  widths=[8, 16], buckets=(2,),
                                  return_classmap=True)
        batch = np.random.default_rng(5).integers(
            0, 256, (2, 32, 32, 3), np.uint8)
        out = servable.apply_fn(servable.params, jnp.asarray(batch))
        result = servable.postprocess(
            {k: np.asarray(v)[0] for k, v in out.items()})
        png = base64.b64decode(result["classmap_png"])
        decoded = np.asarray(Image.open(io.BytesIO(png)))
        assert decoded.shape == (32, 32)
        values, counts = np.unique(decoded, return_counts=True)
        assert {int(v): int(c) for v, c in zip(values, counts)} == \
            result["class_histogram"]

    def test_unet_family_default_keeps_map_on_device(self):
        from ai4e_tpu.runtime import build_servable

        servable = build_servable("unet", name="lc-slim", tile=32,
                                  widths=[8, 16], buckets=(2,))
        batch = np.zeros((2, 32, 32, 3), np.uint8)
        out = servable.apply_fn(servable.params, jnp.asarray(batch))
        assert set(out) == {"counts"}
        result = servable.postprocess(
            {k: np.asarray(v)[0] for k, v in out.items()})
        assert "classmap_png" not in result
        assert sum(result["class_histogram"].values()) == 32 * 32


class TestClassHistogram:
    def test_counts(self):
        cm = jnp.asarray([[[0, 1], [1, 3]]], jnp.uint8)
        counts = class_histogram(cm, 4)
        np.testing.assert_array_equal(np.asarray(counts), [[1, 2, 0, 1]])


class TestNormalizeImage:
    def test_matches_numpy(self):
        rng = np.random.default_rng(3)
        img = rng.integers(0, 256, (2, 64, 128, 3), np.uint8)
        mean = [0.485, 0.456, 0.406]
        std = [0.229, 0.224, 0.225]
        got = normalize_image(jnp.asarray(img), mean, std, tile_h=32)
        expected = (img.astype(np.float32) / 255.0 - mean) / std
        np.testing.assert_allclose(np.asarray(got), expected, rtol=1e-5,
                                   atol=1e-6)

    def test_default_identity_normalization(self):
        img = np.full((1, 32, 128, 3), 255, np.uint8)
        got = normalize_image(jnp.asarray(img))
        np.testing.assert_allclose(np.asarray(got), 1.0, rtol=1e-6)

    def test_rejects_float_input(self):
        with pytest.raises(ValueError):
            normalize_image(jnp.zeros((1, 32, 128, 3), jnp.float32))


class TestFlashAttention:
    def _qkv(self, b=2, h=3, s=256, d=32, seed=0):
        import numpy as _np
        rng = _np.random.default_rng(seed)
        mk = lambda: rng.standard_normal((b, h, s, d)).astype(_np.float32)
        return mk(), mk(), mk()

    def test_matches_reference(self):
        import numpy as _np

        from ai4e_tpu.ops.pallas import flash_attention
        from ai4e_tpu.parallel.ring_attention import reference_attention

        q, k, v = self._qkv()
        got = flash_attention(q, k, v, block_q=64, block_k=64)
        expected = reference_attention(q, k, v)
        _np.testing.assert_allclose(_np.asarray(got), _np.asarray(expected),
                                    rtol=2e-4, atol=2e-5)

    def test_causal_matches_reference(self):
        import numpy as _np

        from ai4e_tpu.ops.pallas import flash_attention
        from ai4e_tpu.parallel.ring_attention import reference_attention

        q, k, v = self._qkv(seed=1)
        got = flash_attention(q, k, v, causal=True, block_q=64, block_k=64)
        expected = reference_attention(q, k, v, causal=True)
        _np.testing.assert_allclose(_np.asarray(got), _np.asarray(expected),
                                    rtol=2e-4, atol=2e-5)

    def test_cross_attention_shapes(self):
        # S_q != S_k (non-causal): decoder-style cross attention.
        import numpy as _np

        from ai4e_tpu.ops.pallas import flash_attention
        from ai4e_tpu.parallel.ring_attention import reference_attention

        rng = _np.random.default_rng(2)
        q = rng.standard_normal((1, 2, 64, 16)).astype(_np.float32)
        k = rng.standard_normal((1, 2, 192, 16)).astype(_np.float32)
        v = rng.standard_normal((1, 2, 192, 16)).astype(_np.float32)
        got = flash_attention(q, k, v, block_q=32, block_k=64)
        _np.testing.assert_allclose(
            _np.asarray(got), _np.asarray(reference_attention(q, k, v)),
            rtol=2e-4, atol=2e-5)

    def test_gradients_match_reference(self):
        # The custom_vjp's pallas backward (FlashAttention-2 recurrence:
        # P recomputed from the saved logsumexp) must match autodiff
        # through the materialized reference — both causal and not, and
        # with uneven block counts so the accumulator carry is exercised.
        import jax as _jax
        import jax.numpy as _jnp
        import numpy as _np

        from ai4e_tpu.ops.pallas import flash_attention
        from ai4e_tpu.parallel.ring_attention import reference_attention

        q, k, v = self._qkv(b=1, h=2, s=256, d=32, seed=4)
        for causal in (False, True):
            def loss_f(q, k, v, _c=causal):
                return _jnp.sum(_jnp.sin(flash_attention(
                    q, k, v, causal=_c, block_q=64, block_k=128)))

            def loss_r(q, k, v, _c=causal):
                return _jnp.sum(_jnp.sin(reference_attention(
                    q, k, v, causal=_c)))

            gf = _jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
            gr = _jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
            for name, a, b in zip("qkv", gf, gr):
                _np.testing.assert_allclose(
                    _np.asarray(a), _np.asarray(b), rtol=2e-3, atol=2e-4,
                    err_msg=f"d{name} causal={causal}")

    def test_seqformer_trains_with_flash_attention(self):
        # The training plane now matches the serving plane: a seqformer
        # built with the flash strategy optimizes end to end (loss drops),
        # with no S×S score matrix in either pass.
        import jax as _jax
        import numpy as _np

        from ai4e_tpu.models import create_seqformer
        from ai4e_tpu.parallel import MeshSpec, make_mesh
        from ai4e_tpu.train import Trainer, cross_entropy_loss

        model, params = create_seqformer(
            seq_len=256, input_dim=16, dim=32, depth=1, heads=2,
            num_classes=4, attention="flash")
        mesh = make_mesh(MeshSpec(), devices=_jax.devices()[:1])
        tr = Trainer(model.apply, params, mesh, loss_fn=cross_entropy_loss)
        rng = _np.random.default_rng(5)
        x = rng.standard_normal((8, 256, 16)).astype(_np.float32)
        y = (rng.integers(0, 4, 8)).astype(_np.int32)
        first = float(tr.train_step(x, y))
        for _ in range(12):
            last = float(tr.train_step(x, y))
        assert last < first * 0.85, (first, last)

    def test_seqformer_flash_strategy_matches_full(self):
        import numpy as _np

        from ai4e_tpu.models import create_seqformer

        model_flash, params = create_seqformer(
            seq_len=256, input_dim=16, dim=32, depth=1, heads=4,
            num_classes=8, attention="flash")
        model_full, _ = create_seqformer(
            seq_len=256, input_dim=16, dim=32, depth=1, heads=4,
            num_classes=8, attention="full")
        x = _np.random.default_rng(3).standard_normal(
            (2, 256, 16)).astype(_np.float32)
        _np.testing.assert_allclose(
            _np.asarray(model_flash.apply(params, x)),
            _np.asarray(model_full.apply(params, x)), rtol=2e-2, atol=2e-2)


class TestValidationHarness:
    def test_validate_kernels_in_interpreter(self):
        """The on-device validation harness (ops/pallas/validate.py — run by
        bench.py on real TPU) must itself be correct: same checks under the
        pallas interpreter pass, and the VMEM accounting stays in budget."""
        from ai4e_tpu.ops.pallas.validate import (
            VMEM_BUDGET_BYTES,
            flash_attention_vmem_bytes,
            validate_kernels,
        )

        results = validate_kernels(interpret=True)
        assert results["all_ok"], results
        for name in ("flash_attention", "segmentation_argmax",
                     "normalize_image"):
            assert results[name]["vmem_bytes"] <= VMEM_BUDGET_BYTES
        # The flash kernel's footprint depends only on block sizes and head
        # dim — never sequence length (the k-axis is a grid axis) — so even
        # the largest serving config (d=128) fits comfortably.
        assert flash_attention_vmem_bytes(128, 128, 128) <= VMEM_BUDGET_BYTES
