"""Resilient-routing tests (``ai4e_tpu/resilience/``, docs/resilience.md):
the per-backend circuit breaker state machine under an injected clock;
health-aware weighted picks ejecting open backends (and the all-open
least-recently-failed last resort); retry budgets and jittered backoff;
the dispatcher's in-delivery retry/failover + 5xx-as-transient
redelivery + duplicate suppression; the gateway sync proxy failing over
on connection error instead of answering 502; and ``resilience=False``
leaving every pre-resilience behavior untouched."""

import asyncio
import random

from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.metrics import MetricsRegistry
from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
from ai4e_tpu.resilience import (BackendHealth, CircuitBreaker,
                                 ResiliencePolicy, RetryBudget, backoff_s)


def run(coro):
    return asyncio.run(coro)


async def serve(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


class FakeClock:
    def __init__(self, t: float = 0.0):
        self.t = t

    def __call__(self) -> float:
        return self.t


# ---------------------------------------------------------------------------
# Breaker state machine
# ---------------------------------------------------------------------------

class TestCircuitBreaker:
    def test_opens_on_consecutive_failures(self):
        clock = FakeClock()
        br = CircuitBreaker(failure_threshold=3, clock=clock)
        assert br.state == "closed"
        assert not br.record_failure()
        assert not br.record_failure()
        assert br.record_failure()  # third consecutive: trips NOW
        assert br.state == "open"
        assert not br.available()

    def test_success_resets_the_consecutive_run(self):
        br = CircuitBreaker(failure_threshold=3, clock=FakeClock())
        br.record_failure()
        br.record_failure()
        br.record_success()
        assert not br.record_failure()
        assert br.state == "closed"

    def test_opens_on_window_error_rate(self):
        # A flapping backend that never fails thrice in a row but fails
        # half its window still trips.
        br = CircuitBreaker(failure_threshold=10, window=6, error_rate=0.5,
                            clock=FakeClock())
        for _ in range(10):
            if br.record_failure() or br.record_failure():
                break
            br.record_success()
        assert br.state == "open"

    def test_half_open_probe_success_closes(self):
        clock = FakeClock()
        br = CircuitBreaker(failure_threshold=1, recovery_seconds=10.0,
                            clock=clock)
        br.record_failure()
        assert br.state == "open" and not br.available()
        clock.t = 11.0  # cooldown elapsed
        assert br.available()
        br.begin_probe()
        assert br.state == "half_open"
        # The single probe slot is taken: no stampede on the recovering pod.
        assert not br.available()
        br.record_success()
        assert br.state == "closed" and br.available()

    def test_stale_success_does_not_cancel_an_open_cooldown(self):
        # Concurrent delivery loops: a request dispatched BEFORE the trip
        # completing 200 after it must not re-admit the flapping backend
        # (review finding: one straggler success per trip would defeat
        # ejection entirely).
        clock = FakeClock()
        br = CircuitBreaker(failure_threshold=2, recovery_seconds=10.0,
                            clock=clock)
        br.record_failure()
        br.record_failure()
        assert br.state == "open"
        br.record_success()  # straggler from before the trip
        assert br.state == "open"
        assert not br.available()

    def test_backpressured_probe_releases_the_slot(self):
        # A half-open probe answered 429/503 (alive but saturated) is
        # neutral for open/close — but it RESOLVES the probe, or one
        # 503'd probe would pin the slot and eject the backend forever
        # (review finding).
        clock = FakeClock()
        br = CircuitBreaker(failure_threshold=1, recovery_seconds=10.0,
                            clock=clock)
        br.record_failure()
        clock.t = 11.0
        br.begin_probe()
        assert not br.available()  # slot taken
        br.record_neutral()        # probe drew a 503
        assert br.state == "half_open"
        assert br.available()      # slot free: the next probe can go

    def test_stale_failures_do_not_extend_an_open_cooldown(self):
        # Staggered timeouts on concurrent loops dribble in for the whole
        # request_timeout after the trip; refreshing the anchor on each
        # would eject a hung-then-restarted backend for minutes instead of
        # recovery_seconds (review finding).
        clock = FakeClock()
        br = CircuitBreaker(failure_threshold=1, recovery_seconds=10.0,
                            clock=clock)
        br.record_failure()          # trips at t=0
        clock.t = 9.0
        br.record_failure()          # straggler while open
        clock.t = 10.5               # recovery_seconds from the TRIP
        assert br.available()

    def test_leaked_probe_slot_escapes_after_a_cooldown(self):
        # A probe cancelled before any outcome (dispatcher stop mid-POST,
        # client disconnect) never records success/failure/neutral; the
        # slot must re-open by time, not stay pinned forever (review
        # finding: permanent ejection in a multi-backend set).
        clock = FakeClock()
        br = CircuitBreaker(failure_threshold=1, recovery_seconds=10.0,
                            clock=clock)
        br.record_failure()
        clock.t = 11.0
        br.begin_probe()             # probe vanishes without an outcome
        assert not br.available()
        clock.t = 22.0               # one cooldown of silence
        assert br.available()

    def test_stale_success_without_inflight_probe_does_not_close(self):
        clock = FakeClock()
        br = CircuitBreaker(failure_threshold=1, recovery_seconds=10.0,
                            clock=clock)
        br.record_failure()
        clock.t = 11.0
        br.begin_probe()
        br.record_neutral()          # probe resolved 503: slot freed
        br.record_success()          # straggler from before the trip
        assert br.state == "half_open"  # only a real probe's 200 closes

    def test_half_open_probe_failure_reopens_with_fresh_cooldown(self):
        clock = FakeClock()
        br = CircuitBreaker(failure_threshold=1, recovery_seconds=10.0,
                            clock=clock)
        br.record_failure()
        clock.t = 11.0
        br.begin_probe()
        assert br.record_failure()  # probe failed → open again (an event)
        assert br.state == "open"
        clock.t = 20.0  # cooldown restarts at the probe failure (t=11)
        assert not br.available()
        clock.t = 21.5
        assert br.available()


# ---------------------------------------------------------------------------
# Retry budget + backoff
# ---------------------------------------------------------------------------

class TestRetry:
    def test_backoff_doubles_jitters_and_caps(self):
        rng = random.Random(7)
        for attempt, ceiling in ((1, 0.1), (2, 0.2), (3, 0.4), (9, 1.0)):
            d = backoff_s(attempt, base=0.1, cap=1.0, rng=rng)
            assert ceiling / 2 <= d <= ceiling
        assert backoff_s(1, base=0.0, cap=1.0) == 0.0
        # Unbounded attempt counts (broker patience is 1440 deliveries)
        # must stay at the cap, not overflow float and skip the backoff.
        huge = backoff_s(1440, base=60.0, cap=150.0, rng=rng)
        assert 75.0 <= huge <= 150.0

    def test_budget_limits_retries_to_a_fraction_of_requests(self):
        budget = RetryBudget(ratio=0.2, reserve=2.0)
        # Reserve spends first...
        assert budget.try_retry() and budget.try_retry()
        assert not budget.try_retry()
        # ...then retries track ~ratio of ordinary requests.
        for _ in range(10):
            budget.on_request()
        assert budget.try_retry()
        assert not budget.try_retry()


# ---------------------------------------------------------------------------
# Health-aware pick (ejection / redistribution / last resort)
# ---------------------------------------------------------------------------

def _health(clock=None, **policy):
    return BackendHealth(policy=ResiliencePolicy(**policy),
                         metrics=MetricsRegistry(),
                         clock=clock or FakeClock(),
                         rng=random.Random(3))


class TestBackendHealth:
    BACKENDS = [("http://a:1/v1/x", 1.0), ("http://b:1/v1/x", 1.0)]

    def test_open_backend_is_ejected_and_weight_redistributes(self):
        h = _health(failure_threshold=1)
        h.record_failure("http://a:1/v1/x")
        picks = {h.pick(self.BACKENDS) for _ in range(20)}
        assert picks == {"http://b:1/v1/x"}
        ej = h.metrics.counter("ai4e_resilience_ejections_total", "")
        assert ej.value(backend="a:1") == 20

    def test_all_open_probes_least_recently_failed(self):
        clock = FakeClock()
        h = _health(clock=clock, failure_threshold=1,
                    recovery_seconds=1000.0)
        clock.t = 1.0
        h.record_failure("http://a:1/v1/x")
        clock.t = 2.0
        h.record_failure("http://b:1/v1/x")
        # Both dark, neither cooled down: probe the one that failed FIRST.
        assert h.pick(self.BACKENDS) == "http://a:1/v1/x"
        # A successful forced probe closes the breaker — the dark set
        # found its way back without any operator.
        h.record_success("http://a:1/v1/x")
        assert h.state("http://a:1/v1/x") == "closed"

    def test_exclude_reaches_a_different_backend(self):
        h = _health()
        for _ in range(10):
            assert h.pick(self.BACKENDS,
                          exclude=["http://a:1/v1/x"]) == "http://b:1/v1/x"
        # Excluding everything falls back to the full set, never empties.
        assert h.pick(self.BACKENDS,
                      exclude=[u for u, _ in self.BACKENDS]) in {
                          u for u, _ in self.BACKENDS}

    def test_observe_status_classifies(self):
        h = _health(failure_threshold=1)
        uri = "http://a:1/v1/x"
        assert not h.observe_status(uri, 503)  # saturation: alive, no trip
        assert h.state(uri) == "closed"
        assert h.observe_status(uri, 500)
        assert h.state(uri) == "open"
        h2 = _health(failure_threshold=1)
        assert not h2.observe_status(uri, 404)  # 4xx: request's fault
        assert h2.state(uri) == "closed"

    def test_breaker_open_transition_counted_once(self):
        h = _health(failure_threshold=2)
        uri = "http://a:1/v1/x"
        assert not h.record_failure(uri)
        assert h.record_failure(uri)
        assert not h.record_failure(uri)  # already open: no second event
        tr = h.metrics.counter("ai4e_resilience_transitions_total", "")
        assert tr.value(backend="a:1", state="open") == 1


# ---------------------------------------------------------------------------
# Dispatcher: failover, 5xx retry, duplicate suppression, redelivery backoff
# ---------------------------------------------------------------------------

def _resilient_platform(**kw):
    cfg = dict(resilience=True, retry_delay=0.01,
               resilience_retry_base_s=0.001,
               resilience_recovery_seconds=0.05)
    cfg.update(kw)
    return LocalPlatform(PlatformConfig(**cfg), metrics=MetricsRegistry())


def _completing_app(platform, calls, fail_first=0, status=500):
    """Backend app that records hits and completes the task — after
    answering ``status`` to the first ``fail_first`` POSTs."""
    async def handler(request):
        calls.append(request.headers["taskId"])
        if len(calls) <= fail_first:
            return web.Response(status=status)
        # Conditional completion (update_status_if): the idempotent
        # completion pattern docs/resilience.md prescribes for
        # at-least-once transports.
        platform.store.update_status_if(
            request.headers["taskId"], "created", "completed", "completed")
        return web.Response(text="ok")

    app = web.Application()
    app.router.add_post("/v1/be/x", handler)
    return app


async def _post_and_wait(platform, gw, path="/v1/pub/x", timeout=5.0):
    resp = await gw.post(path, data=b"payload")
    assert resp.status == 200
    tid = (await resp.json())["TaskId"]
    end = asyncio.get_running_loop().time() + timeout
    from ai4e_tpu.taskstore import TaskStatus
    while asyncio.get_running_loop().time() < end:
        record = platform.store.get(tid)
        if record.canonical_status in TaskStatus.TERMINAL:
            return tid, record
        await asyncio.sleep(0.01)
    return tid, platform.store.get(tid)


class TestDispatcherResilience:
    def test_connection_error_fails_over_to_live_backend(self):
        async def main():
            platform = _resilient_platform()
            calls = []
            be = await serve(_completing_app(platform, calls))
            live = str(be.make_url("/v1/be/x"))
            dead = "http://127.0.0.1:9/v1/be/x"
            platform.publish_async_api("/v1/pub/x", [(dead, 1.0), (live, 1.0)])
            gw = await serve(platform.gateway.app)
            await platform.start()
            try:
                for _ in range(6):
                    _, record = await _post_and_wait(platform, gw)
                    assert record.canonical_status == "completed", record
                failovers = platform.metrics.counter(
                    "ai4e_resilience_failovers_total", "")
                ejections = platform.metrics.counter(
                    "ai4e_resilience_ejections_total", "")
                # The dead host either cost an in-delivery failover or —
                # once its breaker opened — was ejected from the pick.
                assert (failovers.value(component="dispatcher")
                        + ejections.value(backend="127.0.0.1:9")) > 0
            finally:
                await platform.stop()
                await gw.close()
                await be.close()

        run(main())

    def test_transient_500_is_retried_not_terminal(self):
        async def main():
            platform = _resilient_platform()
            calls = []
            be = await serve(_completing_app(platform, calls, fail_first=1))
            platform.publish_async_api("/v1/pub/x",
                                       str(be.make_url("/v1/be/x")))
            gw = await serve(platform.gateway.app)
            await platform.start()
            try:
                _, record = await _post_and_wait(platform, gw)
                assert record.canonical_status == "completed", record
                assert len(calls) >= 2  # the 500 was retried
                retries = platform.metrics.counter(
                    "ai4e_resilience_retries_total", "")
                assert retries.value(component="dispatcher") >= 1
            finally:
                await platform.stop()
                await gw.close()
                await be.close()

        run(main())

    def test_500_without_resilience_stays_permanent(self):
        async def main():
            platform = LocalPlatform(PlatformConfig(retry_delay=0.01),
                                     metrics=MetricsRegistry())
            calls = []
            be = await serve(_completing_app(platform, calls, fail_first=99))
            platform.publish_async_api("/v1/pub/x",
                                       str(be.make_url("/v1/be/x")))
            gw = await serve(platform.gateway.app)
            await platform.start()
            try:
                _, record = await _post_and_wait(platform, gw)
                assert record.canonical_status == "failed", record
                assert len(calls) == 1  # single attempt, byte-identical
            finally:
                await platform.stop()
                await gw.close()
                await be.close()

        run(main())

    def test_duplicate_message_for_terminal_task_is_suppressed(self):
        async def main():
            platform = _resilient_platform()
            calls = []
            be = await serve(_completing_app(platform, calls))
            platform.publish_async_api("/v1/pub/x",
                                       str(be.make_url("/v1/be/x")))
            gw = await serve(platform.gateway.app)
            await platform.start()
            try:
                tid, record = await _post_and_wait(platform, gw)
                assert record.canonical_status == "completed"
                executed = len(calls)
                # Duplicate publish (the lease-expiry hazard): the message
                # must complete off the broker without re-POSTing.
                platform.broker.publish(platform.store.get(tid))
                await asyncio.sleep(0.1)
                assert len(calls) == executed
                dup = platform.metrics.counter("ai4e_dispatch_total", "")
                assert dup.value(outcome="duplicate", queue="/v1/be/x",
                                 backend="") == 1
            finally:
                await platform.stop()
                await gw.close()
                await be.close()

        run(main())

    def test_redelivery_delay_is_jittered_exponential_capped_by_lease(self):
        from ai4e_tpu.broker import InMemoryBroker
        from ai4e_tpu.broker.dispatcher import Dispatcher
        from ai4e_tpu.broker.queue import Message
        from ai4e_tpu.service import LocalTaskManager
        from ai4e_tpu.taskstore import InMemoryTaskStore

        broker = InMemoryBroker(lease_seconds=10.0)
        d = Dispatcher(broker, "/v1/q", "http://b/v1/q",
                       LocalTaskManager(InMemoryTaskStore()),
                       retry_delay=1.0, metrics=MetricsRegistry(),
                       rng=random.Random(0))
        by_count = {}
        for count in (1, 2, 3, 4, 10):
            delays = [d._redelivery_delay(
                Message(task_id="t", endpoint="/v1/q",
                        delivery_count=count)) for _ in range(50)]
            # Jitter band [d/2, d]; cap = lease/2 = 5 s — a retry can
            # never outlive its own lease.
            ceiling = min(5.0, 1.0 * 2 ** (count - 1))
            assert all(ceiling / 2 <= x <= ceiling for x in delays), (
                count, min(delays), max(delays))
            by_count[count] = sum(delays) / len(delays)
        assert by_count[1] < by_count[2] < by_count[3]
        assert by_count[10] <= 5.0

    def test_breaker_open_backs_off_admission_limiter(self):
        # Breaker outcomes feed the admission limiter's backoff signal:
        # an opened breaker shrinks the queue's fan-out immediately.
        async def main():
            platform = _resilient_platform(
                admission=True, resilience_failure_threshold=2,
                admission_initial_limit=64)
            platform.publish_async_api("/v1/pub/x",
                                       "http://127.0.0.1:9/v1/be/x")
            gw = await serve(platform.gateway.app)
            await platform.start()
            scope = platform.admission.scope("dispatch:/v1/be/x")
            before = scope.limit
            try:
                resp = await gw.post("/v1/pub/x", data=b"p")
                assert resp.status == 200
                for _ in range(200):
                    if scope.limit < before:
                        break
                    await asyncio.sleep(0.01)
                assert scope.limit < before
            finally:
                await platform.stop()
                await gw.close()

        run(main())


# ---------------------------------------------------------------------------
# Gateway sync proxy: failover on connection error
# ---------------------------------------------------------------------------

class TestGatewaySyncResilience:
    def test_sync_proxy_fails_over_instead_of_502(self):
        async def main():
            platform = _resilient_platform()
            hits = []

            async def ok(request):
                hits.append(1)
                return web.Response(text="pong")

            app = web.Application()
            app.router.add_post("/v1/be/x", ok)
            be = await serve(app)
            live = str(be.make_url("/v1/be/x"))
            dead = "http://127.0.0.1:9/v1/be/x"
            platform.publish_sync_api("/v1/pub/x", [(dead, 1.0), (live, 1.0)])
            gw = await serve(platform.gateway.app)
            try:
                for _ in range(8):
                    resp = await gw.post("/v1/pub/x", data=b"ping")
                    assert resp.status == 200, await resp.text()
                assert len(hits) == 8
            finally:
                await gw.close()
                await be.close()

        run(main())

    def test_sync_proxy_all_dead_still_answers_502(self):
        async def main():
            platform = _resilient_platform()
            platform.publish_sync_api("/v1/pub/x",
                                      "http://127.0.0.1:9/v1/be/x")
            gw = await serve(platform.gateway.app)
            try:
                resp = await gw.post("/v1/pub/x", data=b"ping")
                assert resp.status == 502
            finally:
                await gw.close()

        run(main())

    def test_sync_proxy_single_attempt_without_resilience(self):
        async def main():
            platform = LocalPlatform(PlatformConfig(),
                                     metrics=MetricsRegistry())
            platform.publish_sync_api("/v1/pub/x",
                                      "http://127.0.0.1:9/v1/be/x")
            gw = await serve(platform.gateway.app)
            try:
                resp = await gw.post("/v1/pub/x", data=b"ping")
                assert resp.status == 502  # unchanged pre-resilience answer
            finally:
                await gw.close()

        run(main())


# ---------------------------------------------------------------------------
# Config surface
# ---------------------------------------------------------------------------

class TestConfigSurface:
    def test_env_knobs_reach_the_policy(self):
        from ai4e_tpu.config import PlatformSection
        sec = PlatformSection.from_env(env={
            "AI4E_PLATFORM_RESILIENCE": "1",
            "AI4E_PLATFORM_RESILIENCE_FAILURE_THRESHOLD": "9",
            "AI4E_PLATFORM_RESILIENCE_RECOVERY_SECONDS": "2.5",
        })
        cfg = sec.to_platform_config()
        assert cfg.resilience is True
        platform = LocalPlatform(cfg, metrics=MetricsRegistry())
        assert platform.resilience.policy.failure_threshold == 9
        assert platform.resilience.policy.recovery_seconds == 2.5

    def test_default_platform_has_no_resilience_state(self):
        platform = LocalPlatform(PlatformConfig(), metrics=MetricsRegistry())
        assert platform.resilience is None
        assert platform.gateway._resilience is None
        d = platform.dispatchers
        assert d.resilience is None
