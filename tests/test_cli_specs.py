"""Deploy-spec validation: the shipped specs in deploy/specs/ must actually
assemble against the CLI builders, and every family factory must produce a
well-formed servable (cheap configs — no big model init here)."""

import json
import os

import numpy as np

from ai4e_tpu.cli import build_control_plane
from ai4e_tpu.config import FrameworkConfig
from ai4e_tpu.runtime import FAMILIES, build_servable

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPECS = os.path.join(REPO, "deploy", "specs")


class TestDeploySpecs:
    def test_routes_spec_assembles_control_plane(self):
        with open(os.path.join(SPECS, "routes.json")) as f:
            routes = json.load(f)
        config = FrameworkConfig()
        config.platform.retry_delay = 0.1
        platform = build_control_plane(config, routes)
        # Every async API got a dispatcher + queue; autoscale specs attached.
        async_apis = [a for a in routes["apis"] if a.get("mode") != "sync"]
        assert len(platform.dispatchers.dispatchers) == len(async_apis)
        with_scaler = [a for a in async_apis if a.get("autoscale")]
        assert len(platform.autoscalers) == len(with_scaler)
        # Task-store HTTP surface rides the gateway app.
        paths = {r.resource.canonical for r in platform.gateway.app.router.routes()}
        assert "/v1/taskstore/upsert" in paths
        assert "/v1/taskstore/result" in paths

    def test_models_spec_families_are_known(self):
        with open(os.path.join(SPECS, "models.json")) as f:
            models = json.load(f)
        for spec in models["models"]:
            assert spec["family"] in FAMILIES, spec

    def test_every_family_builds_and_runs_tiny(self):
        tiny = {
            "echo": dict(size=8, buckets=(2,)),
            "unet": dict(tile=16, widths=(8, 16), buckets=(2,),
                         fused_postprocess=False),
            "resnet": dict(image_size=16, stage_sizes=(1,), width=8,
                           num_classes=4, buckets=(2,)),
            "detector": dict(image_size=32, widths=(8, 8, 8),
                             max_detections=4, buckets=(2,)),
            "vit": dict(image_size=16, patch=8, dim=16, depth=1, heads=2,
                        num_classes=4, buckets=(2,)),
        }
        for family, kwargs in tiny.items():
            servable = build_servable(family, name=f"t-{family}", **kwargs)
            batch = np.zeros((2, *servable.input_shape),
                             servable.input_dtype)
            out = servable.apply_fn(servable.params, batch)
            assert out is not None, family


class TestCheckpointLoading:
    def test_worker_spec_restores_checkpoint_weights(self, tmp_path):
        """A model spec's "checkpoint" restores saved params at worker build
        (SURVEY.md §5 serving-checkpoint slot): the echo servable's scale
        comes from the checkpoint, not the family default."""
        from ai4e_tpu.checkpoint import save_params
        from ai4e_tpu.cli import build_worker

        ckpt = str(tmp_path / "echo-ckpt")
        save_params(ckpt, {"scale": np.float32(3.0)})

        config = FrameworkConfig()
        worker, batcher, _tm = build_worker(config, {
            "service_name": "w", "prefix": "v1/echo",
            "models": [{"family": "echo", "name": "echo", "size": 4,
                        "buckets": [2], "checkpoint": ckpt}]})
        servable = worker.runtime.models["echo"]
        assert float(np.asarray(servable.params["scale"])) == 3.0
        bucket = servable.bucket_for(2)  # buckets round up to mesh multiples
        out = worker.runtime.run_batch(
            "echo", np.ones((bucket, 4), np.float32))
        np.testing.assert_allclose(np.asarray(out),
                                   3.0 * np.ones((bucket, 4)))
