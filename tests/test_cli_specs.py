"""Deploy-spec validation: the shipped specs in deploy/specs/ must actually
assemble against the CLI builders, and every family factory must produce a
well-formed servable (cheap configs — no big model init here)."""

import json
import os

import numpy as np

from ai4e_tpu.cli import build_control_plane
from ai4e_tpu.config import FrameworkConfig
from ai4e_tpu.runtime import FAMILIES, build_servable

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SPECS = os.path.join(REPO, "deploy", "specs")


class TestDeploySpecs:
    def test_routes_spec_assembles_control_plane(self):
        with open(os.path.join(SPECS, "routes.json")) as f:
            routes = json.load(f)
        config = FrameworkConfig()
        config.platform.retry_delay = 0.1
        platform = build_control_plane(config, routes)
        # Every async API got a dispatcher + queue; autoscale specs attached.
        async_apis = [a for a in routes["apis"] if a.get("mode") != "sync"]
        assert len(platform.dispatchers.dispatchers) == len(async_apis)
        with_scaler = [a for a in async_apis if a.get("autoscale")]
        assert len(platform.autoscalers) == len(with_scaler)
        # Task-store HTTP surface rides the gateway app.
        paths = {r.resource.canonical for r in platform.gateway.app.router.routes()}
        assert "/v1/taskstore/upsert" in paths
        assert "/v1/taskstore/result" in paths

    def test_models_spec_families_are_known(self):
        with open(os.path.join(SPECS, "models.json")) as f:
            models = json.load(f)
        for spec in models["models"]:
            assert spec["family"] in FAMILIES, spec

    def test_every_family_builds_and_runs_tiny(self):
        tiny = {
            "echo": dict(size=8, buckets=(2,)),
            "unet": dict(tile=16, widths=(8, 16), buckets=(2,),
                         fused_postprocess=False),
            "resnet": dict(image_size=16, stage_sizes=(1,), width=8,
                           num_classes=4, buckets=(2,)),
            "detector": dict(image_size=32, widths=(8, 8, 8),
                             max_detections=4, buckets=(2,)),
            "vit": dict(image_size=16, patch=8, dim=16, depth=1, heads=2,
                        num_classes=4, buckets=(2,)),
        }
        for family, kwargs in tiny.items():
            servable = build_servable(family, name=f"t-{family}", **kwargs)
            batch = np.zeros((2, *servable.input_shape),
                             servable.input_dtype)
            out = servable.apply_fn(servable.params, batch)
            assert out is not None, family


class TestCheckpointLoading:
    def test_worker_spec_restores_checkpoint_weights(self, tmp_path):
        """A model spec's "checkpoint" restores saved params at worker build
        (SURVEY.md §5 serving-checkpoint slot): the echo servable's scale
        comes from the checkpoint, not the family default."""
        from ai4e_tpu.checkpoint import save_params
        from ai4e_tpu.cli import build_worker

        ckpt = str(tmp_path / "echo-ckpt")
        save_params(ckpt, {"scale": np.float32(3.0)})

        config = FrameworkConfig()
        worker, batcher, _tm = build_worker(config, {
            "service_name": "w", "prefix": "v1/echo",
            "models": [{"family": "echo", "name": "echo", "size": 4,
                        "buckets": [2], "checkpoint": ckpt}]})
        servable = worker.runtime.models["echo"]
        assert float(np.asarray(servable.params["scale"])) == 3.0
        bucket = servable.bucket_for(2)  # buckets round up to mesh multiples
        out = worker.runtime.run_batch(
            "echo", np.ones((bucket, 4), np.float32))
        np.testing.assert_allclose(np.asarray(out),
                                   3.0 * np.ones((bucket, 4)))


class TestDeclarativePipeline:
    def test_handoff_gating(self):
        from ai4e_tpu.cli import _declarative_handoff

        assert _declarative_handoff(None) is None
        h = _declarative_handoff({"endpoint": "/v1/next",
                                  "when_nonempty": "detections"})
        assert h({"detections": []}) is None
        assert h({"detections": [1]}) == ("/v1/next", b"")
        ungated = _declarative_handoff({"endpoint": "/v1/next"})
        assert ungated({"anything": 0}) == ("/v1/next", b"")

    def test_spec_driven_two_stage_pipeline_e2e(self):
        """models.json "pipeline_to" composes two servables of one worker
        into a composite API: stage 1 hands off under the same TaskId and
        stage 2 receives the ORIGINAL body via store replay."""
        import asyncio
        import io

        from aiohttp.test_utils import TestClient, TestServer

        from ai4e_tpu.cli import build_worker as cli_build_worker
        from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig

        async def main():
            platform = LocalPlatform(PlatformConfig(retry_delay=0.05))
            config = FrameworkConfig()
            worker, batcher, _tm = cli_build_worker(config, {
                "service_name": "combo", "prefix": "v1/combo",
                "models": [
                    {"family": "echo", "name": "stage1", "size": 4,
                     "buckets": [2], "async_path": "/stage1-async",
                     "pipeline_to": {"endpoint": "/v1/combo/stage2-async",
                                     "when_nonempty": "echo"}},
                    {"family": "echo", "name": "stage2", "size": 4,
                     "buckets": [2], "async_path": "/stage2-async"},
                ]})
            # Worker stands alone (own store); wire the platform's store in.
            worker.service.task_manager = platform.task_manager
            worker.store = platform.store
            await batcher.start()
            svc_client = await serve_app(worker.service.app)
            base = str(svc_client.make_url("")).rstrip("/")
            platform.publish_async_api(
                "/v1/public/combo", base + "/v1/combo/stage1-async")
            platform.dispatchers.register(
                "/v1/combo/stage2-async", base + "/v1/combo/stage2-async")
            gw = await serve_app(platform.gateway.app)
            await platform.start()
            try:
                buf = io.BytesIO()
                np.save(buf, np.ones(4, np.float32))
                resp = await gw.post("/v1/public/combo", data=buf.getvalue())
                tid = (await resp.json())["TaskId"]
                final = None
                for _ in range(400):
                    r = await gw.get(f"/v1/taskmanagement/task/{tid}")
                    final = await r.json()
                    if ("completed" in final["Status"]
                            or "failed" in final["Status"]):
                        break
                    await asyncio.sleep(0.02)
                assert "completed" in final["Status"], final
                # Stage-1's intermediate output is retrievable by stage name.
                staged = platform.store.get_result(tid, stage="stage1")
                assert staged is not None
            finally:
                await platform.stop()
                await batcher.stop()
                await gw.close()
                await svc_client.close()

        async def serve_app(app):
            client = TestClient(TestServer(app))
            await client.start_server()
            return client

        asyncio.run(main())


class TestCliRateLimitWiring:
    def test_rate_limit_env_installs_limiter(self):
        config = FrameworkConfig.from_env({
            "AI4E_GATEWAY_RATE_LIMIT_RPS": "10",
            "AI4E_GATEWAY_RATE_LIMITS": "vip=100:200",
        })
        platform = build_control_plane(config, {"apis": []})
        limiter = platform.gateway._rate_limiter
        assert limiter is not None
        assert limiter.default.rps == 10 and limiter.default.burst == 20
        assert limiter.per_key["vip"].rps == 100
        assert limiter.per_key["vip"].burst == 200

    def test_no_rate_limit_env_means_unlimited(self):
        platform = build_control_plane(FrameworkConfig(), {"apis": []})
        assert platform.gateway._rate_limiter is None
