"""Inference result cache + single-flight coalescing (``ai4e_tpu/rescache/``)
and the round-5 ADVICE regressions that ride this PR.

Covers the subsystem's acceptance surface end to end: canonical-key
stability across equivalent payloads, LRU/TTL/byte-budget eviction,
invalidation on checkpoint hot reload (a stale result can never outlive a
weight swap), and the coalescing guarantee — N concurrent identical requests
produce exactly ONE device execution (asserted via the runtime's batch-size
metric) while every client receives the correct result. Plus the dispatcher's
serve-a-redelivery-from-cache path, the gateway ``X-Cache`` header contract
(hit/miss/coalesced/bypass), and regressions for the five ADVICE findings.
"""

import asyncio
import importlib.util
import io
import json
import os
import time

import numpy as np
import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.metrics import MetricsRegistry
from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
from ai4e_tpu.rescache import (ResultCache, attach_store, canonical_payload,
                               family_of, request_key)
from ai4e_tpu.runtime import (InferenceWorker, MicroBatcher, ModelRuntime,
                              build_servable)
from ai4e_tpu.taskstore import (APITask, FollowerTaskStore, InMemoryTaskStore,
                                JournaledTaskStore, TaskStatus)
from ai4e_tpu.utils.backends import normalize_backends, pick_backend

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "ai4e_client_rescache", os.path.join(REPO, "clients", "python",
                                         "ai4e_client.py"))
ai4e_client = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(ai4e_client)


def run(coro):
    return asyncio.run(coro)


async def serve(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


async def poll_until(client, task_id, predicate, tries=400, delay=0.02):
    body = None
    for _ in range(tries):
        resp = await client.get(f"/v1/taskmanagement/task/{task_id}")
        body = await resp.json()
        if predicate(body):
            return body
        await asyncio.sleep(delay)
    return body


def npy_bytes(arr) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


# -- canonical request hashing -----------------------------------------------


class TestRequestKey:
    def test_json_equivalent_payloads_share_a_key(self):
        a = request_key("/v1/x", b'{"a": 1, "b": [2, 3]}', "application/json")
        b = request_key("/v1/x", b'{"b":[2,3],"a":1}',
                        "application/json; charset=utf-8")
        assert a == b

    def test_semantically_different_json_differs(self):
        a = request_key("/v1/x", b'{"a": 1}', "application/json")
        b = request_key("/v1/x", b'{"a": 2}', "application/json")
        assert a != b

    def test_binary_payloads_hash_raw(self):
        payload = npy_bytes(np.arange(4, dtype=np.float32))
        a = request_key("/v1/x", payload, "application/octet-stream")
        b = request_key("/v1/x", payload, "application/octet-stream")
        c = request_key("/v1/x", payload + b"\0", "application/octet-stream")
        assert a == b and a != c

    def test_every_dimension_is_significant(self):
        base = request_key("/v1/x", b"p", "application/octet-stream")
        assert request_key("/v1/y", b"p", "application/octet-stream") != base
        assert request_key("/v1/x", b"p", "image/jpeg") != base
        assert request_key("/v1/x", b"p", "application/octet-stream",
                           checkpoint="2") != base
        assert request_key("/v1/x", b"p", "application/octet-stream",
                           extra="op?conf=0.9") != base

    def test_family_recoverable_from_key(self):
        key = request_key("/v1/detect", b"p")
        assert family_of(key) == "/v1/detect"

    def test_invalid_json_falls_back_to_raw_bytes(self):
        broken = b'{"a": '
        assert canonical_payload(broken, "application/json") == broken


# -- eviction ----------------------------------------------------------------


class TestEviction:
    def test_lru_entry_budget(self):
        cache = ResultCache(max_entries=2, max_bytes=1 << 20,
                            metrics=MetricsRegistry())
        cache.put("f|a", b"1")
        cache.put("f|b", b"2")
        assert cache.get("f|a") is not None  # refresh a's recency
        cache.put("f|c", b"3")
        assert cache.peek("f|a") and cache.peek("f|c")
        assert not cache.peek("f|b")  # the LRU victim

    def test_byte_budget(self):
        cache = ResultCache(max_entries=100, max_bytes=10,
                            max_entry_bytes=10, metrics=MetricsRegistry())
        cache.put("f|a", b"12345")
        cache.put("f|b", b"12345")
        cache.put("f|c", b"12345")  # 15 bytes resident -> evict oldest
        assert not cache.peek("f|a")
        assert cache.peek("f|b") and cache.peek("f|c")
        assert cache.stats()["bytes"] == 10

    def test_oversized_entry_refused(self):
        cache = ResultCache(max_bytes=100, max_entry_bytes=4,
                            metrics=MetricsRegistry())
        assert cache.put("f|big", b"12345") is False
        assert not cache.peek("f|big")

    def test_ttl_expiry(self):
        now = [0.0]
        reg = MetricsRegistry()
        cache = ResultCache(ttl_s=10.0, metrics=reg, clock=lambda: now[0])
        cache.put("f|a", b"1")
        now[0] = 9.9
        assert cache.get("f|a") is not None
        now[0] = 10.0
        assert cache.get("f|a") is None  # expired, lazily dropped
        assert cache.stats()["entries"] == 0
        # Lazy expiry keeps the gauges honest too — a read-only lull must
        # not leave /metrics reporting pre-TTL entries/bytes.
        assert reg.gauge("ai4e_rescache_entries", "").value() == 0
        assert reg.gauge("ai4e_rescache_bytes", "").value() == 0

    def test_bypass_header_falsy_values_do_not_bypass(self):
        from ai4e_tpu.rescache.keys import cache_bypass_requested
        assert cache_bypass_requested({"X-Cache-Bypass": "1"})
        assert cache_bypass_requested({"X-Cache-Bypass": "true"})
        assert cache_bypass_requested({"Cache-Control": "no-cache"})
        # Explicit falsy values mean "do not bypass".
        for raw in ("0", "false", "no", "off", ""):
            assert not cache_bypass_requested({"X-Cache-Bypass": raw})
        assert not cache_bypass_requested({})

    def test_invalidate_family_is_scoped(self):
        cache = ResultCache(metrics=MetricsRegistry())
        cache.put("fam1|a", b"1")
        cache.put("fam1|b", b"2")
        cache.put("fam2|c", b"3")
        assert cache.invalidate_family("fam1") == 2
        assert not cache.peek("fam1|a") and not cache.peek("fam1|b")
        assert cache.peek("fam2|c")

    def test_invalidate_family_clears_inflight(self):
        cache = ResultCache(metrics=MetricsRegistry())
        cache.register_inflight("fam1|a", "t1")
        cache.register_inflight("fam2|b", "t2")
        cache.invalidate_family("fam1")
        assert cache.leader_for("fam1|a") is None
        assert cache.leader_for("fam2|b") == "t2"


class TestSingleFlightRegistry:
    def test_register_leader_release(self):
        cache = ResultCache(metrics=MetricsRegistry())
        assert cache.register_inflight("f|k", "t1") is True
        assert cache.register_inflight("f|k", "t2") is False  # t1 owns it
        assert cache.leader_for("f|k") == "t1"
        cache.release_inflight("f|k", "t2")  # stale release: no-op
        assert cache.leader_for("f|k") == "t1"
        cache.release_inflight("f|k", "t1")
        assert cache.leader_for("f|k") is None


# -- gateway async path e2e --------------------------------------------------


async def _echo_platform(reg: MetricsRegistry):
    """Platform + real runtime/batcher/worker serving the echo model on an
    async route, with the result cache enabled. Returns
    (platform, gw_client, svc_client, batcher, payload, public_path)."""
    platform = LocalPlatform(PlatformConfig(retry_delay=0.05,
                                            result_cache=True), metrics=reg)
    servable = build_servable("echo", name="echo", size=8, buckets=(4,))
    runtime = ModelRuntime()
    runtime.register(servable)
    batcher = MicroBatcher(runtime, max_wait_ms=1.0, metrics=reg)
    worker = InferenceWorker("w", runtime, batcher,
                             task_manager=platform.task_manager,
                             prefix="v1/echo", store=platform.store,
                             result_cache=platform.result_cache)
    worker.serve_model(servable, async_path="/run-async")
    await batcher.start()
    svc_client = await serve(worker.service.app)
    backend = str(svc_client.make_url("/v1/echo/run-async"))
    platform.publish_async_api("/v1/public/run", backend)
    gw_client = await serve(platform.gateway.app)
    await platform.start()
    payload = npy_bytes(np.arange(8, dtype=np.float32))
    return platform, gw_client, svc_client, batcher, payload


def _executed_examples(reg: MetricsRegistry) -> float:
    total = 0.0
    for _, _, _labels, data in reg.histogram("ai4e_batch_size", "").collect():
        total += float(data["sum"])
    return total


class TestAsyncPathCaching:
    def test_coalescing_one_execution_for_n_identical_requests(self):
        """THE coalescing guarantee: N concurrent identical requests → one
        device execution (runtime batch-size metric), every client a correct
        completed record + result."""
        async def main():
            reg = MetricsRegistry()
            (platform, gw, svc, batcher, payload) = await _echo_platform(reg)
            try:
                n = 5
                posts = await asyncio.gather(*(
                    gw.post("/v1/public/run", data=payload) for _ in range(n)))
                records, xcache = [], []
                for resp in posts:
                    assert resp.status == 200
                    xcache.append(resp.headers.get("X-Cache"))
                    records.append(await resp.json())
                # Exactly one execution owner; everyone else rode it.
                assert xcache.count("miss") == 1, xcache
                assert all(x in ("miss", "coalesced", "hit") for x in xcache)
                # Coalesced submits share the leader's TaskId.
                leader_id = records[xcache.index("miss")]["TaskId"]
                for rec, x in zip(records, xcache):
                    if x == "coalesced":
                        assert rec["TaskId"] == leader_id
                # Every client's task reaches completed with the right result.
                expect = {"echo": [float(v) for v in range(8)]}
                for rec in records:
                    final = await poll_until(
                        gw, rec["TaskId"],
                        lambda b: "completed" in b["Status"])
                    assert "completed" in final["Status"], final
                    body, _ctype = platform.store.get_result(rec["TaskId"])
                    assert json.loads(body) == expect
                assert _executed_examples(reg) == 1.0

                # A later identical request is a straight cache hit: a fresh,
                # already-terminal task — still no second execution.
                resp = await gw.post("/v1/public/run", data=payload)
                assert resp.headers.get("X-Cache") == "hit"
                rec = await resp.json()
                assert rec["Status"] == "completed - served from cache"
                assert rec["TaskId"] != leader_id
                body, _ctype = platform.store.get_result(rec["TaskId"])
                assert json.loads(body) == expect
                assert _executed_examples(reg) == 1.0
            finally:
                await platform.stop()
                await batcher.stop()
                await gw.close()
                await svc.close()

        run(main())

    def test_bypass_header_opts_out_and_executes(self):
        async def main():
            reg = MetricsRegistry()
            (platform, gw, svc, batcher, payload) = await _echo_platform(reg)
            try:
                first = await gw.post("/v1/public/run", data=payload)
                assert first.headers.get("X-Cache") == "miss"
                await poll_until(gw, (await first.json())["TaskId"],
                                 lambda b: "completed" in b["Status"])
                assert _executed_examples(reg) == 1.0

                resp = await gw.post("/v1/public/run", data=payload,
                                     headers={"X-Cache-Bypass": "1"})
                assert resp.headers.get("X-Cache") == "bypass"
                rec = await resp.json()
                assert rec["Status"] == "created"
                await poll_until(gw, rec["TaskId"],
                                 lambda b: "completed" in b["Status"])
                # Opted out on both ends: executed again, and its result was
                # not stored (no CacheKey on the task).
                assert _executed_examples(reg) == 2.0
                assert "CacheKey" not in rec
            finally:
                await platform.stop()
                await batcher.stop()
                await gw.close()
                await svc.close()

        run(main())

    def test_different_payloads_do_not_share_results(self):
        async def main():
            reg = MetricsRegistry()
            (platform, gw, svc, batcher, payload) = await _echo_platform(reg)
            try:
                other = npy_bytes(np.arange(8, dtype=np.float32) + 1.0)
                r1 = await gw.post("/v1/public/run", data=payload)
                r2 = await gw.post("/v1/public/run", data=other)
                assert r2.headers.get("X-Cache") == "miss"  # distinct key
                t1 = (await r1.json())["TaskId"]
                t2 = (await r2.json())["TaskId"]
                assert t1 != t2
                await poll_until(gw, t1, lambda b: "completed" in b["Status"])
                await poll_until(gw, t2, lambda b: "completed" in b["Status"])
                b1, _ = platform.store.get_result(t1)
                b2, _ = platform.store.get_result(t2)
                assert json.loads(b1) != json.loads(b2)
                assert _executed_examples(reg) == 2.0
            finally:
                await platform.stop()
                await batcher.stop()
                await gw.close()
                await svc.close()

        run(main())


class TestDispatcherServeFromCache:
    def test_redelivery_completes_from_cache_without_backend(self):
        """A task whose identical request's result is already cached
        completes at the DISPATCHER — the backend (dead here) is never
        needed. Covers redeliveries/requeues/journal-restored tasks."""
        async def main():
            reg = MetricsRegistry()
            platform = LocalPlatform(PlatformConfig(
                retry_delay=0.05, result_cache=True), metrics=reg)
            # Backend is a closed port: a plain dispatch can never succeed.
            platform.publish_async_api("/v1/public/dead",
                                       "http://127.0.0.1:1/v1/dead/x")
            gw = await serve(platform.gateway.app)
            await platform.start()
            try:
                resp = await gw.post("/v1/public/dead", data=b"PAYLOAD")
                assert resp.headers.get("X-Cache") == "miss"
                tid = (await resp.json())["TaskId"]
                key = platform.store.get(tid).cache_key
                assert key
                # The identical request's result lands in the cache (as if
                # computed elsewhere); the next redelivery must serve it.
                platform.result_cache.put(key, b'{"ok": 1}')
                final = await poll_until(
                    gw, tid, lambda b: "completed" in b["Status"])
                assert final["Status"] == "completed - served from cache"
                body, ctype = platform.store.get_result(tid)
                assert json.loads(body) == {"ok": 1}
                # Terminal transition released the single-flight leader.
                assert platform.result_cache.leader_for(key) is None
            finally:
                await platform.stop()
                await gw.close()

        run(main())


# -- invalidation on checkpoint hot reload -----------------------------------


class TestInvalidationOnHotReload:
    def test_reload_invalidates_and_serves_new_weights(self, tmp_path):
        """A weight swap must make every pre-swap cached result unreachable:
        the same request after reload returns the NEW model's answer."""
        async def main():
            reg = MetricsRegistry()
            servable = build_servable("echo", name="echo", size=8,
                                      buckets=(4,))
            runtime = ModelRuntime()
            runtime.register(servable)
            batcher = MicroBatcher(runtime, max_wait_ms=1.0, metrics=reg)
            cache = ResultCache(metrics=reg)
            worker = InferenceWorker("w", runtime, batcher,
                                     prefix="v1/echo", result_cache=cache,
                                     checkpoint_root=str(tmp_path))
            worker.serve_model(servable, sync_path="/run")
            await batcher.start()
            client = await serve(worker.service.app)
            try:
                from ai4e_tpu.checkpoint import save_params
                ckpt = str(tmp_path / "echo_v2")
                save_params(ckpt, {"scale": np.array(3.0, np.float32)})

                payload = npy_bytes(np.arange(8, dtype=np.float32))
                before = (await (await client.post(
                    "/v1/echo/run", data=payload)).json())["echo"]
                assert before[:3] == [0.0, 1.0, 2.0]
                executed_once = _executed_examples(reg)
                # Second identical request: served from the worker cache —
                # no new device execution (worker-level lookups are
                # deliberately uncounted in hit/miss, which belong to the
                # gateway edge, so assert on the batch metric instead).
                again = (await (await client.post(
                    "/v1/echo/run", data=payload)).json())["echo"]
                assert again == before
                assert _executed_examples(reg) == executed_once
                assert cache.stats()["entries"] == 1

                resp = await client.post("/v1/echo/models/echo/reload",
                                         json={"checkpoint": ckpt})
                assert resp.status == 200, await resp.json()
                # The family was invalidated with the swap.
                assert cache.stats()["entries"] == 0

                after = (await (await client.post(
                    "/v1/echo/run", data=payload)).json())["echo"]
                assert after[:3] == [0.0, 3.0, 6.0]  # new weights, not stale
            finally:
                await batcher.stop()
                await client.close()

        run(main())


# -- ADVICE r5 regressions ---------------------------------------------------


class TestReloadEndpointHardening:
    """ADVICE r5: the hot-reload endpoint must confine checkpoint paths to
    the configured root (realpath prefix) and honor the API-key gate."""

    def test_traversal_path_rejected_403(self, tmp_path):
        async def main():
            servable = build_servable("echo", name="echo", size=8,
                                      buckets=(4,))
            runtime = ModelRuntime()
            runtime.register(servable)
            batcher = MicroBatcher(runtime, max_wait_ms=1.0,
                                   metrics=MetricsRegistry())
            root = tmp_path / "ckpts"
            root.mkdir()
            worker = InferenceWorker("w", runtime, batcher, prefix="v1/echo",
                                     checkpoint_root=str(root))
            worker.serve_model(servable, sync_path="/run")
            await batcher.start()
            client = await serve(worker.service.app)
            try:
                for evil in (str(root / ".." / "outside"), "/etc/passwd",
                             str(root) + "_sibling/ckpt"):
                    resp = await client.post(
                        "/v1/echo/models/echo/reload",
                        json={"checkpoint": evil})
                    assert resp.status == 403, (evil, await resp.json())
            finally:
                await batcher.stop()
                await client.close()

        run(main())

    def test_symlink_escape_rejected_403(self, tmp_path):
        async def main():
            servable = build_servable("echo", name="echo", size=8,
                                      buckets=(4,))
            runtime = ModelRuntime()
            runtime.register(servable)
            batcher = MicroBatcher(runtime, max_wait_ms=1.0,
                                   metrics=MetricsRegistry())
            root = tmp_path / "ckpts"
            root.mkdir()
            outside = tmp_path / "outside"
            outside.mkdir()
            (root / "link").symlink_to(outside)
            worker = InferenceWorker("w", runtime, batcher, prefix="v1/echo",
                                     checkpoint_root=str(root))
            worker.serve_model(servable, sync_path="/run")
            await batcher.start()
            client = await serve(worker.service.app)
            try:
                resp = await client.post(
                    "/v1/echo/models/echo/reload",
                    json={"checkpoint": str(root / "link" / "ckpt")})
                assert resp.status == 403
            finally:
                await batcher.stop()
                await client.close()

        run(main())

    def test_api_key_gate(self, tmp_path):
        async def main():
            servable = build_servable("echo", name="echo", size=8,
                                      buckets=(4,))
            runtime = ModelRuntime()
            runtime.register(servable)
            batcher = MicroBatcher(runtime, max_wait_ms=1.0,
                                   metrics=MetricsRegistry())
            worker = InferenceWorker("w", runtime, batcher, prefix="v1/echo",
                                     checkpoint_root=str(tmp_path),
                                     admin_api_keys={"sek"})
            worker.serve_model(servable, sync_path="/run")
            await batcher.start()
            client = await serve(worker.service.app)
            try:
                resp = await client.post("/v1/echo/models/echo/reload")
                assert resp.status == 401
                from ai4e_tpu.checkpoint import save_params
                ckpt = str(tmp_path / "echo_v2")
                save_params(ckpt, {"scale": np.array(2.0, np.float32)})
                resp = await client.post(
                    "/v1/echo/models/echo/reload",
                    json={"checkpoint": ckpt},
                    headers={"Ocp-Apim-Subscription-Key": "sek"})
                assert resp.status == 200, await resp.json()
            finally:
                await batcher.stop()
                await client.close()

        run(main())


class TestLegacyTaskIdReplay:
    """ADVICE r5: the ':' TaskId guard must not run on journal replay or
    follower absorb — a legacy journal must load, not crash-loop."""

    def _legacy_record(self, task_id: str) -> dict:
        return {"TaskId": task_id, "Timestamp": time.time(),
                "Status": "created", "BackendStatus": "created",
                "Endpoint": "/v1/legacy/x",
                "ContentType": "application/json",
                "BodyHex": b"legacy-body".hex()}

    def test_replay_accepts_legacy_colon_ids(self, tmp_path):
        path = str(tmp_path / "journal.jsonl")
        with open(path, "w", encoding="utf-8") as f:
            f.write(json.dumps(self._legacy_record("legacy:0")) + "\n")
        store = JournaledTaskStore(path)
        try:
            assert store.get("legacy:0").endpoint == "/v1/legacy/x"
            # External writes still validate.
            with pytest.raises(ValueError):
                store.upsert(APITask(task_id="evil:1", endpoint="/v1/x"))
        finally:
            store.close()

    def test_follower_absorb_accepts_legacy_colon_ids(self, tmp_path):
        path = str(tmp_path / "follower.jsonl")
        store = FollowerTaskStore(path)
        try:
            store.absorb_lines(
                [json.dumps(self._legacy_record("legacy:1"))])
            assert store.get("legacy:1").endpoint == "/v1/legacy/x"
        finally:
            store.close()


class TestPassiveEpochBound:
    """ADVICE r5: unauthenticated X-Store-Epoch evidence may demote a
    primary only within PASSIVE_EPOCH_BOUND of its own epoch; a forged huge
    epoch is ignored — only the authenticated /demote path is unbounded."""

    def test_plausible_epoch_demotes(self, tmp_path):
        store = FollowerTaskStore(str(tmp_path / "a.jsonl"),
                                  start_as_primary=True)
        try:
            store.note_epoch(store.epoch + 1)
            assert store.role == "follower"
        finally:
            store.close()

    def test_forged_huge_epoch_ignored(self, tmp_path):
        store = FollowerTaskStore(str(tmp_path / "b.jsonl"),
                                  start_as_primary=True)
        try:
            forged = store.epoch + store.PASSIVE_EPOCH_BOUND + 1
            store.note_epoch(forged)
            assert store.role == "primary"  # still serving writes
            assert store.epoch == 0        # evidence NOT adopted
            # The explicit authenticated path stays unbounded.
            store.demote(forged)
            assert store.role == "follower"
            assert store.epoch == forged
        finally:
            store.close()


class TestClientRetryExhaustion:
    """ADVICE r5: a replica pass that captures neither a response nor a
    connection error (budget expired mid-pass) must raise a real
    TaskTimeout, not ``raise None``'s TypeError."""

    def test_budget_exhausted_raises_task_timeout(self):
        client = ai4e_client.AI4EClient(
            ["http://127.0.0.1:1", "http://127.0.0.1:2"],
            timeout=0.0, retries=0)
        with pytest.raises(ai4e_client.TaskTimeout):
            client.status("some-task")

    def test_single_gateway_budget_exhausted_raises_task_timeout(self):
        client = ai4e_client.AI4EClient("http://127.0.0.1:1",
                                        timeout=0.0, retries=2,
                                        retry_backoff=0.001)
        with pytest.raises(ai4e_client.TaskTimeout):
            client.status("some-task")


class TestNormalizeBackendsCopy:
    """ADVICE r5: the pre-normalized fast path must return a COPY — caller
    mutation after registration must not rewrite live routing weights."""

    def test_fast_path_returns_copy(self):
        backends = [("http://h1/v1/x", 1.0), ("http://h2/v1/x", 3.0)]
        out = normalize_backends(backends)
        assert out == backends and out is not backends
        backends[1] = ("http://evil/v1/x", 1000.0)
        backends.append(("http://more-evil/v1/x", 1000.0))
        assert out == [("http://h1/v1/x", 1.0), ("http://h2/v1/x", 3.0)]
        # The registered set still routes to the original hosts only.
        assert {pick_backend(out) for _ in range(50)} <= {
            "http://h1/v1/x", "http://h2/v1/x"}


# -- staleness-proof fills + single-flight cleanup (review hardening) --------


class TestStaleFillRefusal:
    """A result that was already EXECUTING when an invalidation landed must
    not re-populate the cache on completion — the fill is conditional on
    still owning the single-flight registration (async path) or on the
    family's invalidation generation (sync proxy path)."""

    def _store_and_cache(self):
        store = InMemoryTaskStore()
        cache = ResultCache(metrics=MetricsRegistry())
        attach_store(store, cache)
        return store, cache

    def _complete(self, store, task):
        store.set_result(task.task_id, b'{"r": 1}')
        store.upsert(task.with_status("completed", TaskStatus.COMPLETED))

    def test_registered_leader_fill_lands(self):
        store, cache = self._store_and_cache()
        task = store.upsert(APITask(endpoint="/v1/x", body=b"p",
                                    cache_key="fam|k"))
        cache.register_inflight("fam|k", task.task_id)
        self._complete(store, task)
        assert cache.peek("fam|k")
        assert cache.leader_for("fam|k") is None

    def test_invalidation_mid_flight_refuses_the_fill(self):
        store, cache = self._store_and_cache()
        task = store.upsert(APITask(endpoint="/v1/x", body=b"p",
                                    cache_key="fam|k"))
        cache.register_inflight("fam|k", task.task_id)
        # Checkpoint hot reload lands while the task is still executing.
        cache.invalidate_family("fam")
        self._complete(store, task)
        assert not cache.peek("fam|k")  # old-weights result never lands
        assert cache.leader_for("fam|k") is None

    def test_unregistered_completion_leaves_cache_cold(self):
        # Journal-restored / requeued task: completed with a cache_key but
        # no live registration — cold is safe, stale is not.
        store, cache = self._store_and_cache()
        task = store.upsert(APITask(endpoint="/v1/x", body=b"p",
                                    cache_key="fam|k"))
        self._complete(store, task)
        assert not cache.peek("fam|k")

    def test_put_if_generation_refuses_stale_sync_fill(self):
        cache = ResultCache(metrics=MetricsRegistry())
        gen = cache.generation("fam|k")  # captured at proxy leadership
        cache.invalidate_family("fam")   # reload lands mid-proxy
        assert cache.put("fam|k", b"old", if_generation=gen) is False
        assert not cache.peek("fam|k")
        assert cache.put("fam|k", b"new",
                         if_generation=cache.generation("fam|k")) is True
        assert cache.peek("fam|k")

    def test_fill_inflight_only_for_the_owner(self):
        cache = ResultCache(metrics=MetricsRegistry())
        cache.register_inflight("f|k", "t1")
        assert cache.fill_inflight("f|k", "t2", b"r") is False
        assert not cache.peek("f|k")
        assert cache.leader_for("f|k") == "t1"  # non-owner releases nothing
        assert cache.fill_inflight("f|k", "t1", b"r") is True
        assert cache.peek("f|k") and cache.leader_for("f|k") is None

    def test_release_inflight_reports_ownership(self):
        cache = ResultCache(metrics=MetricsRegistry())
        cache.register_inflight("f|k", "t1")
        assert cache.release_inflight("f|k", "t2") is False
        assert cache.release_inflight("f|k", "t1") is True


class TestEdgeOnlyCounting:
    def test_uncounted_lookup_leaves_hit_ratio_alone(self):
        """Internal lookups (dispatcher redelivery check, worker sync path)
        pass count=False so one external request records exactly one
        outcome and the hit ratio stays an edge statement."""
        cache = ResultCache(metrics=MetricsRegistry())
        cache.put("f|k", b"x")
        assert cache.get("f|k", count=False) is not None
        assert cache.get("f|missing", count=False) is None
        stats = cache.stats()
        assert stats["hits"] == 0 and stats["misses"] == 0
        cache.get("f|k")
        assert cache.stats()["hits"] == 1


class TestSyncSingleFlightCleanup:
    def test_leader_failure_before_proxy_releases_waiters(self):
        """The leader future is registered BEFORE the backend session is
        acquired; a failure (or cancellation) in that window must still run
        the cleanup, or every later identical POST awaits a future nobody
        will ever resolve. Regression: _get_session raising used to leak the
        registration and wedge the key forever."""
        async def main():
            reg = MetricsRegistry()
            platform = LocalPlatform(PlatformConfig(result_cache=True),
                                     metrics=reg)
            platform.publish_sync_api("/v1/public/sync",
                                      "http://127.0.0.1:1/v1/x")

            async def boom():
                raise RuntimeError("session factory down")

            platform.gateway._get_session = boom
            gw = await serve(platform.gateway.app)
            try:
                # Without the try/finally covering the registration window,
                # one of these wedges forever and gather never returns.
                r1, r2 = await asyncio.wait_for(asyncio.gather(
                    gw.post("/v1/public/sync", data=b"B"),
                    gw.post("/v1/public/sync", data=b"B")), timeout=10.0)
                assert r1.status == 500 and r2.status == 500
                assert platform.gateway._sync_inflight == {}
                # The key is not wedged: a fresh identical POST still runs.
                r3 = await asyncio.wait_for(
                    gw.post("/v1/public/sync", data=b"B"), timeout=10.0)
                assert r3.status == 500
                assert platform.gateway._sync_inflight == {}
            finally:
                await gw.close()

        run(main())


class TestWorkerSyncBypass:
    def test_bypass_header_executes_past_the_worker_cache(self):
        """The documented X-Cache-Bypass contract ("this request must
        execute; no cache read, no store") must hold at the worker's own
        sync cache — not only at the gateway. Regression: the _sync handler
        had no access to request headers, so a bypassed request was still
        answered from the worker cache."""
        async def main():
            reg = MetricsRegistry()
            servable = build_servable("echo", name="echo", size=8,
                                      buckets=(4,))
            runtime = ModelRuntime()
            runtime.register(servable)
            batcher = MicroBatcher(runtime, max_wait_ms=1.0, metrics=reg)
            cache = ResultCache(metrics=reg)
            worker = InferenceWorker("w", runtime, batcher,
                                     prefix="v1/echo", result_cache=cache)
            worker.serve_model(servable, sync_path="/run")
            await batcher.start()
            client = await serve(worker.service.app)
            try:
                payload = npy_bytes(np.arange(8, dtype=np.float32))
                first = await (await client.post(
                    "/v1/echo/run", data=payload)).json()
                assert _executed_examples(reg) == 1.0
                assert cache.stats()["entries"] == 1

                # A cached answer exists — the bypass must execute anyway
                # (no cache read) and must not overwrite the entry (no
                # store).
                for hdr in ({"X-Cache-Bypass": "1"},
                            {"Cache-Control": "no-cache"}):
                    again = await (await client.post(
                        "/v1/echo/run", data=payload, headers=hdr)).json()
                    assert again == first
                assert _executed_examples(reg) == 3.0
                assert cache.stats()["entries"] == 1

                # Without the header the cache still answers.
                assert (await (await client.post(
                    "/v1/echo/run", data=payload)).json()) == first
                assert _executed_examples(reg) == 3.0
            finally:
                await batcher.stop()
                await client.close()

        run(main())


class TestSyncCoalesceInvalidation:
    def test_waiter_does_not_adopt_pre_reload_leader(self):
        """A checkpoint reload that lands while a sync leader is proxying
        invalidates the family; identical requests arriving AFTER the swap
        must re-execute instead of coalescing onto the old-weights
        execution. Regression: waiters joined the leader future with no
        generation check, so the put(if_generation=) guard protected the
        cache but not the coalesced responses."""
        async def main():
            reg = MetricsRegistry()
            hits = 0
            got_request = asyncio.Event()
            release = asyncio.Event()

            from aiohttp import web

            async def backend(request):
                nonlocal hits
                hits += 1
                mine = hits
                got_request.set()
                if mine == 1:
                    await release.wait()
                return web.Response(text=str(mine))

            app = web.Application()
            app.router.add_post("/v1/x", backend)
            be = await serve(app)

            platform = LocalPlatform(PlatformConfig(result_cache=True),
                                     metrics=reg)
            backend_uri = str(be.make_url("/v1/x"))
            platform.publish_sync_api("/v1/public/sync", backend_uri)
            gw = await serve(platform.gateway.app)
            try:
                leader = asyncio.create_task(
                    gw.post("/v1/public/sync", data=b"B"))
                await asyncio.wait_for(got_request.wait(), timeout=10.0)

                # Weight swap mid-proxy: the family's generation advances.
                from ai4e_tpu.taskstore.task import endpoint_path
                platform.result_cache.invalidate_family(
                    endpoint_path(backend_uri))

                waiter = asyncio.create_task(
                    gw.post("/v1/public/sync", data=b"B"))
                await asyncio.sleep(0.05)   # let the waiter join the future
                release.set()

                r1 = await asyncio.wait_for(leader, timeout=10.0)
                r2 = await asyncio.wait_for(waiter, timeout=10.0)
                assert await r1.text() == "1"
                # The waiter re-executed on the (notionally new) weights —
                # it did NOT adopt the pre-swap leader's response.
                assert r2.headers.get("X-Cache") != "coalesced"
                assert await r2.text() == "2"
                assert hits == 2
                # And the leader's stale fill was refused.
                assert platform.result_cache.stats()["entries"] == 0
            finally:
                await gw.close()
                await be.close()

        run(main())


class TestDispatcherNoResultStore:
    def test_cache_hit_without_result_store_dispatches(self):
        """A Dispatcher given a cache but no result_store must NOT complete
        from the cache: there is nowhere to put the payload, and a terminal
        task whose result fetch returns nothing is a permanently lost
        output. It dispatches normally instead."""
        async def main():
            from ai4e_tpu.broker.dispatcher import Dispatcher
            from ai4e_tpu.broker.queue import InMemoryBroker, Message
            cache = ResultCache()
            key = request_key("/v1/x", b"B")
            cache.put(key, b'{"ok": 1}')
            d = Dispatcher(InMemoryBroker(), "q", "http://127.0.0.1:1/v1/x",
                           task_manager=None, result_cache=cache,
                           result_store=None)
            msg = Message(task_id="t-1", endpoint="/v1/x", cache_key=key)
            assert await d._complete_from_cache(msg) is False

        run(main())

    def test_cache_hit_without_task_manager_completes(self):
        """task_manager=None tolerance (result-path-focused tests) must
        survive the PR 5 post-hop terminality re-check: a cache hit with a
        result_store but NO task manager completes from the cache instead
        of crashing on the re-probe (the _try_update shim already
        tolerates the write failing)."""
        async def main():
            from ai4e_tpu.broker.dispatcher import Dispatcher
            from ai4e_tpu.broker.queue import InMemoryBroker, Message

            class Sink:
                def __init__(self):
                    self.results = {}

                def set_result(self, task_id, payload,
                               content_type="application/json"):
                    self.results[task_id] = payload

            cache = ResultCache()
            key = request_key("/v1/x", b"B")
            cache.put(key, b'{"ok": 1}')
            sink = Sink()
            d = Dispatcher(InMemoryBroker(), "q", "http://127.0.0.1:1/v1/x",
                           task_manager=None, result_cache=cache,
                           result_store=sink)
            msg = Message(task_id="t-1", endpoint="/v1/x", cache_key=key)
            assert await d._complete_from_cache(msg) is True
            assert sink.results["t-1"] == b'{"ok": 1}'

        run(main())


class TestStandbyOutcomeCounting:
    def test_not_primary_503_counts_no_cache_outcome(self):
        """A standby replica answers cacheable POSTs with 503 not-primary;
        each client retry must NOT count a rescache miss (or bypass) —
        outcomes sum to answered requests (docs/METRICS.md). Regression:
        count_miss fired before the upsert raised NotPrimaryError."""
        async def main():
            from ai4e_tpu.gateway.router import Gateway
            from ai4e_tpu.taskstore import NotPrimaryError

            class StandbyStore(InMemoryTaskStore):
                def upsert(self, task, **kw):
                    raise NotPrimaryError()

            reg = MetricsRegistry()
            cache = ResultCache(metrics=reg)
            gateway = Gateway(StandbyStore(), metrics=reg)
            gateway.set_result_cache(cache)
            gateway.add_async_route("/v1/public/run",
                                    "http://127.0.0.1:1/v1/x")
            gw = await serve(gateway.app)
            try:
                for hdrs in (None, {"X-Cache-Bypass": "1"}):
                    resp = await gw.post("/v1/public/run", data=b"B",
                                         headers=hdrs)
                    assert resp.status == 503
                    assert resp.headers.get("X-Not-Primary") == "1"
                s = cache.stats()
                assert (s["misses"], s["bypass"]) == (0.0, 0.0)
            finally:
                await gw.close()

        run(main())


class TestNonDurableResultsStayInline:
    def test_hit_result_skips_the_offload_backend(self, tmp_path):
        """With a result backend + offload threshold configured, a
        durable=False record's result must store inline: per-hit blob
        writes would put payload-sized I/O back on the path the cache
        exists to avoid, and a restart would orphan the blobs (no
        journaled record references them)."""
        from ai4e_tpu.taskstore.results import FileResultBackend
        path = str(tmp_path / "journal.jsonl")
        blobs = tmp_path / "blobs"
        store = JournaledTaskStore(
            path, result_backend=FileResultBackend(str(blobs)),
            result_offload_threshold=1)
        payload = b'{"r": "x"}'

        a = store.upsert(APITask(endpoint="/v1/x", status="completed - ok",
                                 backend_status="completed"))
        store.set_result(a.task_id, payload)
        blobs_after_durable = len(list(blobs.iterdir()))
        assert blobs_after_durable > 0   # >= threshold: offloaded

        b = store.upsert(APITask(endpoint="/v1/x",
                                 status="completed - served from cache",
                                 backend_status="completed", durable=False))
        store.set_result(b.task_id, payload)
        assert len(list(blobs.iterdir())) == blobs_after_durable  # inline
        assert store.get_result(b.task_id) == (payload,
                                               "application/json")
        store.close()


class TestNativeStoreCacheProvenance:
    def test_listener_fill_and_release_work_on_the_native_store(self):
        """PlatformConfig(native_store=True, result_cache=True): the C++
        record has no CacheKey field, so provenance rides a Python-side
        sidecar (native.py). Regression: tasks notified by the native store
        carried cache_key=='' — the cache never filled and single-flight
        registrations never released, coalescing every later duplicate onto
        a stale (possibly failed) record until eviction."""
        from ai4e_tpu.taskstore.native import NativeTaskStore
        cache = ResultCache()
        store = NativeTaskStore()
        attach_store(store, cache)
        key = request_key("/v1/api/op", b"payload")

        t = store.upsert(APITask(task_id="", endpoint="http://h/v1/api/op",
                                 body=b"payload", cache_key=key))
        assert store.get(t.task_id).cache_key == key
        cache.register_inflight(key, t.task_id)

        store.set_result(t.task_id, b'{"r": 1}')
        store.update_status(t.task_id, "completed - done",
                            backend_status="completed")
        # The terminal transition filled the cache and released the leader.
        assert cache.get(key) == (b'{"r": 1}', "application/json")
        assert cache.leader_for(key) is None

    def test_failed_leader_releases_on_the_native_store(self):
        """A FAILED task must release its registration too, or duplicates
        coalesce onto the corpse forever."""
        from ai4e_tpu.taskstore.native import NativeTaskStore
        cache = ResultCache()
        store = NativeTaskStore()
        attach_store(store, cache)
        key = request_key("/v1/api/op", b"payload")
        t = store.upsert(APITask(task_id="", endpoint="http://h/v1/api/op",
                                 body=b"payload", cache_key=key))
        cache.register_inflight(key, t.task_id)
        store.update_status(t.task_id, "failed - backend 500",
                            backend_status="failed")
        assert cache.get(key) is None
        assert cache.leader_for(key) is None


class TestHitRecordDurability:
    def test_non_durable_records_skip_the_journal(self, tmp_path):
        """durable=False records (cache hits) stay queryable in memory but
        never reach the journal — not on upsert, not via their result, and
        not through compaction — so a high duplicate rate costs no fsync
        I/O. After a restart they are simply gone (the submit response
        already carried the terminal record)."""
        from ai4e_tpu.taskstore.store import TaskNotFound
        path = str(tmp_path / "journal.jsonl")
        store = JournaledTaskStore(path)
        a = store.upsert(APITask(endpoint="/v1/x", body=b"req-a",
                                 status="completed - done",
                                 backend_status="completed"))
        store.set_result(a.task_id, b'{"r": "a"}')
        size_after_durable = os.path.getsize(path)
        assert size_after_durable > 0

        b = store.upsert(APITask(endpoint="/v1/x", body=b"req-a",
                                 status="completed - served from cache",
                                 backend_status="completed", durable=False))
        store.set_result(b.task_id, b'{"r": "a"}')
        assert os.path.getsize(path) == size_after_durable
        # Queryable while the process lives — the client contract holds.
        assert store.get(b.task_id).status == "completed - served from cache"
        assert store.get_result(b.task_id) == (b'{"r": "a"}',
                                               "application/json")
        # A rewrite must not promote it to durability.
        store.compact()
        store.close()

        reopened = JournaledTaskStore(path)
        assert reopened.get(a.task_id).canonical_status == "completed"
        assert reopened.get_result(a.task_id) == (b'{"r": "a"}',
                                                  "application/json")
        with pytest.raises(TaskNotFound):
            reopened.get(b.task_id)
        assert reopened.get_result(b.task_id) is None
        reopened.close()

    def test_gateway_hit_record_is_non_durable(self):
        """The async-path cache hit marks its task record durable=False."""
        async def main():
            reg = MetricsRegistry()
            (platform, gw, svc, batcher, payload) = await _echo_platform(reg)
            try:
                first = await gw.post("/v1/public/run", data=payload)
                miss_id = (await first.json())["TaskId"]
                await poll_until(gw, miss_id,
                                 lambda rec: "completed" in rec["Status"])
                hit = await gw.post("/v1/public/run", data=payload)
                assert hit.headers.get("X-Cache") == "hit"
                hit_id = (await hit.json())["TaskId"]
                assert platform.store.get(miss_id).durable is True
                assert platform.store.get(hit_id).durable is False
            finally:
                await platform.stop()
                await batcher.stop()
                await gw.close()
                await svc.close()

        run(main())


class TestWorkerCliHardeningWired:
    def test_build_worker_wires_reload_confinement_and_keys(self, tmp_path):
        """The production worker entrypoint must actually pass the reload
        hardening through — checkpoint_root from the checkpoint mount and
        admin keys from the front-door secret — or the ADVICE r5 fix is
        inert in deployment (guards default to None/open)."""
        from ai4e_tpu.cli import build_worker
        from ai4e_tpu.config import FrameworkConfig
        cfg = FrameworkConfig.from_env(env={
            "AI4E_RUNTIME_PLATFORM": "cpu",
            "AI4E_RUNTIME_CHECKPOINT_DIR": str(tmp_path),
            "AI4E_GATEWAY_API_KEYS": "sk-1, sk-2",
        })
        worker, batcher, _tm = build_worker(cfg, {"models": []})
        assert worker._checkpoint_root == os.path.realpath(str(tmp_path))
        assert worker._admin_keys == {"sk-1", "sk-2"}

        open_worker, _b, _t = build_worker(
            FrameworkConfig.from_env(env={"AI4E_RUNTIME_PLATFORM": "cpu"}),
            {"models": []})
        assert open_worker._checkpoint_root is None   # dev stays open
        assert open_worker._admin_keys is None


class TestNonDurablePromotion:
    def test_external_upsert_cannot_promote_a_hit_record(self, tmp_path):
        """A full upsert over a non-durable (cache-hit) record — e.g. via
        the taskstore HTTP facade, where from_dict defaults durable=True —
        must stay memory-only: its create was never journaled, so promoting
        it would journal orphan transitions and resurrect on restart a
        TaskId the hit contract says should 404."""
        path = str(tmp_path / "journal.jsonl")
        store = JournaledTaskStore(path)
        hit = store.upsert(APITask(endpoint="/v1/x",
                                   status="completed - served from cache",
                                   backend_status="completed",
                                   durable=False))
        size = os.path.getsize(path)
        replacement = store.upsert(APITask(task_id=hit.task_id,
                                           endpoint="/v1/x",
                                           status="completed - rewritten",
                                           backend_status="completed"))
        assert replacement.durable is False
        assert os.path.getsize(path) == size
        store.compact()
        store.close()
        reopened = JournaledTaskStore(path)
        from ai4e_tpu.taskstore.store import TaskNotFound
        with pytest.raises(TaskNotFound):
            reopened.get(hit.task_id)
        reopened.close()


class TestConfigPlumbing:
    def test_platform_env_section_carries_cache_knobs(self):
        """The deployable surface: AI4E_PLATFORM_RESULT_CACHE* must reach
        PlatformConfig, or the control-plane CLI can never enable the
        cache."""
        from ai4e_tpu.config import PlatformSection
        cfg = PlatformSection.from_env(env={
            "AI4E_PLATFORM_RESULT_CACHE": "true",
            "AI4E_PLATFORM_CACHE_MAX_ENTRIES": "7",
            "AI4E_PLATFORM_CACHE_MAX_BYTES": "1024",
            "AI4E_PLATFORM_CACHE_TTL_SECONDS": "60",
        }).to_platform_config()
        assert cfg.result_cache is True
        assert cfg.cache_max_entries == 7
        assert cfg.cache_max_bytes == 1024
        assert cfg.cache_ttl_seconds == 60.0
        off = PlatformSection.from_env(env={}).to_platform_config()
        assert off.result_cache is False
