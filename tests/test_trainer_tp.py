"""Multi-device Trainer coverage: tensor-parallel + fsdp sharding.

Round-1 gap: the only dp×fsdp×tp exercise lived in the driver's
``dryrun_multichip`` gate; the suite itself never ran the Trainer on a
multi-device mesh. These tests keep that path covered fast (<30s total on the
virtual 8-device CPU mesh) and assert the actual shard layouts, mirroring the
megatron-style split of `ai4e_tpu/models/vit.py` TP_RULES.
"""

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from ai4e_tpu.models import VIT_TP_RULES, create_vit
from ai4e_tpu.models.vit import ViT
from ai4e_tpu.parallel import MeshSpec, make_mesh
from ai4e_tpu.train import Trainer, cross_entropy_loss


def _batch(mesh, n=4, image=16, classes=4):
    images = jax.device_put(
        np.random.default_rng(0).uniform(size=(n, image, image, 3))
        .astype(np.float32),
        NamedSharding(mesh, P(("dp", "fsdp"))))
    labels = jax.device_put(np.arange(n, dtype=np.int32) % classes,
                            NamedSharding(mesh, P(("dp", "fsdp"))))
    return images, labels


class TestTrainerTensorParallel:
    def test_dp_tp_step_shards_params(self):
        mesh = make_mesh(MeshSpec(dp=2, tp=2), devices=jax.devices()[:4])
        model, params = create_vit(image_size=16, patch=8, dim=32, depth=1,
                                   heads=2, num_classes=4)
        with mesh:
            trainer = Trainer(model.apply, params, mesh,
                              loss_fn=cross_entropy_loss,
                              tp_rules=VIT_TP_RULES)
            images, labels = _batch(mesh)
            loss = trainer.train_step(images, labels)
        assert np.isfinite(loss)

        p = trainer.params["params"]["block0"]
        qkv = p["attn"]["qkv"]["kernel"]
        assert qkv.sharding.spec == P(None, "tp")
        assert qkv.sharding.shard_shape(qkv.shape)[-1] == qkv.shape[-1] // 2
        out = p["attn"]["out"]["kernel"]
        assert out.sharding.spec == P("tp")  # trailing Nones normalized away
        assert out.sharding.shard_shape(out.shape)[0] == out.shape[0] // 2
        # optimizer state inherits the param shardings (optax tree maps
        # under jit preserve placement)
        mu_qkv = trainer.opt_state[0].mu["params"]["block0"]["attn"]["qkv"][
            "kernel"]
        assert mu_qkv.sharding.spec == P(None, "tp")

    def test_dp_fsdp_tp_step_runs(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2),
                         devices=jax.devices()[:8])
        model, params = create_vit(image_size=16, patch=8, dim=32, depth=1,
                                   heads=2, num_classes=4)
        with mesh:
            trainer = Trainer(model.apply, params, mesh,
                              loss_fn=cross_entropy_loss,
                              tp_rules=VIT_TP_RULES)
            images, labels = _batch(mesh, n=8)
            first = trainer.train_step(images, labels)
            second = trainer.train_step(images, labels)
        assert np.isfinite(first) and np.isfinite(second)
        # optimizing the same batch twice must reduce its loss
        assert second < first

    def test_tp_matches_single_device(self):
        """TP is a layout change, not a math change: one train step on a
        dp=1,tp=2 mesh must produce the same loss as single-device, up to
        float tolerance."""
        model = ViT(num_classes=4, patch=8, dim=32, depth=1, heads=2,
                    dtype=jnp.float32)
        params = model.init(jax.random.PRNGKey(0),
                            jnp.zeros((1, 16, 16, 3)))
        images = np.random.default_rng(1).uniform(
            size=(4, 16, 16, 3)).astype(np.float32)
        labels = np.asarray([0, 1, 2, 3], np.int32)

        losses = {}
        for name, spec, tp_rules in [
            ("single", MeshSpec(dp=1), None),
            ("tp", MeshSpec(tp=2), VIT_TP_RULES),
        ]:
            mesh = make_mesh(spec, devices=jax.devices()[:spec.size])
            with mesh:
                # train_step donates param buffers — each trainer needs its
                # own copy of the init tree
                trainer = Trainer(model.apply,
                                  jax.tree.map(jnp.array, params), mesh,
                                  loss_fn=cross_entropy_loss,
                                  tp_rules=tp_rules)
                losses[name] = [trainer.train_step(images, labels)
                                for _ in range(2)]
        np.testing.assert_allclose(losses["single"], losses["tp"],
                                   rtol=2e-5)
