"""``bench.py --wire auto`` resolution (the default-args wire policy).

The driver's round-end artifact of record is ``python bench.py`` with
default arguments; ``--wire auto`` makes that run ride the fastest wire the
archive holds TPU-certified evidence for (e.g. the dct wire, once a tunnel
window captures ``landcover_dct`` faster than ``landcover_yuv``), while
staying on the r3-certified yuv420 wire when no such evidence exists.
Evidence rules pinned here:

- only ``device: tpu*`` captures certify (a CPU fallback JSON must never
  decide the production wire);
- rounds never mix (tunnel bandwidth shifts between rounds, so only
  same-window captures are comparable) — the newest round whose certified
  cells include the yuv420 fallback cell decides, so every decision is an
  intra-round comparison;
- the decision is recorded in the bench JSON (``wire_auto`` provenance).
"""

import importlib.util
import json
from pathlib import Path

_spec = importlib.util.spec_from_file_location(
    "bench", Path(__file__).resolve().parent.parent / "bench.py")
bench = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(bench)


def _write(root: Path, rdir: str, cell: str, device: str, value):
    d = root / rdir
    d.mkdir(parents=True, exist_ok=True)
    (d / f"{cell}.json").write_text(json.dumps(
        {"metric": "m", "value": value, "unit": "req/s", "device": device}))


class TestResolveAutoWire:
    def test_empty_archive_falls_back_to_yuv420(self, tmp_path):
        wire, prov = bench.resolve_auto_wire("landcover", str(tmp_path))
        assert wire == "yuv420"
        assert prov["requested"] == "auto"
        assert prov["decided_by"] == "default"

    def test_certified_dct_beats_yuv(self, tmp_path):
        _write(tmp_path, "r5-tpu", "landcover_yuv", "tpu:v5e", 170.8)
        _write(tmp_path, "r5-tpu", "landcover_dct", "tpu:v5e", 500.0)
        wire, prov = bench.resolve_auto_wire("landcover", str(tmp_path))
        assert wire == "dct"
        assert prov["decided_by"].endswith("landcover_dct.json")
        assert prov["value"] == 500.0

    def test_slower_dct_keeps_yuv(self, tmp_path):
        _write(tmp_path, "r5-tpu", "landcover_yuv", "tpu:v5e", 170.8)
        _write(tmp_path, "r5-tpu", "landcover_dct", "tpu:v5e", 120.0)
        wire, _ = bench.resolve_auto_wire("landcover", str(tmp_path))
        assert wire == "yuv420"

    def test_cpu_capture_never_certifies(self, tmp_path):
        _write(tmp_path, "r5-tpu", "landcover_dct", "cpu:cpux1", 999.0)
        wire, prov = bench.resolve_auto_wire("landcover", str(tmp_path))
        assert wire == "yuv420"
        assert prov["decided_by"] == "default"

    def test_rounds_do_not_mix(self, tmp_path):
        # r4 certified a blazing dct cell, but r5 (newer) has evidence of
        # its own — the newer round's regime decides, alone.
        _write(tmp_path, "r4-tpu", "landcover_dct", "tpu:v5e", 900.0)
        _write(tmp_path, "r5-tpu", "landcover_yuv", "tpu:v5e", 100.0)
        wire, prov = bench.resolve_auto_wire("landcover", str(tmp_path))
        assert wire == "yuv420"
        assert "r5-tpu" in prov["decided_by"]

    def test_older_round_decides_when_newer_is_empty(self, tmp_path):
        _write(tmp_path, "r3-tpu", "landcover_yuv", "tpu:v5e", 170.8)
        _write(tmp_path, "r3-tpu", "landcover", "tpu:v5e", 103.8)
        (tmp_path / "r5-tpu").mkdir()  # probe log only, no captures
        wire, prov = bench.resolve_auto_wire("landcover", str(tmp_path))
        assert wire == "yuv420"
        assert "r3-tpu" in prov["decided_by"]

    def test_round_ordering_is_numeric(self, tmp_path):
        _write(tmp_path, "r9-tpu", "species_yuv", "tpu:v5e", 100.0)
        _write(tmp_path, "r10-tpu", "species_yuv", "tpu:v5e", 40.0)
        _write(tmp_path, "r10-tpu", "species_dct", "tpu:v5e", 50.0)
        wire, prov = bench.resolve_auto_wire("species", str(tmp_path))
        assert wire == "dct"  # r10 > r9 despite lexicographic order
        assert "r10-tpu" in prov["decided_by"]

    def test_partial_window_cannot_promote_dct_alone(self, tmp_path):
        # The matrix runs species_dct before species_yuv; a window dying
        # between them leaves a round with dct evidence but no opponent.
        # Such a round must neither promote dct nor shadow r3's complete
        # comparison.
        _write(tmp_path, "r5-tpu", "species_dct", "tpu:v5e", 999.0)
        _write(tmp_path, "r3-tpu", "species_yuv", "tpu:v5e", 334.4)
        _write(tmp_path, "r3-tpu", "species", "tpu:v5e", 240.9)
        wire, prov = bench.resolve_auto_wire("species", str(tmp_path))
        assert wire == "yuv420"
        assert "r3-tpu" in prov["decided_by"]

    def test_invalid_json_ignored(self, tmp_path):
        d = tmp_path / "r5-tpu"
        d.mkdir()
        (d / "landcover_dct.json").write_text("{not json")
        _write(tmp_path, "r5-tpu", "landcover_yuv", "tpu:v5e", 170.8)
        wire, _ = bench.resolve_auto_wire("landcover", str(tmp_path))
        assert wire == "yuv420"

    def test_models_without_cells_pin_yuv420(self, tmp_path):
        for model in ("mixed", "echo", "longcontext"):
            wire, prov = bench.resolve_auto_wire(model, str(tmp_path))
            assert wire == "yuv420"
            assert prov["decided_by"] == "default"

    def test_real_archive_resolves_today(self):
        # Against the committed archive: r5 has no captures yet and r3
        # certified landcover_yuv at 170.79 — auto must stay on yuv420
        # until a window certifies something faster.
        wire, prov = bench.resolve_auto_wire("landcover")
        assert wire in ("yuv420", "dct")
        if wire == "yuv420" and prov["decided_by"] != "default":
            assert "landcover_yuv.json" in prov["decided_by"]

    def test_megadetector_cells_use_matrix_names(self, tmp_path):
        _write(tmp_path, "r5-tpu", "megadet_dct", "tpu:v5e", 80.0)
        _write(tmp_path, "r5-tpu", "megadet_yuv", "tpu:v5e", 60.0)
        wire, _ = bench.resolve_auto_wire("megadetector", str(tmp_path))
        assert wire == "dct"
