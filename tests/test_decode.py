"""Continuous-batching decode engine (runtime/decode.py + kvcache.py,
docs/streaming.md).

Three layers:

- **engine scheduling over a fake backend** (no JAX): iteration-level
  joins, backpressure, per-step deadline sweeps, cancellation,
  hot-reload re-prefill, slot conservation — plus THE acceptance
  property: a request arriving mid-decode of a long sequence receives
  its first token before that sequence finishes (and provably does NOT
  under the whole-batch baseline);
- **device path** (JAX): the KV-cache step function's correctness
  oracle — token-by-token decode must equal greedy re-prefill over the
  growing history — and the AOT-warm discipline (no serving-path
  compile);
- **metric identity**: constructing no engine registers no
  ``ai4e_decode_*`` series — the decode-engine-off worker's /metrics
  exposition is byte-identical (the PR 13 ladder discipline).
"""

import asyncio
import json
import time

import pytest

from ai4e_tpu.admission.deadline import DeadlineExceeded
from ai4e_tpu.taskstore import APITask
from ai4e_tpu.metrics.registry import MetricsRegistry
from ai4e_tpu.runtime.decode import (DecodeEngine, DecodeSaturated,
                                     SlotError, SlotPool)


class FakeBackend:
    """Deterministic decode backend: token ids count up from the last
    prompt token; ``step_s`` simulates device time so latency ordering
    (TTFT vs remaining decode) is measurable."""

    def __init__(self, slots=2, max_len=64, eos_id=None, step_s=0.0,
                 name="lm"):
        self.slots = slots
        self.max_len = max_len
        self.eos_id = eos_id
        self.name = name
        self.step_s = step_s
        self.params_version = 1
        self.resets = 0
        self.prefills = []
        self.steps = 0

    def reset_cache(self):
        self.resets += 1

    def prefill_into(self, slot, tokens):
        if self.step_s:
            time.sleep(self.step_s)
        self.prefills.append((slot, tuple(tokens)))
        return int(tokens[-1]) + 1

    def step(self, tokens, positions, active):
        if self.step_s:
            time.sleep(self.step_s)
        self.steps += 1
        return [int(t) + 1 for t in tokens]


def run(coro):
    return asyncio.run(coro)


async def wait_until(cond, timeout=10.0):
    deadline = time.perf_counter() + timeout
    while True:
        if cond():
            return
        assert time.perf_counter() < deadline, "condition not reached"
        await asyncio.sleep(0.001)


class TestSlotPool:
    def test_acquire_release_conservation(self):
        pool = SlotPool(3)
        a, b = pool.acquire(), pool.acquire()
        assert {a, b} == {0, 1}
        pool.release(a)
        assert pool.free_count == 2 and pool.busy_count == 1
        pool.check_conservation()

    def test_exhaustion_returns_none(self):
        pool = SlotPool(1)
        assert pool.acquire() == 0
        assert pool.acquire() is None

    def test_double_release_raises(self):
        pool = SlotPool(2)
        s = pool.acquire()
        pool.release(s)
        with pytest.raises(SlotError):
            pool.release(s)

    def test_foreign_release_raises(self):
        pool = SlotPool(2)
        with pytest.raises(SlotError):
            pool.release(1)


class TestEngineScheduling:
    def test_generates_and_streams_tokens(self):
        async def main():
            backend = FakeBackend(slots=2)
            engine = DecodeEngine(backend, metrics=MetricsRegistry())
            await engine.start()
            chunks = []
            out = await engine.submit([5, 6], 4,
                                      on_token=lambda i, t: chunks.append(
                                          (i, t)))
            await engine.stop()
            return out, chunks, backend

        out, chunks, backend = run(main())
        # Prefill emits 7; each step increments the last token.
        assert out == [7, 8, 9, 10]
        assert chunks == [(0, 7), (1, 8), (2, 9), (3, 10)]
        assert backend.prefills[0] == (0, (5, 6))

    def test_eos_finishes_early_and_frees_slot(self):
        async def main():
            backend = FakeBackend(slots=1, eos_id=9)
            engine = DecodeEngine(backend, metrics=MetricsRegistry())
            await engine.start()
            out = await engine.submit([6], 64)
            await engine.stop()
            return out

        assert run(main()) == [7, 8, 9]  # stops AT the eos token

    def test_backpressure_raises_decode_saturated(self):
        async def main():
            backend = FakeBackend(slots=1)
            engine = DecodeEngine(backend, max_pending=1,
                                  metrics=MetricsRegistry())
            # Engine not started: submissions stay queued.
            first = asyncio.ensure_future(engine.submit([1], 2))
            await asyncio.sleep(0)
            with pytest.raises(DecodeSaturated):
                await engine.submit([1], 2)
            first.cancel()
            return True

        assert run(main())

    def test_prompt_must_fit_kv_cache(self):
        async def main():
            engine = DecodeEngine(FakeBackend(slots=1, max_len=4),
                                  metrics=MetricsRegistry())
            with pytest.raises(ValueError):
                await engine.submit([1, 2, 3, 4], 2)

        run(main())

    def test_context_full_finishes_sequence(self):
        async def main():
            backend = FakeBackend(slots=1, max_len=5)
            engine = DecodeEngine(backend, metrics=MetricsRegistry())
            await engine.start()
            # Prompt of 3 + KV length 5: prefill token (position 3) then
            # 2 steps fill the cache → 3 tokens, not the 64 requested.
            out = await engine.submit([1, 2, 3], 64)
            await engine.stop()
            return out

        assert len(run(main())) == 3

    def test_late_joiner_streams_before_running_sequence_finishes(self):
        """THE acceptance property: a request arriving mid-decode of a
        long sequence gets its first chunk while that sequence is still
        decoding — its TTFT is smaller than the remaining decode time of
        the running sequence. The whole-batch baseline provably inverts
        this (the joiner waits for the full drain)."""

        async def drive(continuous):
            backend = FakeBackend(slots=2, step_s=0.002)
            engine = DecodeEngine(backend, continuous=continuous,
                                  metrics=MetricsRegistry())
            await engine.start()
            stamps = {}

            long_task = asyncio.ensure_future(engine.submit([1], 60))
            # Let the long sequence get well into its decode.
            await wait_until(lambda: backend.prefills and backend.steps >= 5)
            t_join = time.perf_counter()
            joiner = await engine.submit(
                [40], 3,
                on_token=lambda i, t: stamps.setdefault(
                    "first", time.perf_counter()))
            t_long_done_floor = time.perf_counter()
            await long_task
            t_long_done = max(time.perf_counter(), t_long_done_floor)
            await engine.stop()
            ttft = stamps["first"] - t_join
            remaining = t_long_done - t_join
            return ttft, remaining, len(joiner)

        ttft, remaining, n = run(drive(continuous=True))
        assert n == 3
        assert ttft < remaining, (
            f"continuous batching must stream the late joiner before the "
            f"running sequence finishes: TTFT {ttft * 1e3:.1f}ms vs "
            f"{remaining * 1e3:.1f}ms remaining")

        async def whole_batch():
            backend = FakeBackend(slots=2, step_s=0.002)
            engine = DecodeEngine(backend, continuous=False,
                                  metrics=MetricsRegistry())
            await engine.start()
            stamps = {}
            long_done = {}

            long_task = asyncio.ensure_future(engine.submit([1], 30))
            long_task.add_done_callback(
                lambda _: long_done.setdefault("t", time.perf_counter()))
            await wait_until(lambda: backend.steps >= 5)
            await engine.submit(
                [40], 3,
                on_token=lambda i, t: stamps.setdefault(
                    "first", time.perf_counter()))
            await long_task
            await engine.stop()
            return stamps["first"], long_done["t"]

        t_first, t_long_done = run(whole_batch())
        assert t_first >= t_long_done, (
            "whole-batch baseline must NOT admit the joiner before the "
            "running batch drains")

    def test_deadline_sweep_frees_slot_mid_decode(self):
        async def main():
            # 5 ms per device call: the 10k-token budget cannot finish
            # inside the 50 ms deadline — the sweep MUST fire mid-decode.
            backend = FakeBackend(slots=1, step_s=0.005)
            reg = MetricsRegistry()
            engine = DecodeEngine(backend, metrics=reg)
            await engine.start()
            with pytest.raises(DeadlineExceeded):
                # Deadline passes mid-decode (the sequence wants 10k
                # tokens); the per-step sweep retires it and frees the
                # slot instead of completing late.
                await engine.submit([1], 10_000,
                                    deadline_at=time.time() + 0.05)
            assert engine.pool.free_count == 1
            expired = reg.counter("ai4e_admission_expired_total")
            assert expired.value(hop="decode", priority="interactive") == 1
            await engine.stop()
            engine.pool.check_conservation()

        run(main())

    def test_cancelled_waiter_frees_slot(self):
        async def main():
            backend = FakeBackend(slots=1)
            engine = DecodeEngine(backend, metrics=MetricsRegistry())
            await engine.start()
            fut = asyncio.ensure_future(engine.submit([1], 10_000))
            await wait_until(lambda: engine.active_count)
            fut.cancel()
            await wait_until(lambda: not engine.active_count)
            assert engine.pool.free_count == 1
            await engine.stop()
            engine.pool.check_conservation()

        run(main())

    def test_hot_reload_invalidates_and_reprefills(self):
        async def main():
            backend = FakeBackend(slots=1, step_s=0.002)
            reg = MetricsRegistry()
            engine = DecodeEngine(backend, metrics=reg)
            await engine.start()
            fut = asyncio.ensure_future(engine.submit([1], 30))
            await wait_until(lambda: backend.steps >= 3)
            backend.params_version += 1  # hot reload lands
            out = await fut
            await engine.stop()
            return backend, reg, out

        backend, reg, out = run(main())
        assert len(out) == 30
        # The invalidation reset the pooled cache and re-prefilled the
        # active sequence from its prompt + generated history.
        assert backend.resets >= 1
        reprefill = [p for p in backend.prefills if len(p[1]) > 1]
        assert reprefill, "active sequence must re-prefill on reload"
        assert reg.counter("ai4e_decode_reprefills_total").value(
            model="lm") >= 1
        # The re-prefilled history starts with the original prompt.
        assert reprefill[0][1][0] == 1

    def test_metrics_registered_only_with_engine(self):
        reg = MetricsRegistry()
        assert not any(n.startswith("ai4e_decode_") for n in reg._metrics)
        DecodeEngine(FakeBackend(), metrics=reg)
        decode_metrics = {n for n in reg._metrics
                          if n.startswith("ai4e_decode_")}
        assert decode_metrics == {
            "ai4e_decode_ttft_seconds", "ai4e_decode_intertoken_seconds",
            "ai4e_decode_step_seconds", "ai4e_decode_slot_occupancy",
            "ai4e_decode_pending", "ai4e_decode_tokens_total",
            "ai4e_decode_sequences_total", "ai4e_decode_reprefills_total"}

    def test_default_worker_has_no_decode_metrics(self):
        """Decode-engine-off identity (acceptance): nothing in the
        default worker construction path registers a decode series —
        same discipline as the ladder-off exposition assertions."""
        from ai4e_tpu.runtime.batcher import MicroBatcher
        from types import SimpleNamespace
        reg = MetricsRegistry()
        MicroBatcher(SimpleNamespace(models={}), metrics=reg)
        text = reg.render_prometheus()
        assert "ai4e_decode_" not in text


# -- device path (JAX) -------------------------------------------------------


@pytest.fixture(scope="module")
def lm_runtime():
    from ai4e_tpu.runtime.kvcache import (PagedDecodeRuntime,
                                          build_lm_servable)
    servable = build_lm_servable(name="lm", vocab_size=64, max_len=24,
                                 dim=32, depth=2, heads=4)
    runtime = PagedDecodeRuntime(servable, slots=3, prompt_buckets=(4, 8))
    runtime.warm()
    return runtime


class TestPagedDecodeRuntime:
    def test_prompt_buckets_cover_max_len(self, lm_runtime):
        assert lm_runtime.prompt_buckets == (4, 8, 24)
        assert lm_runtime.bucket_for(3) == 4
        assert lm_runtime.bucket_for(9) == 24

    def test_decode_matches_greedy_reprefill_oracle(self, lm_runtime):
        """The KV-cache step path must produce exactly the tokens greedy
        re-prefill over the growing history produces — the correctness
        oracle for cache insert/step index arithmetic."""
        from ai4e_tpu.runtime.kvcache import PagedDecodeRuntime
        prompt = [3, 7, 11]
        tok = lm_runtime.prefill_into(1, prompt)
        got = [tok]
        position = len(prompt)
        for _ in range(5):
            out = lm_runtime.step(
                [0, got[-1], 0], [0, position, 0], [False, True, False])
            got.append(out[1])
            position += 1

        oracle_rt = PagedDecodeRuntime(lm_runtime.servable, slots=1,
                                       prompt_buckets=(24,))
        history = list(prompt)
        oracle = []
        for _ in range(6):
            t = oracle_rt.prefill_into(0, history)
            oracle.append(t)
            history.append(t)
        assert got == oracle

    def test_reload_params_bumps_version_and_checks_tree(self, lm_runtime):
        import jax
        before = lm_runtime.params_version
        new = jax.tree.map(lambda a: a, lm_runtime.servable.params)
        assert lm_runtime.reload_params(new) == before + 1
        with pytest.raises(ValueError):
            lm_runtime.reload_params({"params": {}})

    def test_engine_end_to_end_on_device(self, lm_runtime):
        async def main():
            engine = DecodeEngine(lm_runtime, metrics=MetricsRegistry())
            await engine.start()
            a, b = await asyncio.gather(engine.submit([1, 2, 3], 5),
                                        engine.submit([4, 5], 4))
            await engine.stop()
            engine.pool.check_conservation()
            return a, b

        a, b = run(main())
        assert len(a) == 5 and len(b) == 4
        assert all(0 <= t < 64 for t in a + b)


# -- worker serve_stream + SSE chunk flow ------------------------------------


class TestServeStream:
    def _worker(self, hub=None, engine=None):
        from ai4e_tpu.runtime.worker import InferenceWorker
        from ai4e_tpu.service.task_manager import LocalTaskManager
        from ai4e_tpu.taskstore import InMemoryTaskStore
        from types import SimpleNamespace
        store = InMemoryTaskStore()
        runtime = SimpleNamespace(models={})
        batcher = SimpleNamespace(pending_count=0, max_pending=8)
        worker = InferenceWorker("svc", runtime, batcher,
                                 task_manager=LocalTaskManager(store),
                                 metrics=MetricsRegistry(), store=store)
        if engine is not None:
            worker.serve_stream(engine, event_hub=hub)
        return worker, store

    def test_stream_endpoint_publishes_chunks_and_result(self):
        from ai4e_tpu.pipeline.events import TaskEventHub

        async def main():
            backend = FakeBackend(slots=2, name="lm")
            engine = DecodeEngine(backend, metrics=MetricsRegistry())
            hub = TaskEventHub(metrics=MetricsRegistry())
            worker, store = self._worker(hub=hub, engine=engine)
            await engine.start()
            store.upsert(APITask(task_id="t-1",
                                 endpoint="/lm-stream-async",
                                 body=b"", publish=False))
            handler = worker.service.endpoints["/lm-stream-async"].func
            body = json.dumps({"prompt": [5], "max_new_tokens": 3}).encode()
            await handler(taskId="t-1", body=body,
                          content_type="application/json")
            await engine.stop()
            return hub, store

        hub, store = run(main())
        events = hub.replay("t-1")
        chunks = [e for e in events if e["event"] == "chunk"]
        assert [c["data"]["data"]["token"] for c in chunks] == [6, 7, 8]
        assert all(c["data"]["stage"] == "lm" for c in chunks)
        task = store.get("t-1")
        assert task.canonical_status == "completed"
        result, _ = store.get_result("t-1")
        assert json.loads(result) == {"tokens": [6, 7, 8], "count": 3}

    def test_bad_input_fails_task_not_engine(self):
        async def main():
            backend = FakeBackend(slots=1)
            engine = DecodeEngine(backend, metrics=MetricsRegistry())
            worker, store = self._worker(engine=engine)
            store.upsert(APITask(task_id="t-bad",
                                 endpoint="/lm-stream-async",
                                 body=b"", publish=False))
            handler = worker.service.endpoints["/lm-stream-async"].func
            await handler(taskId="t-bad", body=b'{"prompt": "nope"}',
                          content_type="application/json")
            return store

        store = run(main())
        assert store.get("t-bad").canonical_status == "failed"

    def test_saturated_engine_answers_503_at_admission(self):
        async def main():
            backend = FakeBackend(slots=1)
            engine = DecodeEngine(backend, max_pending=0,
                                  metrics=MetricsRegistry())
            worker, _ = self._worker(engine=engine)
            check = worker.service.endpoints[
                "/lm-stream-async"].admission_check
            return check()

        status, _, headers = run(main())
        assert status == 503
        # Every refusal names its retry horizon (docs/analysis.md AIL015).
        assert headers["Retry-After"] == "1"


# -- CLI wiring (AI4E_RUNTIME_DECODE_*) --------------------------------------


class TestCliDecodeWiring:
    MODELS = {
        "service_name": "w", "prefix": "v1/lm",
        "models": [
            {"family": "echo", "name": "echo", "size": 4, "buckets": [2]},
            {"family": "seqformer-lm", "name": "lm", "vocab_size": 32,
             "max_len": 32, "dim": 16, "depth": 1, "heads": 2,
             "eos_id": 2}]}

    def test_decode_enable_builds_engine_and_stream_endpoint(self):
        from ai4e_tpu.cli import build_worker
        from ai4e_tpu.config import FrameworkConfig
        config = FrameworkConfig()
        config.runtime.decode_enable = True
        config.runtime.kv_slots = 2
        config.runtime.decode_prompt_buckets = (4,)
        worker, _batcher, _tm = build_worker(config, dict(self.MODELS))
        assert len(worker.decode_engines) == 1
        engine = worker.decode_engines[0]
        assert engine.backend.slots == 2
        # Spec max_len wins over the kv_max_len default; the prompt
        # ladder is the knob's, with the covering top appended.
        assert engine.backend.max_len == 32
        assert engine.backend.prompt_buckets == (4, 32)
        assert engine.backend.eos_id == 2
        # The LM is NOT a batch servable…
        assert "lm" not in worker.runtime.models
        # …but IS a served streaming endpoint.
        assert "/lm-stream-async" in worker.service.endpoints
        assert worker._served["lm"]["stream_async"] == \
            "/v1/lm/lm-stream-async"

    def test_decode_off_skips_lm_specs(self):
        """Default knobs: no engine, no stream route, no LM in the batch
        registry — the decode-off worker is the pre-decode worker. (The
        /metrics byte-identity half lives in
        ``TestEngineScheduling.test_default_worker_has_no_decode_metrics``
        on an isolated registry — the cli path shares the process-default
        registry, which an earlier decode-on test legitimately used.)"""
        from ai4e_tpu.cli import build_worker
        from ai4e_tpu.config import FrameworkConfig
        worker, _batcher, _tm = build_worker(FrameworkConfig(),
                                             dict(self.MODELS))
        assert worker.decode_engines == []
        assert "/lm-stream-async" not in worker.service.endpoints
        assert "lm" not in worker.runtime.models


class TestLMHotReloadEndpoint:
    def test_reload_endpoint_reaches_decode_backend(self, tmp_path):
        """POST {prefix}/models/{lm}/reload must resolve streaming LMs
        (they never enter runtime.models) and bump params_version — the
        engine's KV-cache invalidation trigger."""
        from aiohttp.test_utils import TestClient, TestServer

        from ai4e_tpu.checkpoint import save_params
        from ai4e_tpu.runtime.kvcache import (PagedDecodeRuntime,
                                              build_lm_servable)

        async def main():
            lm = build_lm_servable(name="lm", vocab_size=16, max_len=16,
                                   dim=16, depth=1, heads=2)
            backend = PagedDecodeRuntime(lm, slots=1, prompt_buckets=(4,))
            engine = DecodeEngine(backend, metrics=MetricsRegistry())
            worker, _store = TestServeStream()._worker(engine=engine)
            ckpt = str(tmp_path / "lm-ckpt")
            save_params(ckpt, lm.params)
            client = TestClient(TestServer(worker.service.app))
            await client.start_server()
            try:
                resp = await client.post("/v1/models/lm/reload",
                                         json={"checkpoint": ckpt})
                body = await resp.json()
                missing = await client.post("/v1/models/nope/reload",
                                            json={"checkpoint": ckpt})
                return resp.status, body, missing.status, backend
            finally:
                await client.close()

        status, body, missing, backend = run(main())
        assert status == 200, body
        assert body["params_version"] == 2
        assert backend.params_version == 2
        assert body["checkpoint"].endswith("lm-ckpt")
        assert missing == 404

    def test_oversized_prompt_fails_task_as_bad_input(self):
        async def main():
            backend = FakeBackend(slots=1, max_len=4)
            engine = DecodeEngine(backend, metrics=MetricsRegistry())
            worker, store = TestServeStream()._worker(engine=engine)
            store.upsert(APITask(task_id="t-big",
                                 endpoint="/lm-stream-async",
                                 body=b"", publish=False))
            handler = worker.service.endpoints["/lm-stream-async"].func
            await handler(
                taskId="t-big",
                body=json.dumps({"prompt": [1, 2, 3, 4, 5],
                                 "max_new_tokens": 2}).encode(),
                content_type="application/json")
            return store.get("t-big")

        task = run(main())
        assert task.canonical_status == "failed"
        assert "bad input" in task.status
