"""Checkpoint factory tests (VERDICT r1 missing #1): deterministic synthetic
training must move each family from chance to competence, save through the
orbax path, and restore into a servable whose *behavior* shows the trained
weights — the full weights-distribution loop the reference handled by baking
weights into container images (prod-values.yaml:35-36)."""

import numpy as np

from ai4e_tpu.checkpoint import load_params
from ai4e_tpu.runtime import build_servable
from ai4e_tpu.train.make_checkpoints import (
    landcover_batch,
    make_checkpoint,
    species_batch,
    train_species,
)


class TestRecipesLearn:
    def test_species_trains_saves_and_serves(self, tmp_path):
        # The real species recipe at its fast step count (deterministic:
        # reaches 1.0 on the seeded task); restore into the resnet family
        # servable the deploy spec builds.
        entry = make_checkpoint("species", str(tmp_path), min_eval=0.85,
                                steps=65)
        assert entry["eval"]["accuracy"] >= 0.85

        servable = build_servable(
            "resnet", name="species", image_size=64, num_classes=8,
            stage_sizes=(2, 2, 2), width=32, buckets=(4,))
        random_params = servable.params
        servable.params = load_params(entry["path"], like=servable.params)

        img, lab = species_batch(np.random.default_rng(99), 16, 64)
        # The family ingests uint8 (fused on-device normalize back to the
        # [0,1] floats the recipe trained on) — the production wire format.
        img = np.clip(np.round(img * 255), 0, 255).astype(np.uint8)
        logits = np.asarray(servable.apply_fn(servable.params, img))
        acc = float((np.argmax(logits, -1) == lab).mean())
        assert acc >= 0.85, f"restored weights only {acc} on held-out data"
        # ...and the loaded weights are behaviorally distinct from init.
        rand_logits = np.asarray(servable.apply_fn(random_params, img))
        rand_acc = float((np.argmax(rand_logits, -1) == lab).mean())
        assert acc > rand_acc + 0.3

    def test_landcover_trains_above_chance(self, tmp_path):
        # Tiny UNet (widths must be passed identically at restore — the
        # kwargs contract models.json relies on; num_classes rides along in
        # the recipe's result kwargs).
        entry = make_checkpoint(
            "landcover", str(tmp_path), min_eval=0.7,
            steps=100, tile=32, batch=8, widths=(8, 16))
        assert entry["eval"]["pixel_accuracy"] >= 0.7
        assert entry["kwargs"]["num_classes"] == 4
        # Restored tree serves through the unet family (unfused path gives
        # logits directly) with the SAME behavior the factory measured: on
        # the factory's own eval batch (seed+1 convention) the servable must
        # reproduce the recorded pixel accuracy — restore fidelity, not a
        # second generalization claim (a tiny UNet's accuracy varies across
        # random scenes).
        servable = build_servable("unet", name="landcover", tile=32,
                                  widths=(8, 16), num_classes=4, buckets=(4,),
                                  fused_postprocess=False)
        servable.params = load_params(entry["path"], like=servable.params)
        img, lab = landcover_batch(np.random.default_rng(1), 8, 32)
        logits = np.asarray(servable.apply_fn(servable.params, img))
        acc = float((np.argmax(logits, -1) == lab).mean())
        assert abs(acc - entry["eval"]["pixel_accuracy"]) < 1e-3, (
            acc, entry["eval"])

    def test_longcontext_trains_and_restores_into_token_servable(
            self, tmp_path):
        # The marker-token task at toy geometry (the full recipe trains the
        # serving shape on TPU — seq_len/vocab are structural there). A
        # short schedule with a lowered gate proves trained-not-random +
        # restore fidelity without the full convergence cost in CI.
        kw = dict(seq_len=128, dim=32, depth=2, heads=2, vocab_size=256,
                  batch=16, attention="full")
        entry = make_checkpoint("longcontext", str(tmp_path), min_eval=0.5,
                                steps=100, **kw)
        assert entry["eval"]["accuracy"] >= 0.5
        assert entry["kwargs"]["vocab_size"] == 256  # structural, recorded

        servable = build_servable("seqformer", name="longcontext",
                                  buckets=(4,), num_classes=16,
                                  **{k: v for k, v in kw.items()
                                     if k != "batch"})
        random_params = servable.params
        servable.params = load_params(entry["path"], like=servable.params)
        from ai4e_tpu.train.make_checkpoints import longcontext_batch
        toks, lab = longcontext_batch(np.random.default_rng(77), 16, 128, 256)
        acc = float((np.argmax(np.asarray(
            servable.apply_fn(servable.params, toks)), -1) == lab).mean())
        rand = float((np.argmax(np.asarray(
            servable.apply_fn(random_params, toks)), -1) == lab).mean())
        assert acc >= 0.5 and acc > rand + 0.2, (acc, rand)

    def test_moe_trains_and_restores_under_capacity_dispatch(self, tmp_path):
        # Trains dense, gates on the capacity dispatch it will serve — the
        # param tree is dispatch-independent, so restore must reproduce the
        # gated behavior through the capacity servable.
        kw = dict(seq_len=128, dim=32, heads=1, num_experts=4,
                  vocab_size=256, batch=16)
        entry = make_checkpoint("moe", str(tmp_path), min_eval=0.5,
                                steps=100, **kw)
        assert entry["eval"]["accuracy"] >= 0.5
        assert entry["kwargs"]["dispatch"] == "capacity"

        servable = build_servable(
            "moe", name="moe", seq_len=128, dim=32, heads=1, num_experts=4,
            vocab_size=256, num_classes=16, dispatch="capacity",
            attention="full", buckets=(4,))
        random_params = servable.params
        servable.params = load_params(entry["path"], like=servable.params)
        from ai4e_tpu.train.make_checkpoints import longcontext_batch
        toks, lab = longcontext_batch(np.random.default_rng(88), 16, 128, 256)
        acc = float((np.argmax(np.asarray(
            servable.apply_fn(servable.params, toks)), -1) == lab).mean())
        rand = float((np.argmax(np.asarray(
            servable.apply_fn(random_params, toks)), -1) == lab).mean())
        assert acc >= 0.5 and acc > rand + 0.2, (acc, rand)

    def test_unconverged_training_is_refused(self, tmp_path):
        import pytest

        with pytest.raises(AssertionError, match="below"):
            make_checkpoint("species", str(tmp_path), min_eval=0.99,
                            steps=1, image_size=32, batch=8,
                            stage_sizes=(1,), width=8)


class TestDeterminism:
    def test_same_seed_same_weights(self):
        a = train_species(steps=3, image_size=32, batch=8,
                          stage_sizes=(1,), width=8)
        b = train_species(steps=3, image_size=32, batch=8,
                          stage_sizes=(1,), width=8)
        la = jax_leaves(a["params"])
        lb = jax_leaves(b["params"])
        assert len(la) == len(lb)
        for x, y in zip(la, lb):
            np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def jax_leaves(tree):
    import jax
    return jax.tree_util.tree_leaves(tree)


class TestTrainAutoAttention:
    """`train-auto` picks the training attention per backend: the
    differentiable flash kernel on TPU (fresh-clone window training), XLA's
    materialised attention on CPU CI. Explicit strategies pass through."""

    def test_cpu_resolves_to_full(self):
        from ai4e_tpu.train.make_checkpoints import resolve_train_attention
        assert resolve_train_attention("train-auto") == "full"

    def test_tpu_resolves_to_flash(self, monkeypatch):
        import jax

        from ai4e_tpu.train.make_checkpoints import resolve_train_attention
        monkeypatch.setattr(jax, "default_backend", lambda: "tpu")
        assert resolve_train_attention("train-auto") == "flash"

    def test_explicit_strategy_passes_through(self):
        from ai4e_tpu.train.make_checkpoints import resolve_train_attention
        for strategy in ("full", "flash", "ring"):
            assert resolve_train_attention(strategy) == strategy
