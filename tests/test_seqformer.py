"""Long-context serving tests — SeqFormer with ring/Ulysses sequence
parallelism over the mesh's sp axis (``models/seqformer.py``; the long-context
slot SURVEY.md §5 marks absent in the reference)."""

import io

import jax
import numpy as np
import pytest

from ai4e_tpu.models import create_seqformer
from ai4e_tpu.parallel import MeshSpec, make_mesh
from ai4e_tpu.runtime import ModelRuntime, build_servable

S, F = 256, 16


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh(MeshSpec(dp=2, sp=4))


class TestCorrectness:
    def test_ring_matches_full_attention(self, sp_mesh):
        """Same params, same input: sequence-parallel attention must produce
        the same logits as plain full attention."""
        model_sp, params = create_seqformer(
            seq_len=S, input_dim=F, dim=32, depth=2, heads=4, num_classes=8,
            mesh=sp_mesh, attention="ring")
        model_full, _ = create_seqformer(
            seq_len=S, input_dim=F, dim=32, depth=2, heads=4, num_classes=8,
            attention="full")
        x = np.random.default_rng(0).standard_normal((2, S, F)).astype(np.float32)
        got = np.asarray(model_sp.apply(params, x))
        expected = np.asarray(model_full.apply(params, x))
        np.testing.assert_allclose(got, expected, rtol=2e-2, atol=2e-2)

    def test_ulysses_matches_full_attention(self, sp_mesh):
        model_sp, params = create_seqformer(
            seq_len=S, input_dim=F, dim=32, depth=1, heads=4, num_classes=8,
            mesh=sp_mesh, attention="ulysses")
        model_full, _ = create_seqformer(
            seq_len=S, input_dim=F, dim=32, depth=1, heads=4, num_classes=8,
            attention="full")
        x = np.random.default_rng(1).standard_normal((2, S, F)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(model_sp.apply(params, x)),
            np.asarray(model_full.apply(params, x)), rtol=2e-2, atol=2e-2)

    def test_seq_len_must_divide_sp(self, sp_mesh):
        with pytest.raises(ValueError, match="not divisible"):
            create_seqformer(seq_len=S + 1, input_dim=F, mesh=sp_mesh,
                             attention="ring")

    def test_parallel_attention_requires_sp_mesh(self):
        with pytest.raises(ValueError, match="sp > 1"):
            create_seqformer(seq_len=S, input_dim=F, attention="ring")


class TestServing:
    def test_family_serves_on_sp_mesh(self, sp_mesh):
        """The seqformer family registers on a dp×sp mesh and scores a long
        sequence end-to-end through the runtime."""
        runtime = ModelRuntime(mesh=sp_mesh)
        servable = build_servable(
            "seqformer", name="longcontext", seq_len=S, input_dim=F, dim=32,
            depth=1, heads=4, num_classes=8, buckets=(2,), mesh=sp_mesh)
        runtime.register(servable)
        runtime.warmup()

        seq = np.random.default_rng(2).standard_normal((S, F)).astype(np.float32)
        buf = io.BytesIO()
        np.save(buf, seq)
        example = servable.preprocess(buf.getvalue(), "application/octet-stream")
        bucket = servable.bucket_for(1)
        batch = np.zeros((bucket, S, F), np.float32)
        batch[0] = example
        out = runtime.run_batch("longcontext", batch)
        result = servable.postprocess(
            jax.tree_util.tree_map(lambda a: a[0], out))
        assert 0 <= result["class_id"] < 8
        assert 0.0 < result["confidence"] <= 1.0


class TestMeshFromConfig:
    def test_env_axes_build_mesh(self):
        from ai4e_tpu.cli import _mesh_from_config
        from ai4e_tpu.config import RuntimeSection

        rt = RuntimeSection(sp=4)
        mesh = _mesh_from_config(rt)
        assert mesh.shape["sp"] == 4
        assert mesh.shape["dp"] == jax.device_count() // 4

        assert _mesh_from_config(RuntimeSection()) is None
