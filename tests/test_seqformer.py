"""Long-context serving tests — SeqFormer with ring/Ulysses sequence
parallelism over the mesh's sp axis (``models/seqformer.py``; the long-context
slot SURVEY.md §5 marks absent in the reference)."""

import io

import jax
import numpy as np
import pytest

from ai4e_tpu.models import create_seqformer
from ai4e_tpu.parallel import MeshSpec, make_mesh
from ai4e_tpu.runtime import ModelRuntime, build_servable

S, F = 256, 16


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh(MeshSpec(dp=2, sp=4))


class TestCorrectness:
    def test_ring_matches_full_attention(self, sp_mesh):
        """Same params, same input: sequence-parallel attention must produce
        the same logits as plain full attention."""
        model_sp, params = create_seqformer(
            seq_len=S, input_dim=F, dim=32, depth=2, heads=4, num_classes=8,
            mesh=sp_mesh, attention="ring")
        model_full, _ = create_seqformer(
            seq_len=S, input_dim=F, dim=32, depth=2, heads=4, num_classes=8,
            attention="full")
        x = np.random.default_rng(0).standard_normal((2, S, F)).astype(np.float32)
        got = np.asarray(model_sp.apply(params, x))
        expected = np.asarray(model_full.apply(params, x))
        np.testing.assert_allclose(got, expected, rtol=2e-2, atol=2e-2)

    def test_ulysses_matches_full_attention(self, sp_mesh):
        model_sp, params = create_seqformer(
            seq_len=S, input_dim=F, dim=32, depth=1, heads=4, num_classes=8,
            mesh=sp_mesh, attention="ulysses")
        model_full, _ = create_seqformer(
            seq_len=S, input_dim=F, dim=32, depth=1, heads=4, num_classes=8,
            attention="full")
        x = np.random.default_rng(1).standard_normal((2, S, F)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(model_sp.apply(params, x)),
            np.asarray(model_full.apply(params, x)), rtol=2e-2, atol=2e-2)

    def test_seq_len_must_divide_sp(self, sp_mesh):
        with pytest.raises(ValueError, match="not divisible"):
            create_seqformer(seq_len=S + 1, input_dim=F, mesh=sp_mesh,
                             attention="ring")

    def test_parallel_attention_requires_sp_mesh(self):
        with pytest.raises(ValueError, match="sp > 1"):
            create_seqformer(seq_len=S, input_dim=F, attention="ring")


class TestServing:
    def test_family_serves_on_sp_mesh(self, sp_mesh):
        """The seqformer family registers on a dp×sp mesh and scores a long
        sequence end-to-end through the runtime."""
        runtime = ModelRuntime(mesh=sp_mesh)
        servable = build_servable(
            "seqformer", name="longcontext", seq_len=S, input_dim=F, dim=32,
            depth=1, heads=4, num_classes=8, buckets=(2,), mesh=sp_mesh)
        runtime.register(servable)
        runtime.warmup()

        seq = np.random.default_rng(2).standard_normal((S, F)).astype(np.float32)
        buf = io.BytesIO()
        np.save(buf, seq)
        example = servable.preprocess(buf.getvalue(), "application/octet-stream")
        bucket = servable.bucket_for(1)
        # Build the batch in the servable's wire dtype (f16 by default) so
        # this exercises the program the production batcher actually runs.
        batch = np.zeros((bucket, S, F), servable.input_dtype)
        batch[0] = example
        out = runtime.run_batch("longcontext", batch)
        result = servable.postprocess(
            jax.tree_util.tree_map(lambda a: a[0], out))
        assert 0 <= result["class_id"] < 8
        assert 0.0 < result["confidence"] <= 1.0


class TestWireDtype:
    def test_f16_wire_default_casts_and_matches_f32(self):
        """The family's half-precision wire (its default) must accept f32
        client payloads, carry f16 examples, and score within bf16 noise of
        the f32-wire variant — the model computes bf16 either way."""
        kw = dict(seq_len=64, input_dim=8, dim=16, depth=1, heads=2,
                  num_classes=4, buckets=(1,), attention="full")
        f16 = build_servable("seqformer", name="lc16", **kw)
        f32 = build_servable("seqformer", name="lc32", wire_dtype="float32",
                             **kw)
        assert np.dtype(f16.input_dtype) == np.float16
        seq = np.random.default_rng(3).standard_normal((64, 8)).astype(
            np.float32)
        buf = io.BytesIO(); np.save(buf, seq)
        ex = f16.preprocess(buf.getvalue(), "application/octet-stream")
        assert ex.dtype == np.float16
        a = np.asarray(f16.apply_fn(f16.params, ex[None].astype(np.float16)))
        b = np.asarray(f32.apply_fn(f16.params, seq[None]))
        np.testing.assert_allclose(a, b, rtol=5e-2, atol=5e-2)

    def test_bad_wire_dtype_rejected(self):
        with pytest.raises(ValueError):
            build_servable("seqformer", name="bad", seq_len=64, input_dim=8,
                           wire_dtype="int8")

    def test_out_of_f16_range_payload_fails_that_task(self):
        """A narrowing f32→f16 cast must not silently turn 1e38 into inf
        (NaN scores downstream) — preprocess raises, failing one task."""
        sv = build_servable("seqformer", name="lcrange", seq_len=64,
                            input_dim=8, dim=16, depth=1, heads=2,
                            num_classes=4, buckets=(1,), attention="full")
        seq = np.zeros((64, 8), np.float32); seq[0, 0] = 1e38
        buf = io.BytesIO(); np.save(buf, seq)
        with pytest.raises(ValueError, match="range"):
            sv.preprocess(buf.getvalue(), "application/octet-stream")
        # NaN is reported as NaN, not as a bogus magnitude overflow.
        seq[0, 0] = np.nan
        buf = io.BytesIO(); np.save(buf, seq)
        with pytest.raises(ValueError, match="NaN"):
            sv.preprocess(buf.getvalue(), "application/octet-stream")
        # The batch-stack decode path shares the guard (worker.serve_batch
        # decodes via cast_image_payload).
        from ai4e_tpu.runtime.families import cast_image_payload
        with pytest.raises(ValueError, match="NaN"):
            cast_image_payload(seq[None], np.float16)


class TestTokenMode:
    """``vocab_size`` switches the family to (S,) token-id input with
    on-device embedding — the production long-context wire (2 B/token vs
    128 B/token of pre-embedded f16 features)."""

    KW = dict(seq_len=64, dim=16, depth=1, heads=2, num_classes=4,
              buckets=(1,), attention="full", vocab_size=100)

    def _payload(self, tokens):
        buf = io.BytesIO()
        np.save(buf, tokens)
        return buf.getvalue()

    def test_token_servable_scores_and_wire_is_2_bytes_per_token(self):
        sv = build_servable("seqformer", name="lctok", **self.KW)
        assert sv.input_shape == (64,)
        assert np.dtype(sv.input_dtype) == np.int32
        toks = np.random.default_rng(0).integers(
            0, 100, size=(64,), dtype=np.uint16)
        body = self._payload(toks)
        # uint16 npy wire: 128 header bytes + 2 bytes/token.
        assert len(body) <= 2 * 64 + 128
        ex = sv.preprocess(body, "application/octet-stream")
        assert ex.dtype == np.int32
        out = sv.postprocess(np.asarray(
            sv.apply_fn(sv.params, ex[None])[0]))
        assert 0 <= out["class_id"] < 4

    def test_embedding_actually_selects_rows(self):
        """Two sequences differing only in ids must embed differently, and
        identical ids identically — the Embed table is really indexed."""
        sv = build_servable("seqformer", name="lctok2", **self.KW)
        a = np.full((64,), 3, np.int32)
        b = np.full((64,), 7, np.int32)
        la = np.asarray(sv.apply_fn(sv.params, a[None]))
        lb = np.asarray(sv.apply_fn(sv.params, b[None]))
        assert not np.allclose(la, lb)
        np.testing.assert_allclose(
            la, np.asarray(sv.apply_fn(sv.params, a[None])))

    def test_out_of_range_and_float_payloads_fail_that_task(self):
        sv = build_servable("seqformer", name="lctok3", **self.KW)
        bad = np.full((64,), 100, np.int64)  # == vocab_size
        with pytest.raises(ValueError, match=r"\[0, 100\)"):
            sv.preprocess(self._payload(bad), "application/octet-stream")
        with pytest.raises(ValueError, match="integer"):
            sv.preprocess(self._payload(np.zeros((64,), np.float32)),
                          "application/octet-stream")
        with pytest.raises(ValueError, match="expected"):
            sv.preprocess(self._payload(np.zeros((32,), np.uint16)),
                          "application/octet-stream")

    def test_token_mode_rides_the_sp_mesh(self, sp_mesh):
        """Ring attention over sp composes with on-device embedding: the
        sharded token forward matches the single-device full-attention
        oracle with the same params."""
        model_sp, params = create_seqformer(
            seq_len=S, input_dim=F, dim=32, depth=1, heads=4, num_classes=8,
            mesh=sp_mesh, attention="ring", vocab_size=50)
        model_full, _ = create_seqformer(
            seq_len=S, input_dim=F, dim=32, depth=1, heads=4, num_classes=8,
            attention="full", vocab_size=50)
        toks = np.random.default_rng(4).integers(0, 50, size=(2, S),
                                                 dtype=np.int32)
        np.testing.assert_allclose(
            np.asarray(model_sp.apply(params, toks)),
            np.asarray(model_full.apply(params, toks)),
            rtol=2e-2, atol=2e-2)


class TestMeshFromConfig:
    def test_env_axes_build_mesh(self):
        from ai4e_tpu.cli import _mesh_from_config
        from ai4e_tpu.config import RuntimeSection

        rt = RuntimeSection(sp=4)
        mesh = _mesh_from_config(rt)
        assert mesh.shape["sp"] == 4
        assert mesh.shape["dp"] == jax.device_count() // 4

        assert _mesh_from_config(RuntimeSection()) is None
