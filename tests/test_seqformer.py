"""Long-context serving tests — SeqFormer with ring/Ulysses sequence
parallelism over the mesh's sp axis (``models/seqformer.py``; the long-context
slot SURVEY.md §5 marks absent in the reference)."""

import io

import jax
import numpy as np
import pytest

from ai4e_tpu.models import create_seqformer
from ai4e_tpu.parallel import MeshSpec, make_mesh
from ai4e_tpu.runtime import ModelRuntime, build_servable

S, F = 256, 16


@pytest.fixture(scope="module")
def sp_mesh():
    return make_mesh(MeshSpec(dp=2, sp=4))


class TestCorrectness:
    def test_ring_matches_full_attention(self, sp_mesh):
        """Same params, same input: sequence-parallel attention must produce
        the same logits as plain full attention."""
        model_sp, params = create_seqformer(
            seq_len=S, input_dim=F, dim=32, depth=2, heads=4, num_classes=8,
            mesh=sp_mesh, attention="ring")
        model_full, _ = create_seqformer(
            seq_len=S, input_dim=F, dim=32, depth=2, heads=4, num_classes=8,
            attention="full")
        x = np.random.default_rng(0).standard_normal((2, S, F)).astype(np.float32)
        got = np.asarray(model_sp.apply(params, x))
        expected = np.asarray(model_full.apply(params, x))
        np.testing.assert_allclose(got, expected, rtol=2e-2, atol=2e-2)

    def test_ulysses_matches_full_attention(self, sp_mesh):
        model_sp, params = create_seqformer(
            seq_len=S, input_dim=F, dim=32, depth=1, heads=4, num_classes=8,
            mesh=sp_mesh, attention="ulysses")
        model_full, _ = create_seqformer(
            seq_len=S, input_dim=F, dim=32, depth=1, heads=4, num_classes=8,
            attention="full")
        x = np.random.default_rng(1).standard_normal((2, S, F)).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(model_sp.apply(params, x)),
            np.asarray(model_full.apply(params, x)), rtol=2e-2, atol=2e-2)

    def test_seq_len_must_divide_sp(self, sp_mesh):
        with pytest.raises(ValueError, match="not divisible"):
            create_seqformer(seq_len=S + 1, input_dim=F, mesh=sp_mesh,
                             attention="ring")

    def test_parallel_attention_requires_sp_mesh(self):
        with pytest.raises(ValueError, match="sp > 1"):
            create_seqformer(seq_len=S, input_dim=F, attention="ring")


class TestServing:
    def test_family_serves_on_sp_mesh(self, sp_mesh):
        """The seqformer family registers on a dp×sp mesh and scores a long
        sequence end-to-end through the runtime."""
        runtime = ModelRuntime(mesh=sp_mesh)
        servable = build_servable(
            "seqformer", name="longcontext", seq_len=S, input_dim=F, dim=32,
            depth=1, heads=4, num_classes=8, buckets=(2,), mesh=sp_mesh)
        runtime.register(servable)
        runtime.warmup()

        seq = np.random.default_rng(2).standard_normal((S, F)).astype(np.float32)
        buf = io.BytesIO()
        np.save(buf, seq)
        example = servable.preprocess(buf.getvalue(), "application/octet-stream")
        bucket = servable.bucket_for(1)
        # Build the batch in the servable's wire dtype (f16 by default) so
        # this exercises the program the production batcher actually runs.
        batch = np.zeros((bucket, S, F), servable.input_dtype)
        batch[0] = example
        out = runtime.run_batch("longcontext", batch)
        result = servable.postprocess(
            jax.tree_util.tree_map(lambda a: a[0], out))
        assert 0 <= result["class_id"] < 8
        assert 0.0 < result["confidence"] <= 1.0


class TestWireDtype:
    def test_f16_wire_default_casts_and_matches_f32(self):
        """The family's half-precision wire (its default) must accept f32
        client payloads, carry f16 examples, and score within bf16 noise of
        the f32-wire variant — the model computes bf16 either way."""
        kw = dict(seq_len=64, input_dim=8, dim=16, depth=1, heads=2,
                  num_classes=4, buckets=(1,), attention="full")
        f16 = build_servable("seqformer", name="lc16", **kw)
        f32 = build_servable("seqformer", name="lc32", wire_dtype="float32",
                             **kw)
        assert np.dtype(f16.input_dtype) == np.float16
        seq = np.random.default_rng(3).standard_normal((64, 8)).astype(
            np.float32)
        buf = io.BytesIO(); np.save(buf, seq)
        ex = f16.preprocess(buf.getvalue(), "application/octet-stream")
        assert ex.dtype == np.float16
        a = np.asarray(f16.apply_fn(f16.params, ex[None].astype(np.float16)))
        b = np.asarray(f32.apply_fn(f16.params, seq[None]))
        np.testing.assert_allclose(a, b, rtol=5e-2, atol=5e-2)

    def test_bad_wire_dtype_rejected(self):
        with pytest.raises(ValueError):
            build_servable("seqformer", name="bad", seq_len=64, input_dim=8,
                           wire_dtype="int8")

    def test_out_of_f16_range_payload_fails_that_task(self):
        """A narrowing f32→f16 cast must not silently turn 1e38 into inf
        (NaN scores downstream) — preprocess raises, failing one task."""
        sv = build_servable("seqformer", name="lcrange", seq_len=64,
                            input_dim=8, dim=16, depth=1, heads=2,
                            num_classes=4, buckets=(1,), attention="full")
        seq = np.zeros((64, 8), np.float32); seq[0, 0] = 1e38
        buf = io.BytesIO(); np.save(buf, seq)
        with pytest.raises(ValueError, match="range"):
            sv.preprocess(buf.getvalue(), "application/octet-stream")
        # NaN is reported as NaN, not as a bogus magnitude overflow.
        seq[0, 0] = np.nan
        buf = io.BytesIO(); np.save(buf, seq)
        with pytest.raises(ValueError, match="NaN"):
            sv.preprocess(buf.getvalue(), "application/octet-stream")
        # The batch-stack decode path shares the guard (worker.serve_batch
        # decodes via cast_image_payload).
        from ai4e_tpu.runtime.families import cast_image_payload
        with pytest.raises(ValueError, match="NaN"):
            cast_image_payload(seq[None], np.float16)


class TestMeshFromConfig:
    def test_env_axes_build_mesh(self):
        from ai4e_tpu.cli import _mesh_from_config
        from ai4e_tpu.config import RuntimeSection

        rt = RuntimeSection(sp=4)
        mesh = _mesh_from_config(rt)
        assert mesh.shape["sp"] == 4
        assert mesh.shape["dp"] == jax.device_count() // 4

        assert _mesh_from_config(RuntimeSection()) is None
