"""HTTP task-store service + HttpTaskManager client tests — the multi-host
path (services on other hosts sharing one store, the reference's
CACHE_CONNECTOR_*_URI pattern)."""

import asyncio

import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.service.task_manager import HttpTaskManager
from ai4e_tpu.taskstore import InMemoryTaskStore
from ai4e_tpu.taskstore.http import make_app


def run(coro):
    return asyncio.run(coro)


async def manager_for(store):
    client = TestClient(TestServer(make_app(store)))
    await client.start_server()
    tm = HttpTaskManager(str(client.make_url("")), session=client.session)
    return client, tm


class TestHttpTaskManager:
    def test_add_and_poll(self):
        store = InMemoryTaskStore()

        async def main():
            client, tm = await manager_for(store)
            try:
                task = await tm.add_task("http://h/v1/api", b'{"x":1}')
                got = await tm.get_task_status(task["TaskId"])
                assert got["Status"] == "created"
                assert store.get(task["TaskId"]).body == b'{"x":1}'
            finally:
                await client.close()

        run(main())

    def test_binary_body_survives_json_roundtrip(self):
        # JPEG magic bytes are not valid UTF-8; surrogateescape must carry
        # them through the JSON wire format intact.
        store = InMemoryTaskStore()
        payload = b"\xff\xd8\xff\xe0\x00\x10JFIF\x00"

        async def main():
            client, tm = await manager_for(store)
            try:
                task = await tm.add_task("http://h/v1/api", payload)
                assert store.get(task["TaskId"]).body == payload
            finally:
                await client.close()

        run(main())

    def test_status_updates_are_atomic_server_side(self):
        store = InMemoryTaskStore()

        async def main():
            client, tm = await manager_for(store)
            try:
                task = await tm.add_task("http://h/v1/api", b"x")
                tid = task["TaskId"]
                await tm.update_task_status(tid, "running")
                await tm.complete_task(tid, "completed - ok")
                got = await tm.get_task_status(tid)
                assert got["Status"] == "completed - ok"
                assert store.get(tid).endpoint == "http://h/v1/api"  # preserved
            finally:
                await client.close()

        run(main())

    def test_update_unknown_task_raises_keyerror(self):
        store = InMemoryTaskStore()

        async def main():
            client, tm = await manager_for(store)
            try:
                with pytest.raises(KeyError):
                    await tm.update_task_status("no-such-task", "running")
            finally:
                await client.close()

        run(main())

    def test_get_unknown_task_returns_none(self):
        store = InMemoryTaskStore()

        async def main():
            client, tm = await manager_for(store)
            try:
                assert await tm.get_task_status("missing") is None
            finally:
                await client.close()

        run(main())

    def test_content_type_preserved_over_wire(self):
        store = InMemoryTaskStore()

        async def main():
            client, tm = await manager_for(store)
            try:
                from ai4e_tpu.taskstore import APITask
                task = APITask(endpoint="http://h/v1/api", body=b"\x00\x01",
                               content_type="image/jpeg")
                result = await tm._upsert(task)
                assert store.get(result["TaskId"]).content_type == "image/jpeg"
            finally:
                await client.close()

        run(main())

    def test_pipeline_over_http(self):
        store = InMemoryTaskStore()
        published = []
        store.set_publisher(published.append)

        async def main():
            client, tm = await manager_for(store)
            try:
                task = await tm.add_task("http://h/v1/detector", b"IMG",
                                         publish=True)
                tid = task["TaskId"]
                await tm.add_pipeline_task(tid, "http://h/v1/classifier")
                assert published[-1].body == b"IMG"  # original body replayed
                assert store.get(tid).endpoint_path == "/v1/classifier"
            finally:
                await client.close()

        run(main())


class TestDepthsEndpoint:
    def test_depths(self):
        store = InMemoryTaskStore()

        async def main():
            client, tm = await manager_for(store)
            try:
                await tm.add_task("http://h/v1/api", b"a")
                await tm.add_task("http://h/v1/api", b"b")
                resp = await client.get("/v1/taskstore/depths")
                depths = await resp.json()
                assert depths["/v1/api"]["created"] == 2
            finally:
                await client.close()

        run(main())


class TestBodyCap:
    """ADVICE r2 (medium): the taskstore surface often rides the gateway app,
    whose aiohttp cap is disabled — these handlers must bound their own
    buffering and refuse oversized writes with 413."""

    def test_oversized_result_rejected(self):
        store = InMemoryTaskStore()

        async def main():
            client = TestClient(TestServer(make_app(store,
                                                    max_body_bytes=1024,
                                                    max_result_bytes=2048)))
            await client.start_server()
            try:
                t = store.upsert(
                    __import__("ai4e_tpu.taskstore", fromlist=["APITask"])
                    .APITask(endpoint="http://h/v1/api", body=b"x"))
                resp = await client.post(
                    f"/v1/taskstore/result?taskId={t.task_id}",
                    data=b"\x00" * 4096)
                assert resp.status == 413
                assert store.get_result(t.task_id) is None
                # Within the cap still works.
                resp = await client.post(
                    f"/v1/taskstore/result?taskId={t.task_id}", data=b"ok")
                assert resp.status == 200
            finally:
                await client.close()

        run(main())

    def test_oversized_upsert_rejected(self):
        store = InMemoryTaskStore()

        async def main():
            client = TestClient(TestServer(make_app(store,
                                                    max_body_bytes=512)))
            await client.start_server()
            try:
                resp = await client.post("/v1/taskstore/upsert",
                                         data=b"{" + b" " * 2048 + b"}")
                assert resp.status == 413
            finally:
                await client.close()

        run(main())


class TestStreamingResults:
    def test_offloaded_result_streams_with_length(self, tmp_path):
        """Large (offloaded) results stream from the blob backend in chunks
        — never buffered whole in server memory — with an honest
        Content-Length; inline results ride the same path."""
        from ai4e_tpu.taskstore import APITask, FileResultBackend

        store = InMemoryTaskStore(
            result_backend=FileResultBackend(str(tmp_path / "blobs")),
            result_offload_threshold=1024)

        async def main():
            client = TestClient(TestServer(make_app(store)))
            await client.start_server()
            try:
                t = store.upsert(APITask(endpoint="http://h/v1/api",
                                         body=b"x"))
                big = bytes(range(256)) * 4096  # 1 MiB, offloaded
                store.set_result(t.task_id, big,
                                 content_type="application/octet-stream")
                resp = await client.get(
                    f"/v1/taskstore/result?taskId={t.task_id}")
                assert resp.status == 200
                assert resp.headers["Content-Length"] == str(len(big))
                assert await resp.read() == big

                store.set_result(t.task_id, b"tiny", stage="s")  # inline
                resp = await client.get(
                    f"/v1/taskstore/result?taskId={t.task_id}&stage=s")
                assert await resp.read() == b"tiny"
                # Absent results still 204 through the streaming path.
                resp = await client.get(
                    "/v1/taskstore/result?taskId=" + t.task_id + "&stage=no")
                assert resp.status == 204
            finally:
                await client.close()

        run(main())


class TestDirectResultStore:
    def test_worker_writes_blob_registers_pointer_store_serves(
            self, tmp_path):
        """Full direct-to-storage loop over HTTP: the worker-side
        DirectResultStore writes the shared mount and POSTs only a ref; the
        control-plane store then streams the blob to pollers."""
        from ai4e_tpu.service.task_manager import (DirectResultStore,
                                                   HttpResultStore)
        from ai4e_tpu.taskstore import APITask, FileResultBackend

        root = str(tmp_path / "shared")
        store = InMemoryTaskStore(result_backend=FileResultBackend(root))

        async def main():
            client = TestClient(TestServer(make_app(store)))
            await client.start_server()
            direct = DirectResultStore(
                root, HttpResultStore(str(client.make_url("")),
                                      session=client.session),
                threshold=64)
            try:
                t = store.upsert(APITask(endpoint="http://h/v1/api",
                                         body=b"x"))
                big = b"\x5a" * 4096
                await direct.set_result(t.task_id, big,
                                        "application/octet-stream")
                # The payload never crossed the HTTP surface; the store
                # serves it from the shared root.
                resp = await client.get(
                    f"/v1/taskstore/result?taskId={t.task_id}")
                assert await resp.read() == big
                assert resp.headers["Content-Type"] == (
                    "application/octet-stream")
                # Small results still upload inline.
                await direct.set_result(t.task_id, b"tiny", stage="s")
                got = await direct.get_result(t.task_id, stage="s")
                assert got == (b"tiny", "application/octet-stream") or \
                    got[0] == b"tiny"
            finally:
                await client.close()

        run(main())

    def test_ref_for_missing_blob_is_409(self, tmp_path):
        from ai4e_tpu.taskstore import APITask, FileResultBackend

        store = InMemoryTaskStore(
            result_backend=FileResultBackend(str(tmp_path / "b")))

        async def main():
            client = TestClient(TestServer(make_app(store)))
            await client.start_server()
            try:
                t = store.upsert(APITask(endpoint="http://h/v1/api",
                                         body=b"x"))
                import json as _json
                resp = await client.post(
                    "/v1/taskstore/result-ref",
                    data=_json.dumps({"TaskId": t.task_id}))
                assert resp.status == 409
            finally:
                await client.close()

        run(main())

    def test_dropped_ref_reaps_the_orphan_blob(self, tmp_path):
        """Control plane no longer knows the task (restart/eviction): the
        worker's blob must be reaped, not left on the shared mount forever."""
        import os

        from ai4e_tpu.service.task_manager import (DirectResultStore,
                                                   HttpResultStore)

        root = str(tmp_path / "shared")
        store = InMemoryTaskStore(result_backend=None)

        async def main():
            from ai4e_tpu.taskstore import FileResultBackend
            served = InMemoryTaskStore(
                result_backend=FileResultBackend(root))
            client = TestClient(TestServer(make_app(served)))
            await client.start_server()
            direct = DirectResultStore(
                root, HttpResultStore(str(client.make_url("")),
                                      session=client.session),
                threshold=8)
            try:
                await direct.set_result("no-such-task", b"B" * 64)
                assert os.listdir(root) == []  # orphan reaped
            finally:
                await client.close()

        run(main())


class TestRedrive:
    """POST /v1/taskstore/redrive — the Service Bus Explorer resubmit
    workflow (the reference outsourced dead-letter inspection/resubmission
    to Azure tooling; here the store's ORIG replay makes a redrive a
    conditional republish)."""

    @staticmethod
    async def _seed(store, status):
        from ai4e_tpu.taskstore.task import APITask

        task = store.upsert(APITask(task_id="", endpoint="http://h/v1/api",
                                    body=b"payload", publish=False))
        if status:
            store.update_status(task.task_id, status)
        return task.task_id

    def test_sweep_redrives_dead_lettered_only(self):
        store = InMemoryTaskStore()
        published = []
        store.set_publisher(published.append)

        async def main():
            dead = await self._seed(
                store, "failed - delivery attempts exhausted")
            model_err = await self._seed(store, "failed - model exploded")
            done = await self._seed(store, "completed")
            client = TestClient(TestServer(make_app(store)))
            await client.start_server()
            try:
                resp = await client.post("/v1/taskstore/redrive", json={})
                body = await resp.json()
                assert resp.status == 200
                assert body["redriven"] == 1
                assert body["task_ids"] == [dead]
                # Redriven: created again, ORIGINAL body republished.
                assert store.get(dead).canonical_status == "created"
                assert [m.task_id for m in published] == [dead]
                assert published[0].body == b"payload"
                # Untouched: a model failure and a completed task.
                assert store.get(model_err).canonical_status == "failed"
                assert store.get(done).canonical_status == "completed"

                # Contains="" sweeps EVERY failed task.
                resp = await client.post("/v1/taskstore/redrive",
                                         json={"Contains": ""})
                body = await resp.json()
                assert body["task_ids"] == [model_err]
            finally:
                await client.close()

        run(main())

    def test_single_task_redrive_and_guards(self):
        store = InMemoryTaskStore()
        published = []
        store.set_publisher(published.append)

        async def main():
            failed = await self._seed(store, "failed - model exploded")
            done = await self._seed(store, "completed")
            client = TestClient(TestServer(make_app(store)))
            await client.start_server()
            try:
                # Explicit TaskId redrives any failed task (no prose filter).
                resp = await client.post("/v1/taskstore/redrive",
                                         json={"TaskId": failed})
                assert resp.status == 200
                assert (await resp.json())["Status"] == "created"
                assert [m.task_id for m in published] == [failed]
                # Never re-runs a completed task.
                resp = await client.post("/v1/taskstore/redrive",
                                         json={"TaskId": done})
                assert resp.status == 409
                # Unknown task is a 404, not a silent no-op.
                resp = await client.post("/v1/taskstore/redrive",
                                         json={"TaskId": "nope"})
                assert resp.status == 404
            finally:
                await client.close()

        run(main())

    def test_follower_refuses_redrive(self, tmp_path):
        from ai4e_tpu.taskstore.store import FollowerTaskStore

        store = FollowerTaskStore(str(tmp_path / "j.jsonl"))
        assert store.role == "follower"

        async def main():
            client = TestClient(TestServer(make_app(store)))
            await client.start_server()
            try:
                resp = await client.post("/v1/taskstore/redrive", json={})
                assert resp.status == 503
                assert resp.headers.get("X-Not-Primary") == "1"
            finally:
                await client.close()

        run(main())

    def test_non_object_json_body_is_400(self):
        store = InMemoryTaskStore()

        async def main():
            client = TestClient(TestServer(make_app(store)))
            await client.start_server()
            try:
                resp = await client.post("/v1/taskstore/redrive", data=b"[]")
                assert resp.status == 400
            finally:
                await client.close()

        run(main())

    def test_colon_task_id_is_400_on_the_wire(self):
        store = InMemoryTaskStore()

        async def main():
            client = TestClient(TestServer(make_app(store)))
            await client.start_server()
            try:
                resp = await client.post(
                    "/v1/taskstore/upsert",
                    json={"TaskId": "job:7", "Endpoint": "http://h/v1/x"})
                assert resp.status == 400
                assert "must not contain" in (await resp.json())["error"]
            finally:
                await client.close()

        run(main())


class TestConditionalWireUpdate:
    """``ExpectedStatus`` on ``POST /v1/taskstore/update`` — the wire form
    of ``update_status_if`` (ISSUE 11): a remote writer's terminal
    transition evaluates its precondition under the STORE's lock instead
    of carrying the reachably-racy probe-then-write shape across the hop
    (docs/concurrency.md's documented residual window, closed)."""

    def test_conditional_update_applies_once_and_409s_the_loser(self):
        from ai4e_tpu.taskstore import TaskStatus
        store = InMemoryTaskStore()

        async def main():
            client, tm = await manager_for(store)
            try:
                task = await tm.add_task("http://h/v1/api", b"x")
                tid = task["TaskId"]
                store.update_status(tid, "running", TaskStatus.RUNNING)
                won = await tm.update_task_status_if(
                    tid, TaskStatus.RUNNING, "completed",
                    TaskStatus.COMPLETED)
                assert won is not None and "completed" in won["Status"]
                # The duplicate's conditional write refuses instead of
                # clobbering the completion the client may have read.
                lost = await tm.update_task_status_if(
                    tid, TaskStatus.RUNNING, "failed - duplicate",
                    TaskStatus.FAILED)
                assert lost is None
                assert store.get(tid).status == "completed"
            finally:
                await client.close()

        run(main())

    def test_conditional_update_of_unknown_task_is_none(self):
        from ai4e_tpu.taskstore import TaskStatus
        store = InMemoryTaskStore()

        async def main():
            client, tm = await manager_for(store)
            try:
                got = await tm.update_task_status_if(
                    "t-nope", TaskStatus.RUNNING, "completed",
                    TaskStatus.COMPLETED)
                assert got is None
            finally:
                await client.close()

        run(main())
