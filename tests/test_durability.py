"""Durable-truth hardening (docs/durability.md): the checksummed,
hash-chained journal envelope, torn-tail salvage vs interior quarantine,
the AI4E_TASKSTORE_FSYNC policy ladder, the disk-fault degraded mode, and
checksum-verified replication.

The headline regressions:

- a torn final journal line (kill mid-append) used to CRASH-LOOP the
  store at boot (bare ``json.loads``), and even a skip-only fix would
  leave the ``"a"``-mode handle concatenating the next record onto the
  torn tail — salvage truncates BEFORE the handle opens;
- ``_append``'s old "already made this mutation durable" claim was false
  for a machine crash — the fsync policy ladder makes the real contract
  explicit and testable;
- a checksum-failing replicated line used to absorb silently — now it
  forces the follower's generation-mismatch resync path.
"""

import asyncio
import errno
import json
import os
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.chaos.disk import DiskFaultInjector, attach_journal_faults
from ai4e_tpu.metrics import MetricsRegistry
from ai4e_tpu.taskstore import (APITask, FollowerTaskStore,
                                JournalCorruptError, JournalDegradedError,
                                JournaledTaskStore, TaskNotFound, TaskStatus)
from ai4e_tpu.taskstore import journal as jf
from ai4e_tpu.taskstore.http import make_app
from ai4e_tpu.taskstore.replication import (JournalReplicator,
                                            split_complete_lines)


def run(coro):
    return asyncio.run(coro)


async def serve(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


async def wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval)
    return predicate()


def store_at(tmp_path, name="j", **kw):
    kw.setdefault("metrics", MetricsRegistry())
    return JournaledTaskStore(str(tmp_path / name), **kw)


def make_task(body=b"payload", endpoint="/v1/dur/x"):
    return APITask(endpoint=endpoint, body=body, status="created",
                   publish=False)


# -- envelope + chain math ---------------------------------------------------


class TestEnvelope:
    def test_crc32c_known_vectors(self):
        # RFC 3720 appendix test vector + the empty string.
        assert jf.crc32c(b"123456789") == 0xE3069283
        assert jf.crc32c(b"") == 0
        assert jf.crc32c(b"\x00" * 32) == 0x8A9136AA

    def test_encode_verify_round_trip_and_chain(self):
        line1, c1 = jf.encode_record({"a": 1}, jf.GENESIS)
        line2, c2 = jf.encode_record({"b": 2}, c1)
        rec1, got1, legacy1 = jf.verify_line(line1, jf.GENESIS)
        rec2, got2, legacy2 = jf.verify_line(line2, got1)
        assert (rec1, rec2) == ({"a": 1}, {"b": 2})
        assert (got1, got2) == (c1, c2)
        assert not legacy1 and not legacy2

    def test_bit_flip_detected_at_the_exact_record(self):
        line, _ = jf.encode_record({"a": 1}, jf.GENESIS)
        tampered = line[:-2] + ("9" if line[-2] != "9" else "8") + line[-1]
        with pytest.raises(JournalCorruptError) as exc:
            jf.verify_line(tampered, jf.GENESIS)
        assert exc.value.reason == "checksum"

    def test_dropped_predecessor_breaks_the_chain(self):
        line1, c1 = jf.encode_record({"a": 1}, jf.GENESIS)
        line2, _ = jf.encode_record({"b": 2}, c1)
        # Verify line2 as if line1 never existed: its own checksum is
        # fine, the CHAIN is what catches the fork.
        with pytest.raises(JournalCorruptError) as exc:
            jf.verify_line(line2, jf.GENESIS)
        assert exc.value.reason == "chain"

    def test_legacy_line_verifies_and_advances_the_chain(self):
        rec, chain, legacy = jf.verify_line('{"Epoch": 3}', jf.GENESIS)
        assert legacy and rec == {"Epoch": 3}
        assert chain != jf.GENESIS  # the head stays well-defined
        # Unanchored legacy (prev unknown) stays unanchored.
        _, chain2, _ = jf.verify_line('{"Epoch": 3}', None)
        assert chain2 is None

    def test_malformed_envelope_is_corrupt(self):
        with pytest.raises(JournalCorruptError):
            jf.verify_line("J1:zzzzzzzz:00000000:{}", jf.GENESIS)
        with pytest.raises(JournalCorruptError):
            jf.verify_line("not json at all", jf.GENESIS)

    def test_fsync_policy_grammar(self):
        assert jf.parse_fsync_policy("never") == ("never", 0.0)
        assert jf.parse_fsync_policy("always") == ("always", 0.0)
        kind, s = jf.parse_fsync_policy("group:20")
        assert kind == "group" and abs(s - 0.02) < 1e-9
        # NaN/inf windows would construct a store whose group fsync
        # silently never fires (NaN compares False both ways) — the
        # validator must refuse them like any other junk (review
        # finding).
        for bad in ("sometimes", "group:", "group:-5", "group:x",
                    "group:nan", "group:inf", "group:-inf", "group:0"):
            with pytest.raises(ValueError):
                jf.parse_fsync_policy(bad)


# -- split_complete_lines edge cases (replication's shared split rule) -------


class TestSplitCompleteLines:
    def test_empty_buffer(self):
        assert split_complete_lines(b"") == ([], b"")

    def test_crlf_terminated_records(self):
        lines, rest = split_complete_lines(b"alpha\r\nbeta\r\n")
        assert lines == ["alpha", "beta"]
        assert rest == b""

    def test_record_straddling_three_chunks(self):
        record = b'{"TaskId": "abc", "Status": "created"}\n'
        chunks = [record[:10], record[10:25], record[25:]]
        buffer = b""
        collected = []
        for chunk in chunks:
            lines, buffer = split_complete_lines(buffer + chunk)
            collected.extend(lines)
        assert collected == [record.decode().rstrip("\n")]
        assert buffer == b""

    def test_final_chunk_with_no_newline_stays_buffered(self):
        lines, rest = split_complete_lines(b"done\npart")
        assert lines == ["done"]
        assert rest == b"part"  # absorbed whole or not at all


# -- salvage vs quarantine ---------------------------------------------------


class TestSalvage:
    def test_kill_mid_append_boot_clean_then_append_parses(self, tmp_path):
        """THE regression: torn final line → boot clean (no crash-loop),
        truncated before the append handle opens, and a post-boot append
        lands on a clean boundary (parses + survives another restart)."""
        s = store_at(tmp_path)
        kept = s.upsert(make_task())
        s.close()
        path = str(tmp_path / "j")
        with open(path, "a", encoding="utf-8") as fh:
            fh.write('J1:12345678:9abcdef0:{"TaskId": "torn-mid-wri')
        s2 = store_at(tmp_path)  # must not raise
        assert s2.get(kept.task_id).canonical_status == "created"
        with pytest.raises(TaskNotFound):
            s2.get("torn-mid-wri")
        after = s2.upsert(make_task(body=b"post-salvage"))
        s2.close()
        # Every line of the final file parses — the torn tail was
        # truncated, never concatenated onto.
        scan = jf.scan_journal(path)
        assert scan.clean
        s3 = store_at(tmp_path)
        assert s3.get(after.task_id).canonical_status == "created"
        s3.close()

    def test_salvage_writes_report_sidecar_and_metric(self, tmp_path):
        metrics = MetricsRegistry()
        s = store_at(tmp_path, metrics=metrics)
        s.upsert(make_task())
        s.close()
        path = str(tmp_path / "j")
        with open(path, "a", encoding="utf-8") as fh:
            fh.write("garbage-tail-no-newline")
        metrics2 = MetricsRegistry()
        s2 = JournaledTaskStore(path, metrics=metrics2)
        s2.close()
        report = json.load(open(path + ".salvage.json"))
        assert report["dropped_bytes"] == len("garbage-tail-no-newline")
        assert report["records_kept"] == 1
        assert metrics2.counter(
            "ai4e_journal_salvages_total", "").value(reason="torn") == 1
        assert s2.journal_stats()["salvages"] == 1

    def test_complete_but_corrupt_final_line_is_salvaged(self, tmp_path):
        s = store_at(tmp_path)
        kept = s.upsert(make_task())
        doomed = s.upsert(make_task(body=b"doomed"))
        s.close()
        path = str(tmp_path / "j")
        lines = open(path).read().splitlines()
        lines[-1] = lines[-1][:-3] + 'xx}'  # newline-terminated, bad CRC
        open(path, "w").write("\n".join(lines) + "\n")
        s2 = store_at(tmp_path)
        assert s2.get(kept.task_id)
        with pytest.raises(TaskNotFound):
            s2.get(doomed.task_id)
        s2.close()

    def test_legacy_checksumless_journal_torn_tail_salvaged(self, tmp_path):
        path = str(tmp_path / "legacy")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(json.dumps({"TaskId": "old-1", "Endpoint": "/v1/x",
                                 "Status": "created",
                                 "BackendStatus": "created"}) + "\n")
            fh.write('{"TaskId": "old-torn", "Endp')  # kill mid-append
        s = JournaledTaskStore(path, metrics=MetricsRegistry())
        assert s.get("old-1").canonical_status == "created"
        with pytest.raises(TaskNotFound):
            s.get("old-torn")
        s.close()

    def test_corrupt_interior_record_refuses_loudly_with_offset(
            self, tmp_path):
        s = store_at(tmp_path)
        s.upsert(make_task())
        s.upsert(make_task(body=b"two"))
        s.upsert(make_task(body=b"three"))
        s.close()
        path = str(tmp_path / "j")
        lines = open(path).read().splitlines()
        expected_offset = len((lines[0] + "\n").encode())
        lines[1] = lines[1][:-3] + 'xx}'  # interior record
        open(path, "w").write("\n".join(lines) + "\n")
        with pytest.raises(JournalCorruptError) as exc:
            JournaledTaskStore(path, metrics=MetricsRegistry())
        assert exc.value.offset == expected_offset
        assert "durability.md" in str(exc.value)
        # The file was NOT touched — quarantine, not silent repair.
        assert open(path).read().splitlines()[1] == lines[1]

    def test_verify_cli_verdicts(self, tmp_path, capsys):
        s = store_at(tmp_path)
        s.upsert(make_task())
        s.close()
        path = str(tmp_path / "j")
        assert jf.main([path]) == 0
        assert "OK" in capsys.readouterr().out
        with open(path, "a") as fh:
            fh.write("torn")
        assert jf.main([path]) == 0  # salvageable → boot repairs it
        assert "TORN TAIL" in capsys.readouterr().out


# -- replay compatibility ----------------------------------------------------


class TestLegacyReplay:
    def test_pre_envelope_journal_replays_and_mixes(self, tmp_path):
        """Old journals (bare JSON lines) replay verbatim; new appends
        land enveloped in the same file; the mixed file replays again."""
        path = str(tmp_path / "legacy")
        with open(path, "w", encoding="utf-8") as fh:
            fh.write(json.dumps({"TaskId": "old-1", "Endpoint": "/v1/x",
                                 "Status": "created",
                                 "BackendStatus": "created",
                                 "BodyHex": b"hello".hex()}) + "\n")
            fh.write(json.dumps({"TaskId": "old-1", "Slim": True,
                                 "Status": "completed - ok",
                                 "BackendStatus": "completed"}) + "\n")
        s = JournaledTaskStore(path, metrics=MetricsRegistry())
        assert s.get("old-1").canonical_status == "completed"
        fresh = s.upsert(make_task())
        s.close()
        raw = open(path).read().splitlines()
        assert not raw[0].startswith("J1:")      # legacy kept verbatim
        assert raw[-1].startswith("J1:")         # new append enveloped
        s2 = JournaledTaskStore(path, metrics=MetricsRegistry())
        assert s2.get("old-1").canonical_status == "completed"
        assert s2.get(fresh.task_id).canonical_status == "created"
        s2.close()

    def test_chain_head_survives_restart_and_compaction(self, tmp_path):
        s = store_at(tmp_path)
        t = s.upsert(make_task())
        s.update_status(t.task_id, "completed - x", TaskStatus.COMPLETED)
        head = s.chain_head
        s.close()
        s2 = store_at(tmp_path)
        assert s2.chain_head == head
        s2.compact()
        assert s2.chain_head != head  # new byte lineage…
        head2 = s2.chain_head
        s2.close()
        s3 = store_at(tmp_path)
        assert s3.chain_head == head2  # …that replays to the same head
        assert s3.get(t.task_id).canonical_status == "completed"
        s3.close()


# -- fsync policy ladder -----------------------------------------------------


class TestFsyncPolicies:
    @pytest.fixture()
    def fsync_counter(self, monkeypatch):
        calls = []
        real = os.fsync

        def counting(fd):
            calls.append(fd)
            return real(fd)

        monkeypatch.setattr(os, "fsync", counting)
        return calls

    def test_default_never_is_todays_write_behavior(self, tmp_path,
                                                    fsync_counter):
        """The byte-identical-default acceptance: no fsync ever issues on
        the append path, exactly the pre-hardening behavior."""
        s = store_at(tmp_path)
        assert s._fsync_kind == "never"
        for _ in range(5):
            s.upsert(make_task())
        assert fsync_counter == []
        s.close()
        assert fsync_counter == []  # nothing owed at close either

    def test_always_fsyncs_every_append(self, tmp_path, fsync_counter):
        s = store_at(tmp_path, fsync="always")
        base = len(fsync_counter)
        s.upsert(make_task())
        s.upsert(make_task())
        assert len(fsync_counter) - base == 2
        assert s.journal_stats()["fsyncs"] == 2
        s.close()

    def test_group_commit_amortizes_and_timer_completes_window(
            self, tmp_path, fsync_counter):
        s = store_at(tmp_path, fsync="group:30")
        base = len(fsync_counter)
        for _ in range(10):
            s.upsert(make_task())
        burst = len(fsync_counter) - base
        assert burst <= 3  # amortized, never one per append
        deadline = time.monotonic() + 2.0
        while s._fsync_dirty and time.monotonic() < deadline:
            time.sleep(0.01)
        assert not s._fsync_dirty  # the timer synced the idle tail
        s.close()

    def test_env_knob_resolves_when_arg_is_none(self, tmp_path,
                                                monkeypatch):
        monkeypatch.setenv("AI4E_TASKSTORE_FSYNC", "group:50")
        s = store_at(tmp_path)
        assert (s._fsync_kind, s._fsync_group_s) == ("group", 0.05)
        s.close()
        # Explicit argument wins over the env.
        s2 = store_at(tmp_path, name="j2", fsync="never")
        assert s2._fsync_kind == "never"
        s2.close()

    def test_malformed_policy_fails_at_construction(self, tmp_path):
        with pytest.raises(ValueError):
            store_at(tmp_path, fsync="sometimes")


# -- degraded mode -----------------------------------------------------------


class TestDegradedMode:
    def _faulted_store(self, tmp_path, **rule):
        s = store_at(tmp_path)
        seeded = s.upsert(make_task(body=b"pre-fault"))
        injector = DiskFaultInjector(seed=7)
        attach_journal_faults(s, injector)
        if rule:
            injector.add_rule(**rule)
        return s, seeded, injector

    def test_enospc_on_append_rolls_back_and_fences(self, tmp_path):
        s, seeded, _ = self._faulted_store(tmp_path, op="write",
                                           errno=errno.ENOSPC)
        with pytest.raises(JournalDegradedError) as exc:
            s.upsert(make_task(body=b"doomed"))
        assert exc.value.rollback
        assert s.degraded
        # Memory never ran ahead of disk: the doomed create is GONE.
        assert len(s._tasks) == 1
        # Reads serve; every further mutation refuses with the typed
        # error BEFORE touching memory.
        assert s.get(seeded.task_id).canonical_status == "created"
        with pytest.raises(JournalDegradedError):
            s.update_status(seeded.task_id, "completed - x",
                            TaskStatus.COMPLETED)
        assert s.get(seeded.task_id).canonical_status == "created"
        with pytest.raises(JournalDegradedError):
            s.set_result(seeded.task_id, b"r")
        assert s.get_result(seeded.task_id) is None
        s.close()

    def test_update_rollback_keeps_prior_status_and_sets(self, tmp_path):
        s, seeded, _ = self._faulted_store(tmp_path, op="write",
                                           errno=errno.ENOSPC)
        with pytest.raises(JournalDegradedError):
            s.update_status(seeded.task_id, "completed - x",
                            TaskStatus.COMPLETED)
        assert s.get(seeded.task_id).canonical_status == "created"
        assert s.set_members("/v1/dur/x", "created") == [seeded.task_id]
        assert s.set_members("/v1/dur/x", "completed") == []
        s.close()

    def test_torn_write_then_recover_salvages_the_tail(self, tmp_path):
        """The fault writes a PREFIX of the record before failing (short
        write): recover() must truncate that torn tail before reopening,
        and a restart replays exactly the acknowledged history."""
        s, seeded, injector = self._faulted_store(
            tmp_path, op="write", errno=errno.ENOSPC, torn_bytes=25)
        with pytest.raises(JournalDegradedError):
            s.upsert(make_task(body=b"torn-victim"))
        assert s.degraded
        injector.clear()
        assert s.recover()
        after = s.upsert(make_task(body=b"post-recovery"))
        s.close()
        s2 = store_at(tmp_path)
        assert {t.task_id for t in s2.snapshot()} == {
            seeded.task_id, after.task_id}
        s2.close()

    def test_eio_on_fsync_keeps_memory_equal_to_file(self, tmp_path):
        s = store_at(tmp_path, fsync="always")
        injector = DiskFaultInjector(seed=7)
        attach_journal_faults(s, injector)
        injector.add_rule(op="fsync", errno=errno.EIO)
        with pytest.raises(JournalDegradedError) as exc:
            s.upsert(make_task(body=b"refused-but-durable"))
        assert not exc.value.rollback
        assert s.degraded
        # The bytes ARE in the file — the refused-but-durable residual:
        # memory keeps the record so reads here match a future replay.
        assert len(s._tasks) == 1
        injector.clear()
        assert s.recover()
        s.close()
        s2 = store_at(tmp_path)
        assert len(s2.snapshot()) == 1
        s2.close()

    def test_degraded_metrics_and_stats(self, tmp_path):
        metrics = MetricsRegistry()
        s = JournaledTaskStore(str(tmp_path / "j"), metrics=metrics)
        injector = DiskFaultInjector(seed=1)
        attach_journal_faults(s, injector)
        injector.add_rule(op="write", errno=errno.ENOSPC)
        with pytest.raises(JournalDegradedError):
            s.upsert(make_task())
        assert metrics.gauge("ai4e_journal_degraded", "").value() == 1.0
        assert metrics.counter("ai4e_journal_degraded_total", "").value(
            errno="ENOSPC") == 1
        assert s.journal_stats()["degraded"] is True
        injector.clear()
        assert s.recover()
        assert metrics.gauge("ai4e_journal_degraded", "").value() == 0.0
        s.close()

    def test_flush_failure_buffer_never_resurrects_rolled_back_record(
            self, tmp_path):
        """Review regression: write() buffers cleanly, flush() fails —
        the Python-side buffer RETAINS the refused record's bytes, and an
        ordinary close() (by recover() or shutdown) would re-flush them
        onto the healed file, resurrecting a mutation the caller was told
        was refused and unwound. The store discards the broken handle's
        buffer instead."""
        s, seeded, injector = self._faulted_store(
            tmp_path, op="flush", errno=errno.ENOSPC)
        with pytest.raises(JournalDegradedError) as exc:
            s.upsert(make_task(body=b"refused-and-unwound"))
        assert exc.value.rollback
        injector.clear()
        assert s.recover()
        # Live store: rolled back, and recovery did not resurrect it.
        assert {t.task_id for t in s.snapshot()} == {seeded.task_id}
        after = s.upsert(make_task(body=b"post-recovery"))
        s.close()
        # Restart: the refused record's bytes never reached the file —
        # neither recover()'s handle swap nor close() flushed them.
        s2 = store_at(tmp_path)
        assert {t.task_id for t in s2.snapshot()} == {
            seeded.task_id, after.task_id}
        s2.close()

    def test_flush_failure_close_while_degraded_discards_buffer(
            self, tmp_path):
        """Same hazard on the OTHER exit path: closing a degraded store
        (the sharded facade's mark_dead before replica promotion) must
        not flush the refused record where the replica drain — or a
        restart — would pick it up."""
        s, seeded, _ = self._faulted_store(tmp_path, op="flush",
                                           errno=errno.ENOSPC)
        with pytest.raises(JournalDegradedError):
            s.upsert(make_task(body=b"refused"))
        s.close()
        s2 = store_at(tmp_path)
        assert {t.task_id for t in s2.snapshot()} == {seeded.task_id}
        s2.close()

    def test_evict_append_failure_restores_the_whole_task(self, tmp_path):
        """Review regression: an eviction whose Evict append fails must
        restore the task wholesale (record, status set, orig body,
        result) — otherwise memory forgets a task the journal still
        holds, a recovered retry no-ops before journaling the eviction,
        and a restart resurrects it."""
        s = store_at(tmp_path)
        t = s.upsert(make_task(body=b"evict-me"))
        s.update_status(t.task_id, "completed - x", TaskStatus.COMPLETED)
        s.set_result(t.task_id, b"kept-result")
        injector = DiskFaultInjector(seed=3)
        attach_journal_faults(s, injector)
        injector.add_rule(op="write", errno=errno.ENOSPC)
        with pytest.raises(JournalDegradedError):
            s.evict_terminal_older_than(0.0)
        # Fully restored: record, set membership, result, original body.
        assert s.get(t.task_id).canonical_status == "completed"
        assert s.set_members("/v1/dur/x", "completed") == [t.task_id]
        assert s.get_result(t.task_id)[0] == b"kept-result"
        assert s.get_original_body(t.task_id) == b"evict-me"
        injector.clear()
        assert s.recover()
        # The retried eviction now journals and sticks across restart.
        assert s.evict_terminal_older_than(0.0) == 1
        s.close()
        s2 = store_at(tmp_path)
        with pytest.raises(TaskNotFound):
            s2.get(t.task_id)
        s2.close()

    def test_recover_salvage_bumps_generation_for_readers(self, tmp_path):
        """Review regression: recover()'s salvage truncates bytes that
        replication readers may have already consumed (a torn fragment
        streams like any other bytes) — without a generation bump, a
        reader whose offset passed the verified prefix reports zero lag
        while missing every post-recover write, or splices fresh record
        bytes onto its stale buffer and parks. The bump forces the
        full-resync path, same contract as compaction."""
        from ai4e_tpu.taskstore.sharding import ShardGroup

        group = ShardGroup(0, journal_path=str(tmp_path / "j"),
                           replicas=1)
        try:
            link = group.links[0]
            t1 = group.primary.upsert(make_task())
            assert link.sync_once() > 0
            injector = DiskFaultInjector(seed=9)
            attach_journal_faults(group.primary, injector)
            injector.add_rule(op="write", errno=errno.ENOSPC,
                              torn_bytes=10)
            with pytest.raises(JournalDegradedError):
                group.primary.upsert(make_task())
            gen_before = group.primary.journal_generation
            # The torn fragment is visible file bytes: the link consumes
            # them and its offset passes the verified prefix.
            link.sync_once()
            assert group.primary.recover()
            assert group.primary.journal_generation == gen_before + 1
            t2 = group.primary.upsert(make_task())
            while link.sync_once():
                pass
            assert link.standby.get(t1.task_id)
            assert link.standby.get(t2.task_id)
            assert (link.standby.replica_chain_head
                    == group.primary.chain_head)
        finally:
            group.close()

    def test_set_result_append_failure_keeps_prior_offloaded_result(
            self, tmp_path):
        """Review regression: superseding an offloaded result deletes the
        stale blob in the base apply — which must not happen before the
        record is known journaled. A degraded append used to roll back to
        a pointer whose blob was already gone, making an ACKNOWLEDGED
        result unreadable. Append-first leaves memory (and the blob)
        untouched on failure."""
        from ai4e_tpu.taskstore import FileResultBackend

        backend = FileResultBackend(str(tmp_path / "blobs"))
        s = store_at(tmp_path, result_backend=backend,
                     result_offload_threshold=64)
        t = s.upsert(make_task())
        big = b"\x41" * 256
        s.set_result(t.task_id, big)  # offloads: memory holds a pointer
        assert s._results[t.task_id][0] is None
        injector = DiskFaultInjector(seed=11)
        attach_journal_faults(s, injector)
        injector.add_rule(op="write", errno=errno.ENOSPC)
        # Inline supersede refused mid-append: the acknowledged result
        # must STAY readable (pointer intact, blob intact).
        with pytest.raises(JournalDegradedError):
            s.set_result(t.task_id, b"small-inline")
        assert s.get_result(t.task_id) == (big, "application/json")
        assert backend.get(t.task_id) is not None
        injector.clear()
        assert s.recover()
        # The retried supersede now lands and reaps the stale blob.
        s.set_result(t.task_id, b"small-inline")
        assert s.get_result(t.task_id)[0] == b"small-inline"
        assert backend.get(t.task_id) is None
        s.close()

    def test_set_result_pointer_rewrite_failure_never_dangles(
            self, tmp_path):
        """Pointer→pointer companion: put() overwrites the blob in place
        BEFORE the lock, so a refused append cannot restore the old
        bytes — but the visible pointer must never dangle. set_result's
        reap skips keys that already held a pointer; the documented
        residual is that the blob serves the refused write's content."""
        from ai4e_tpu.taskstore import FileResultBackend

        backend = FileResultBackend(str(tmp_path / "blobs"))
        s = store_at(tmp_path, result_backend=backend,
                     result_offload_threshold=64)
        t = s.upsert(make_task())
        s.set_result(t.task_id, b"\x41" * 256)
        injector = DiskFaultInjector(seed=11)
        attach_journal_faults(s, injector)
        injector.add_rule(op="write", errno=errno.ENOSPC)
        with pytest.raises(JournalDegradedError):
            s.set_result(t.task_id, b"\x42" * 256)
        # Readable — never a pointer to a deleted blob (the residual:
        # content is the refused write's, docs/durability.md).
        found = s.get_result(t.task_id)
        assert found is not None and found[0] == b"\x42" * 256
        s.close()

    def test_fsync_failure_result_applies_memory_and_keeps_blob(
            self, tmp_path):
        """Review regression: append-first must not invert the
        rollback=False contract. EIO on fsync lands the Result record
        durably in the file; memory must still apply it (memory == file,
        the refused-but-possibly-durable residual upsert/update keep)
        and the cleanup must NOT reap the blob the durable record points
        to — a restart would otherwise replay a result pointer whose
        blob is gone and serve None for a journaled result."""
        from ai4e_tpu.taskstore import FileResultBackend

        backend = FileResultBackend(str(tmp_path / "blobs"))
        s = store_at(tmp_path, fsync="always", result_backend=backend,
                     result_offload_threshold=64)
        t = s.upsert(make_task())
        injector = DiskFaultInjector(seed=13)
        attach_journal_faults(s, injector)
        injector.add_rule(op="fsync", errno=errno.EIO)
        big = b"\x44" * 256
        with pytest.raises(JournalDegradedError) as exc:
            s.set_result(t.task_id, big)
        assert not exc.value.rollback
        # Memory == file: the result is visible and its blob survives.
        assert s.get_result(t.task_id) == (big, "application/json")
        assert backend.get(t.task_id) is not None
        s.close()
        # The durable record replays WITH a readable blob.
        s2 = store_at(tmp_path, result_backend=backend,
                      result_offload_threshold=64)
        assert s2.get_result(t.task_id) == (big, "application/json")
        s2.close()

    def test_evict_mid_batch_degraded_reaps_journaled_victims_blobs(
            self, tmp_path):
        """Review regression: a mid-batch degraded abort used to skip the
        blob-delete loop for victims already evicted AND journaled — no
        journal record references their blobs anymore, so nothing would
        ever delete them (a permanent orphan on the mount)."""
        from ai4e_tpu.taskstore import FileResultBackend

        backend = FileResultBackend(str(tmp_path / "blobs"))
        s = store_at(tmp_path, result_backend=backend,
                     result_offload_threshold=64)
        tasks = []
        for _ in range(2):
            t = s.upsert(make_task())
            s.update_status(t.task_id, "completed - x",
                            TaskStatus.COMPLETED)
            s.set_result(t.task_id, b"\x43" * 256)  # offloaded
            tasks.append(t)
        injector = DiskFaultInjector(seed=5)
        attach_journal_faults(s, injector)
        # First Evict append lands; the second one faults.
        injector.add_rule(op="write", errno=errno.ENOSPC, after_ops=1)
        with pytest.raises(JournalDegradedError):
            s.evict_terminal_older_than(0.0)
        # Victim 1: evicted, journaled — its orphaned blob WAS deleted.
        with pytest.raises(TaskNotFound):
            s.get(tasks[0].task_id)
        assert backend.get(tasks[0].task_id) is None
        # Victim 2: rolled back wholesale — record AND blob intact.
        assert s.get(tasks[1].task_id).canonical_status == "completed"
        assert s.get_result(tasks[1].task_id)[0] == b"\x43" * 256
        s.close()

    def test_http_surface_answers_typed_503(self, tmp_path):
        async def main():
            s = store_at(tmp_path)
            seeded = s.upsert(make_task())
            injector = DiskFaultInjector(seed=1)
            attach_journal_faults(s, injector)
            injector.add_rule(op="write", errno=errno.ENOSPC,
                              times=None)
            client = await serve(make_app(s))
            try:
                resp = await client.post("/v1/taskstore/upsert", json={
                    "Endpoint": "/v1/dur/x", "Status": "created"})
                assert resp.status == 503
                assert resp.headers["X-Shed-Reason"] == "journal-degraded"
                assert "X-Not-Primary" not in resp.headers  # reads stay
                resp = await client.post("/v1/taskstore/update", json={
                    "TaskId": seeded.task_id, "Status": "completed - x"})
                assert resp.status == 503
                assert resp.headers["X-Shed-Reason"] == "journal-degraded"
                # Reads keep serving through the degradation.
                resp = await client.get(
                    f"/v1/taskstore/task?taskId={seeded.task_id}")
                assert resp.status == 200
                # The role endpoint names the state + the chain head.
                resp = await client.get("/v1/taskstore/role")
                doc = await resp.json()
                assert doc["degraded"] is True
                assert doc["chain_head"] == s.chain_head
            finally:
                await client.close()
                s.close()

        run(main())


# -- verified replication ----------------------------------------------------


class TestVerifiedAbsorb:
    def _primary_lines(self, tmp_path, n=3):
        p = store_at(tmp_path, name="p")
        tasks = [p.upsert(make_task(body=f"b{i}".encode()))
                 for i in range(n)]
        lines = [ln.rstrip("\n")
                 for ln in open(str(tmp_path / "p")) if ln.strip()]
        return p, tasks, lines

    def test_absorb_verifies_and_converges_chain_heads(self, tmp_path):
        p, tasks, lines = self._primary_lines(tmp_path)
        f = FollowerTaskStore(str(tmp_path / "f"),
                              metrics=MetricsRegistry())
        f.reset()
        f.absorb_lines(lines)
        assert f.replica_chain_head == p.chain_head
        for t in tasks:
            assert f.get(t.task_id)
        # The follower's own file is self-consistent: restart replays it.
        f.close()
        f2 = FollowerTaskStore(str(tmp_path / "f"),
                               metrics=MetricsRegistry())
        for t in tasks:
            assert f2.get(t.task_id)
        f2.close()
        p.close()

    def test_corrupt_streamed_line_refused_prefix_kept(self, tmp_path):
        p, tasks, lines = self._primary_lines(tmp_path)
        metrics = MetricsRegistry()
        f = FollowerTaskStore(str(tmp_path / "f"), metrics=metrics)
        f.reset()
        bad = lines[1][:-3] + 'xx}'
        with pytest.raises(JournalCorruptError):
            f.absorb_lines([lines[0], bad, lines[2]])
        # The verified prefix applied; the bad line and its successors
        # did NOT absorb silently.
        assert f.get(tasks[0].task_id)
        with pytest.raises(TaskNotFound):
            f.get(tasks[1].task_id)
        with pytest.raises(TaskNotFound):
            f.get(tasks[2].task_id)
        assert metrics.counter(
            "ai4e_journal_verify_failures_total", "").value() == 1
        f.close()
        p.close()

    def test_checksumless_legacy_lines_absorb_for_migration(self, tmp_path):
        f = FollowerTaskStore(str(tmp_path / "f"),
                              metrics=MetricsRegistry())
        f.reset()
        f.absorb_lines([json.dumps({"TaskId": "legacy-1",
                                    "Endpoint": "/v1/x",
                                    "Status": "created",
                                    "BackendStatus": "created"})])
        assert f.get("legacy-1").canonical_status == "created"
        f.close()

    def test_parked_replica_link_unparks_on_generation_resync(
            self, tmp_path):
        """Review regression: a link parked on a verified-corrupt record
        kept its park tuple across a generation resync — a stale
        (generation, offset) pair could later match a fresh one exactly
        and silently stall a healthy replica forever (sync_once
        returning 0 with no log line). The resync branch clears it."""
        from ai4e_tpu.taskstore.sharding import ShardGroup

        group = ShardGroup(0, journal_path=str(tmp_path / "j"),
                           replicas=1)
        try:
            link = group.links[0]
            t = group.primary.upsert(make_task())
            assert link.sync_once() > 0
            # Bit-rot appended behind the store's back: the link parks.
            with open(group.journal_path, "a") as fh:
                fh.write("## bit-rot, not a journal line ##\n")
            assert link.sync_once() == 0
            assert link._corrupt_at is not None
            assert link.sync_once() == 0  # parked: no re-read
            # Compaction rewrites clean bytes at a new generation: the
            # link resyncs AND drops the stale park.
            group.primary.compact()
            assert link.sync_once() > 0
            assert link._corrupt_at is None
            assert (link.standby.replica_chain_head
                    == group.primary.chain_head)
            assert link.standby.get(t.task_id)
        finally:
            group.close()

    def test_role_endpoint_exposes_replica_chain_head(self, tmp_path):
        """Review regression: the HTTP divergence check must compare the
        primary's chain_head to the FOLLOWER's replica_chain_head. A
        re-seeded follower's OWN file legitimately diverges (reset writes
        its epoch line), so exposing only chain_head read as a permanent
        false divergence on a perfectly converged pair."""
        async def main():
            p, tasks, lines = self._primary_lines(tmp_path)
            f = FollowerTaskStore(str(tmp_path / "f"),
                                  metrics=MetricsRegistry())
            f.demote(1)  # fenced once — the post-failover shape
            f.reset()    # re-seed: writes the epoch line, forking own file
            f.absorb_lines(lines)
            client = await serve(make_app(f))
            try:
                doc = await (await client.get("/v1/taskstore/role")).json()
                # The comparable pair converges...
                assert doc["replica_chain_head"] == p.chain_head
                # ...while the naive own-file comparison never would.
                assert doc["chain_head"] != p.chain_head
            finally:
                await client.close()
                f.close()
                p.close()

        run(main())

    def test_streamed_corruption_forces_generation_resync(self, tmp_path):
        """Satellite: a checksum-failing line in the HTTP journal stream
        must force the follower's generation-mismatch resync path — and
        once the primary's compaction rewrites a clean generation, the
        follower converges instead of holding poisoned state."""
        async def main():
            primary = store_at(tmp_path, name="p")
            t1 = primary.upsert(make_task(body=b"one"))
            client = await serve(make_app(primary))
            follower = FollowerTaskStore(str(tmp_path / "f"),
                                         metrics=MetricsRegistry())
            repl = JournalReplicator(follower, str(client.make_url("")),
                                     poll_wait=0.2)
            repl.start()
            try:
                assert await wait_for(
                    lambda: follower.replica_chain_head
                    == primary.chain_head)
                # Corrupt the stream at the source: garbage appended to
                # the primary's FILE behind the store's back.
                with open(str(tmp_path / "p"), "a") as fh:
                    fh.write("## bit-rot, not a journal line ##\n")
                gen_before = primary.journal_generation
                assert await wait_for(lambda: repl.generation == -1)
                assert not repl.synced.is_set()
                # The primary compacts (its memory is the clean truth):
                # new generation, clean bytes — the follower resyncs and
                # converges.
                t2 = primary.upsert(make_task(body=b"two"))
                primary.compact()
                assert primary.journal_generation > gen_before
                assert await wait_for(
                    lambda: follower.replica_chain_head
                    == primary.chain_head)
                assert follower.get(t1.task_id)
                assert follower.get(t2.task_id)
            finally:
                await repl.aclose()
                await client.close()
                follower.close()
                primary.close()

        run(main())


# -- assembly defaults -------------------------------------------------------


class TestAssemblyDefaults:
    def test_platform_default_policy_is_never_and_env_resolves(
            self, tmp_path, monkeypatch):
        from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
        cfg = PlatformConfig(journal_path=str(tmp_path / "j"))
        assert cfg.taskstore_fsync is None
        platform = LocalPlatform(cfg, metrics=MetricsRegistry())
        assert platform.store._fsync_kind == "never"
        platform.store.close()
        monkeypatch.setenv("AI4E_TASKSTORE_FSYNC", "always")
        platform2 = LocalPlatform(
            PlatformConfig(journal_path=str(tmp_path / "j2")),
            metrics=MetricsRegistry())
        assert platform2.store._fsync_kind == "always"
        platform2.store.close()

    def test_replicaless_degraded_shard_keeps_serving_reads(self, tmp_path):
        """Review regression: with NO promotable replica, a degraded
        shard primary must NOT be closed by the facade — that would turn
        a transient disk fault into a permanent full-shard outage. The
        typed error surfaces, reads keep serving, and recover() re-admits
        writes."""
        import errno as errno_mod

        from ai4e_tpu.taskstore.sharding import ShardedTaskStore
        store = ShardedTaskStore(2, journal_path=str(tmp_path / "j"),
                                 replicas=0, metrics=MetricsRegistry())
        t = store.upsert(make_task())
        victim = store.groups[store.shard_for(t.task_id)]
        injector = DiskFaultInjector(seed=5)
        attach_journal_faults(victim.active, injector)
        injector.add_rule(op="write", errno=errno_mod.ENOSPC, times=None)
        with pytest.raises(JournalDegradedError):
            store.update_status(t.task_id, "completed - x",
                                TaskStatus.COMPLETED)
        # NOT closed, NOT marked dead: reads still route and serve.
        assert not victim.dead
        assert store.get(t.task_id).canonical_status == "created"
        # Disk heals → the shard re-admits writes in place.
        injector.clear()
        assert victim.active.recover()
        store.update_status(t.task_id, "completed - x",
                            TaskStatus.COMPLETED)
        assert store.get(t.task_id).canonical_status == "completed"
        store.close()

    def test_sharded_topology_exposes_chain_heads(self, tmp_path):
        from ai4e_tpu.taskstore.sharding import ShardedTaskStore
        store = ShardedTaskStore(2, journal_path=str(tmp_path / "j"),
                                 replicas=1, metrics=MetricsRegistry())
        t = store.upsert(make_task())
        for group in store.groups:
            for link in group.links:
                link.drain()
        topo = store.topology()
        owner = store.shard_for(t.task_id)
        g = topo["groups"][owner]
        assert g["chain_head"] == store.groups[owner].active.chain_head
        assert g["replica_chain_heads"] == [
            store.groups[owner].active.chain_head]
        assert g["degraded"] is False
        assert store.journal_stats()["bytes_appended"] > 0
        store.close()

    def test_out_of_band_knob_survives_config_from_env(self, monkeypatch):
        from ai4e_tpu.config import FrameworkConfig
        monkeypatch.setenv("AI4E_TASKSTORE_FSYNC", "group:25")
        FrameworkConfig.from_env()  # must not raise unknown-section


# -- review regressions: degraded promote / evict-fsync blob reap ------------


class TestDegradedPromotion:
    def _follower(self, tmp_path, **kw):
        s = FollowerTaskStore(str(tmp_path / "f"),
                              metrics=MetricsRegistry(), **kw)
        injector = DiskFaultInjector(seed=13)
        attach_journal_faults(s, injector)
        return s, injector

    def test_promote_epoch_append_failure_unwinds_wholesale(self, tmp_path):
        """Review regression: a half-promoted store (role flipped, epoch
        minted in memory, Epoch record never in the file) breaks the
        no-two-promotions-share-an-epoch fencing guarantee — a restart
        replays the OLD epoch and a later promotion re-mints one the
        deposed lineage already claimed. The failed promote must unwind
        wholesale, and recover() + a retried promote() must mint
        cleanly."""
        s, injector = self._follower(tmp_path)
        injector.add_rule(op="write", errno=errno.ENOSPC)
        with pytest.raises(JournalDegradedError) as exc:
            s.promote()
        assert exc.value.rollback
        # Unwound: still an intact (degraded) follower at epoch 0.
        assert s.role == "follower"
        assert s.epoch == 0
        assert s._journal is None
        injector.clear()
        assert s.recover()
        s.promote()
        assert s.role == "primary" and s.epoch == 1
        created = s.upsert(make_task())
        s.close()
        # Restart replays exactly one minted epoch + the write.
        s2 = FollowerTaskStore(str(tmp_path / "f"), start_as_primary=True,
                               metrics=MetricsRegistry())
        assert s2.epoch == 1
        assert s2.get(created.task_id).canonical_status == "created"
        s2.close()

    def test_promote_fsync_failure_is_durable_and_degraded(self, tmp_path):
        """rollback=False companion: the Epoch record IS in the file, so
        the promotion is complete — promote() returns, the store is
        primary at epoch 1 and degraded (mutations refuse typed)."""
        s, injector = self._follower(tmp_path, fsync="always")
        injector.add_rule(op="fsync", errno=errno.EIO)
        s.promote()  # must NOT raise
        assert s.role == "primary" and s.epoch == 1
        assert s.degraded
        with pytest.raises(JournalDegradedError):
            s.upsert(make_task())
        s.close()
        s2 = FollowerTaskStore(str(tmp_path / "f"), start_as_primary=True,
                               metrics=MetricsRegistry())
        assert s2.epoch == 1  # the mint survived the restart
        s2.close()

    def test_failover_skips_replica_whose_disk_faults_mid_promotion(
            self, tmp_path):
        """Review regression: _fail_over used to let a standby's own
        JournalDegradedError escape AFTER popping it from the links —
        aborting the failover and silently discarding the replica. It
        must try the next replica instead."""
        from ai4e_tpu.taskstore.sharding import ShardedTaskStore
        store = ShardedTaskStore(1, journal_path=str(tmp_path / "s"),
                                 replicas=2, metrics=MetricsRegistry())
        t = store.upsert(make_task())
        group = store.groups[0]
        for link in group.links:
            link.drain()
        second = group.links[1].standby
        bad = DiskFaultInjector(seed=3)
        attach_journal_faults(group.links[0].standby, bad)
        bad.add_rule(op="write", errno=errno.ENOSPC, times=None)
        store.kill_shard_primary(0)
        store.update_status(t.task_id, "completed - x",
                            TaskStatus.COMPLETED)
        assert group.active is second
        assert second.epoch == 1
        assert not group.links  # the faulted replica was consumed
        assert store.get(t.task_id).canonical_status == "completed"
        store.close()


class TestEvictFsyncFailure:
    def test_evict_fsync_failure_still_reaps_blobs(self, tmp_path):
        """Review regression: on the fsync-failure shape the Evict record
        is in the file and memory already forgot the task — raising out
        of _apply_evict dropped the victim's blob keys on the floor,
        orphaning its offloaded result on the mount forever. The
        completed eviction must surrender its keys to the delete loop."""
        from ai4e_tpu.taskstore import FileResultBackend

        backend = FileResultBackend(str(tmp_path / "blobs"))
        s = store_at(tmp_path, fsync="always", result_backend=backend,
                     result_offload_threshold=64)
        t = s.upsert(make_task())
        s.update_status(t.task_id, "completed - x", TaskStatus.COMPLETED)
        s.set_result(t.task_id, b"\x44" * 256)  # offloaded
        assert backend.get(t.task_id) is not None
        injector = DiskFaultInjector(seed=9)
        attach_journal_faults(s, injector)
        injector.add_rule(op="fsync", errno=errno.EIO)
        # The eviction completes (record in file, memory forgot it) —
        # no raise, and the orphaned blob is reaped.
        assert s.evict_terminal_older_than(0.0) == 1
        assert s.degraded
        with pytest.raises(TaskNotFound):
            s.get(t.task_id)
        assert backend.get(t.task_id) is None
        s.close()
        # Restart agrees: the journaled Evict record replays the task away.
        s2 = store_at(tmp_path, result_backend=backend)
        assert s2.snapshot() == []
        s2.close()
