"""Fleet metrics federation (observability/federation.py): parse/merge,
bounded-cardinality labels, the sound cross-tick conservation check with
its confirmed/advisory distinction, the live collector against real HTTP
targets, and the `top` dashboard renderer. JAX-free."""

from __future__ import annotations

import asyncio
import http.server
import threading

import pytest

from ai4e_tpu.metrics import MetricsRegistry
from ai4e_tpu.observability.federation import (FleetCollector, merge_series,
                                               parse_prometheus, render_key,
                                               role_of)
from ai4e_tpu.observability.top import render_top

GW_PAGE = """# HELP ai4e_gateway_requests_total Gateway requests
# TYPE ai4e_gateway_requests_total counter
ai4e_gateway_requests_total{outcome="created",route="/v1/echo"} 10
ai4e_gateway_requests_total{outcome="413",route="/v1/echo"} 1
ai4e_process_rss_bytes 1048576
ai4e_process_loop_lag_max_seconds 0.002
"""

STORE_PAGE = """ai4e_request_outcomes_total{outcome="ok",route="/v1/echo"} 6
ai4e_request_outcomes_total{outcome="failed",route="/v1/echo"} 1
ai4e_process_rss_bytes 2097152
"""


class _MetricsServer:
    """One fake role: serves a settable exposition page at /metrics."""

    def __init__(self, page: str):
        self.page = page
        outer = self

        class Handler(http.server.BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — stdlib contract
                body = outer.page.encode()
                self.send_response(200)
                self.send_header("Content-Type", "text/plain")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, *a):  # silence
                pass

        self.httpd = http.server.ThreadingHTTPServer(("127.0.0.1", 0),
                                                     Handler)
        self.thread = threading.Thread(target=self.httpd.serve_forever,
                                       daemon=True)
        self.thread.start()

    @property
    def url(self) -> str:
        return f"http://127.0.0.1:{self.httpd.server_address[1]}"

    def stop(self) -> None:
        self.httpd.shutdown()
        self.httpd.server_close()


@pytest.fixture()
def fake_fleet():
    gw = _MetricsServer(GW_PAGE)
    store = _MetricsServer(STORE_PAGE)
    yield gw, store
    gw.stop()
    store.stop()


class TestParseMerge:
    def test_parse_page(self):
        series = parse_prometheus(GW_PAGE)
        assert series[("ai4e_gateway_requests_total",
                       'outcome="created",route="/v1/echo"')] == 10
        assert series[("ai4e_process_rss_bytes", "")] == 1048576

    def test_merge_sums_same_keys(self):
        a = parse_prometheus(GW_PAGE)
        b = parse_prometheus(GW_PAGE)
        merged = merge_series({"g0": a, "g1": b})
        assert merged[("ai4e_gateway_requests_total",
                       'outcome="created",route="/v1/echo"')] == 20
        assert render_key(("x", "")) == "x"
        assert render_key(("x", 'a="1"')) == 'x{a="1"}'

    def test_role_of(self):
        assert role_of("gateway0") == "gateway"
        assert role_of("store1r0") == "store"
        assert role_of("dispatcher0.1") == "dispatcher"
        assert role_of("worker0.0") == "worker"
        assert role_of("balancer") == "balancer"

    def test_verdict_scrape_and_merge_delegates(self, fake_fleet):
        # The post-hoc teardown merge and the live collector share one
        # parse/merge core (the promotion satellite): verdict's output
        # shape is unchanged.
        from ai4e_tpu.rig.verdict import scrape_and_merge
        gw, store = fake_fleet
        view = scrape_and_merge({"gateway0": gw.url, "store0": store.url,
                                 "dead": "http://127.0.0.1:9"})
        assert view["unreachable"] == ["dead"]
        assert view["merged"][
            'ai4e_gateway_requests_total{outcome="created",'
            'route="/v1/echo"}'] == 10
        assert view["per_role_series"]["store0"] == 3


class TestFleetCollector:
    def _collect(self, coro):
        return asyncio.run(coro)

    def test_scrape_snapshot_and_merged_labels(self, fake_fleet):
        gw, store = fake_fleet
        m = MetricsRegistry()
        col = FleetCollector({"gateway0": gw.url, "store0": store.url},
                             metrics=m)

        async def run():
            await col.scrape_once()
            return col.snapshot(), col.render_merged()

        snap, merged = self._collect(run())
        assert snap["fleet"]["admitted"] == 10
        assert snap["fleet"]["terminal"] == 7
        assert snap["fleet"]["in_flight"] == 3
        assert snap["per_proc"]["gateway0"]["up"] is True
        assert snap["per_proc"]["gateway0"]["rss_bytes"] == 1048576
        assert snap["per_proc"]["store0"]["outcomes"] == {"ok": 6,
                                                          "failed": 1}
        assert snap["conservation"]["ok"] is True
        # Merged exposition carries proc+role labels and is itself
        # parseable by the same parser (round-trip honesty).
        reparsed = parse_prometheus(merged)
        assert reparsed[("ai4e_process_rss_bytes",
                         'proc="gateway0",role="gateway"')] == 1048576
        assert m.gauge("ai4e_fleet_up").value(proc="store0") == 1

    def test_dead_target_keeps_last_seen_lower_bound(self, fake_fleet):
        gw, store = fake_fleet
        col = FleetCollector({"gateway0": gw.url, "store0": store.url},
                             metrics=MetricsRegistry())

        async def run():
            await col.scrape_once()
            store.stop()
            await col.scrape_once()
            return col.snapshot()

        snap = self._collect(run())
        assert snap["per_proc"]["store0"]["up"] is False
        # The monotonic counters' last observation survives as a lower
        # bound — the fleet terminal count doesn't vanish with the proc.
        assert snap["fleet"]["terminal"] == 7

    def test_conservation_cross_tick_bound_confirmed(self, fake_fleet):
        """terminal(k) > admitted(k+1) is a REAL breach when no
        admitted-side proc was lost: more terminal outcomes than
        admissions ever issued."""
        gw, store = fake_fleet
        col = FleetCollector({"gateway0": gw.url, "store0": store.url},
                             metrics=MetricsRegistry())

        async def run():
            await col.scrape_once()
            # The store suddenly claims 99 completions while the gateway
            # only ever admitted 10 — a duplicate/phantom flood.
            store.page = STORE_PAGE.replace(
                'outcome="ok",route="/v1/echo"} 6',
                'outcome="ok",route="/v1/echo"} 99')
            await col.scrape_once()
            await col.scrape_once()
            return col.snapshot()

        snap = self._collect(run())
        cons = snap["conservation"]
        assert cons["ok"] is False
        assert cons["confirmed_violations"]
        assert cons["confirmed_violations"][0]["kind"] == \
            "terminal_exceeds_admitted"

    def test_no_false_positive_within_one_tick(self, fake_fleet):
        """The unsound same-tick comparison would flag terminal >
        admitted-as-scraped-earlier; the cross-tick bound must not: a
        fleet where completions caught up between the two reads is
        healthy."""
        gw, store = fake_fleet
        col = FleetCollector({"gateway0": gw.url, "store0": store.url},
                             metrics=MetricsRegistry())

        async def run():
            await col.scrape_once()
            # Both advance between ticks; terminal(k)=7 <= admitted(k+1).
            gw.page = GW_PAGE.replace("} 10", "} 20")
            store.page = STORE_PAGE.replace("} 6", "} 18")
            await col.scrape_once()
            return col.snapshot()

        snap = self._collect(run())
        assert snap["conservation"]["violations"] == []

    def test_gateway_loss_degrades_to_advisory(self, fake_fleet):
        """A chaos-killed gateway takes un-scraped admissions with it:
        later breaches are recorded but confirmed=false, and the
        overall conservation verdict stays ok (the journal verdict is
        authoritative for degraded runs — docs/deployment.md)."""
        gw, store = fake_fleet
        col = FleetCollector({"gateway0": gw.url, "store0": store.url},
                             metrics=MetricsRegistry())

        async def run():
            await col.scrape_once()
            gw.stop()  # the kill
            await col.scrape_once()
            store.page = STORE_PAGE.replace(
                'outcome="ok",route="/v1/echo"} 6',
                'outcome="ok",route="/v1/echo"} 50')
            await col.scrape_once()
            await col.scrape_once()
            return col.snapshot()

        snap = self._collect(run())
        cons = snap["conservation"]
        assert cons["degraded"] is True
        assert cons["violations"], "breach must still be RECORDED"
        assert all(not v["confirmed"] for v in cons["violations"])
        assert cons["ok"] is True

    def test_proc_cardinality_is_bounded(self, fake_fleet):
        gw, _store = fake_fleet
        col = FleetCollector({f"gateway{i}": gw.url for i in range(6)},
                             metrics=MetricsRegistry(), max_procs=4)

        async def run():
            await col.scrape_once()
            return col.render_merged()

        merged = self._collect(run())
        reparsed = parse_prometheus(merged)
        procs = {lbl for (_n, lbl) in reparsed}
        assert any('proc="other"' in lbl for lbl in procs)
        named = {lbl for lbl in procs if 'proc="gateway' in lbl}
        assert len({lbl.split('proc="')[1].split('"')[0]
                    for lbl in named}) == 4
        # The overflow procs' series still COUNT (collapsed, not lost).
        assert reparsed[("ai4e_process_rss_bytes",
                         'proc="other",role="other"')] == 2 * 1048576

    def test_requires_targets(self):
        with pytest.raises(ValueError):
            FleetCollector({})


class TestTopRenderer:
    def _snap(self, t=100.0, req=50.0):
        return {
            "t": t, "targets": 2, "ticks": 1,
            "fleet": {"admitted": 10, "terminal": 7, "in_flight": 3,
                      "up": 2},
            "conservation": {"ok": True, "violations": [],
                             "confirmed_violations": [],
                             "degraded": False},
            "per_proc": {
                "gateway0": {"role": "gateway", "up": True,
                             "requests_total": req,
                             "outcomes": {}, "loop_lag_max_s": 0.004,
                             "rss_bytes": 50 * 1024 * 1024,
                             "open_fds": 12, "slo_burn_max": None},
                "store0": {"role": "store", "up": True,
                           "requests_total": 0.0,
                           "outcomes": {"ok": 6, "failed": 1},
                           "loop_lag_max_s": None, "rss_bytes": None,
                           "open_fds": None, "slo_burn_max": 2.5},
            },
        }

    def test_frame_contents_and_rates(self):
        prev = self._snap(t=100.0, req=50.0)
        cur = self._snap(t=102.0, req=70.0)
        frame = render_top(cur, prev)
        assert "conservation OK" in frame
        assert "gateway0" in frame and "store0" in frame
        assert "10.0" in frame          # (70-50)/2s
        assert "85.7%" in frame         # 6 ok / 7 terminal
        assert "4ms" in frame           # loop lag
        assert "50M" in frame           # rss
        assert "2.5" in frame           # burn

    def test_violated_and_degraded_frame(self):
        snap = self._snap()
        snap["conservation"] = {
            "ok": False, "degraded": True,
            "violations": [{"kind": "terminal_exceeds_admitted",
                            "confirmed": True, "t": 1.0}],
            "confirmed_violations": [{"kind": "terminal_exceeds_admitted",
                                      "confirmed": True, "t": 1.0}]}
        frame = render_top(snap)
        assert "VIOLATED" in frame
        assert "degraded" in frame
        assert "confirmed conservation violation" in frame

    def test_once_against_live_collector(self, fake_fleet):
        """`top --targets ... --once` end-to-end: one frame, exit 0."""
        from ai4e_tpu.observability.top import run_top
        gw, store = fake_fleet
        frames = []
        rc = asyncio.run(run_top(
            targets=f"gateway0={gw.url},store0={store.url}",
            once=True, out=frames.append))
        assert rc == 0
        assert len(frames) == 1
        assert "gateway0" in frames[0]
        assert "admitted 10" in frames[0]

    def test_top_requires_a_source(self):
        from ai4e_tpu.observability.top import run_top
        assert asyncio.run(run_top()) == 2


class TestConservationSoundness:
    def test_counter_reset_degrades_to_advisory(self, fake_fleet):
        """A supervisor-RESTARTED gateway resets its registry without
        the scrape ever failing (the replacement answers the next tick)
        — the up→down heuristic can't see it, but the monotonic counter
        going backward can. Breaches after a reset must be advisory,
        not a false CONFIRMED conviction the journals would overturn."""
        gw, store = fake_fleet
        col = FleetCollector({"gateway0": gw.url, "store0": store.url},
                             metrics=MetricsRegistry())

        async def run():
            await col.scrape_once()
            # Restart: admitted counter falls 10 -> 2 between ticks
            # while the terminal side keeps its history.
            gw.page = GW_PAGE.replace("} 10", "} 2")
            await col.scrape_once()
            await col.scrape_once()
            return col.snapshot()

        snap = asyncio.run(run())
        cons = snap["conservation"]
        assert cons["degraded"] is True
        assert cons["violations"], "the breach is still RECORDED"
        assert all(not v["confirmed"] for v in cons["violations"])
        assert cons["ok"] is True

    def test_conservation_off_is_view_only(self, fake_fleet):
        """conservation=False (top --targets, non-rig surfaces whose
        sync/refusal outcomes never had an admission): totals still
        serve, no violations ever recorded, snapshot says unchecked."""
        gw, store = fake_fleet
        col = FleetCollector({"gateway0": gw.url, "store0": store.url},
                             metrics=MetricsRegistry(),
                             conservation=False)

        async def run():
            await col.scrape_once()
            # A shape that WOULD violate: terminal >> admitted.
            store.page = STORE_PAGE.replace(
                'outcome="ok",route="/v1/echo"} 6',
                'outcome="ok",route="/v1/echo"} 99')
            await col.scrape_once()
            await col.scrape_once()
            return col.snapshot()

        snap = asyncio.run(run())
        assert snap["conservation"]["checked"] is False
        assert snap["conservation"]["violations"] == []
        assert snap["fleet"]["terminal"] == 100  # the view still serves
        frame = render_top(snap)
        assert "conservation unchecked" in frame
