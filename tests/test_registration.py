"""API-registration customizer tests — typed ApiDefinition → gateway routes
(the reference's api_management_customizer.py:4-44 +
create_*_api_management_api.sh registration flow as code)."""

import asyncio
import json

from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.gateway import (
    ApiDefinition,
    load_definitions,
    register_definitions,
    routes_from_definitions,
)
from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig


def run(coro):
    return asyncio.run(coro)


async def serve(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


class TestDefinitionShapes:
    def test_public_prefix_matches_reference_url_shape(self):
        # /{version}/{organization}/{api} — the shape AddPipelineTask builds
        # (distributed_api_task.py:74-75).
        d = ApiDefinition(organization="camera-trap", api="detection",
                          backend_host="http://worker:8081")
        assert d.public_prefix == "/v1/camera-trap/detection"
        assert d.backend_uri == "http://worker:8081/v1/detection"

    def test_backend_path_override(self):
        d = ApiDefinition(organization="org", api="seg",
                          backend_host="http://w:1/",
                          backend_path="/v1/landcover/classify-async")
        assert d.backend_uri == "http://w:1/v1/landcover/classify-async"

    def test_routes_rendering(self):
        defs = [
            ApiDefinition(organization="o", api="a",
                          backend_host="http://w:1", concurrency=4,
                          autoscale={"max_replicas": 8}),
            ApiDefinition(organization="o", api="b",
                          backend_host="http://w:1", mode="sync"),
        ]
        spec = routes_from_definitions(defs)
        assert spec["apis"][0] == {
            "prefix": "/v1/o/a", "backend": "http://w:1/v1/a",
            "mode": "async", "concurrency": 4,
            "autoscale": {"max_replicas": 8}}
        assert spec["apis"][1]["mode"] == "sync"

    def test_load_definitions(self, tmp_path):
        p = tmp_path / "apis.json"
        p.write_text(json.dumps({"apis": [
            {"organization": "o", "api": "a", "backend_host": "http://w:1",
             "operations": ["classify", "tile"]}]}))
        defs = load_definitions(str(p))
        assert defs[0].operations == ("classify", "tile")


class TestRegisterOnPlatform:
    def test_async_definition_served_end_to_end(self):
        async def main():
            platform = LocalPlatform(PlatformConfig(retry_delay=0.05))
            svc = platform.make_service("det", prefix="v1/detection")

            @svc.api_async_func("/detect")
            def detect(taskId, body, content_type):
                asyncio.run(platform.task_manager.complete_task(
                    taskId, "completed - registered"))

            svc_client = await serve(svc.app)
            register_definitions(platform, [ApiDefinition(
                organization="camera-trap", api="detection",
                backend_host=str(svc_client.make_url("")).rstrip("/"),
                backend_path="/v1/detection/detect")])
            gw = await serve(platform.gateway.app)
            await platform.start()
            try:
                resp = await gw.post("/v1/camera-trap/detection", data=b"x")
                tid = (await resp.json())["TaskId"]
                final = None
                for _ in range(200):
                    r = await gw.get(f"/v1/taskmanagement/task/{tid}")
                    final = await r.json()
                    if "completed" in final["Status"]:
                        break
                    await asyncio.sleep(0.02)
                assert final["Status"] == "completed - registered"
            finally:
                await platform.stop()
                await gw.close()
                await svc_client.close()

        run(main())

    def test_definitions_key_in_control_plane_spec(self):
        from ai4e_tpu.cli import build_control_plane
        from ai4e_tpu.config import FrameworkConfig

        platform = build_control_plane(FrameworkConfig(), {
            "definitions": [{"organization": "o", "api": "a",
                             "backend_host": "http://w:1"}]})
        assert any(r.prefix == "/v1/o/a" for r in platform.gateway.routes)
