"""Expert-parallel MoE family (models/moe.py): expert tensors shard over the
mesh's ep axis, the sharded forward matches the single-device oracle, and the
family serves through the runtime on an ep mesh — the ep analogue of the sp
coverage in test_seqformer.py."""

import jax
import numpy as np

from ai4e_tpu.models.moe import create_moe
from ai4e_tpu.parallel import MeshSpec, make_mesh

SEQ, DIM_IN = 128, 16


def small_moe(mesh=None, experts=8):
    return create_moe(seq_len=SEQ, input_dim=DIM_IN, dim=32, depth=1,
                      heads=2, num_experts=experts, num_classes=4,
                      mesh=mesh, attention="full")


class TestExpertSharding:
    def test_expert_tensors_carry_ep_spec(self):
        mesh = make_mesh(MeshSpec(dp=2, ep=4), devices=jax.devices()[:8])
        _, params = small_moe(mesh)
        up = params["params"]["block0"]["moe"]["up"]
        assert "ep" in str(up.sharding.spec), up.sharding
        shard = up.sharding.shard_shape(up.shape)
        assert shard[0] == up.shape[0] // 4, (shard, up.shape)
        # Non-expert params replicate over ep.
        emb = params["params"]["embed"]["kernel"]
        assert "ep" not in str(emb.sharding.spec)

    def test_expert_count_must_divide_ep(self):
        import pytest

        mesh = make_mesh(MeshSpec(dp=2, ep=4), devices=jax.devices()[:8])
        with pytest.raises(ValueError, match="not divisible"):
            small_moe(mesh, experts=6)


class TestEpEquivalence:
    def test_sharded_forward_matches_single_device(self):
        x = np.random.default_rng(0).standard_normal(
            (4, SEQ, DIM_IN)).astype(np.float32)

        model_1d, params_1d = small_moe(mesh=None)
        want = np.asarray(jax.jit(model_1d.apply)(params_1d, x))

        mesh = make_mesh(MeshSpec(dp=2, ep=4), devices=jax.devices()[:8])
        model_ep, params_ep = small_moe(mesh)  # same rng → same values
        with mesh:
            got = np.asarray(jax.jit(model_ep.apply)(params_ep, x))
        # bf16 matmuls + ep psum reorder → loose-ish tolerance.
        np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-2)
        assert np.all(np.isfinite(got))


class TestMoEServing:
    def test_family_serves_on_ep_mesh(self):
        from ai4e_tpu.runtime import ModelRuntime, build_servable

        mesh = make_mesh(MeshSpec(dp=2, ep=4), devices=jax.devices()[:8])
        runtime = ModelRuntime(mesh=mesh)
        servable = build_servable(
            "moe", name="moe", seq_len=SEQ, input_dim=DIM_IN, dim=32,
            depth=1, heads=2, num_experts=8, num_classes=4,
            attention="full", buckets=(2,), mesh=mesh)
        runtime.register(servable)
        # register() re-places params on its mesh — the expert sharding must
        # SURVIVE it (rules ride on the servable), or "expert parallel"
        # would silently serve fully-replicated experts.
        up = runtime.models["moe"].params["params"]["block0"]["moe"]["up"]
        assert "ep" in str(up.sharding.spec), up.sharding
        assert up.sharding.shard_shape(up.shape)[0] == up.shape[0] // 4
        batch = np.random.default_rng(1).standard_normal(
            (servable.batch_buckets[0], SEQ, DIM_IN)).astype(np.float32)
        out = np.asarray(runtime.run_batch("moe", batch))
        assert out.shape == (servable.batch_buckets[0], 4)
        assert np.all(np.isfinite(out))
        # Per-example postprocess yields the classifier payload.
        res = servable.postprocess(out[0])
        assert set(res) >= {"class_id", "confidence"}


class TestCapacityDispatch:
    def test_matches_dense_when_capacity_ample(self):
        """With capacity_factor high enough that nothing drops, the
        static-capacity gather/scatter must reproduce the dense one-hot
        combine (same params, same router decisions)."""
        x = np.random.default_rng(3).standard_normal(
            (2, SEQ, DIM_IN)).astype(np.float32)
        dense_m, params = create_moe(
            seq_len=SEQ, input_dim=DIM_IN, dim=32, depth=1, heads=2,
            num_experts=4, num_classes=4, attention="full",
            dispatch="dense")
        cap_m, _ = create_moe(
            seq_len=SEQ, input_dim=DIM_IN, dim=32, depth=1, heads=2,
            num_experts=4, num_classes=4, attention="full",
            dispatch="capacity", capacity_factor=4.0)  # C == T: no drops
        want = np.asarray(jax.jit(dense_m.apply)(params, x))
        got = np.asarray(jax.jit(cap_m.apply)(params, x))
        np.testing.assert_allclose(got, want, rtol=4e-2, atol=4e-2)

    def test_overflow_drops_are_survivable(self):
        """Starved capacity (C ~ T/8) drops most tokens to the residual —
        output must stay finite and well-shaped, not NaN or crash."""
        x = np.random.default_rng(4).standard_normal(
            (2, SEQ, DIM_IN)).astype(np.float32)
        cap_m, params = create_moe(
            seq_len=SEQ, input_dim=DIM_IN, dim=32, depth=1, heads=2,
            num_experts=4, num_classes=4, attention="full",
            dispatch="capacity", capacity_factor=0.125)
        out = np.asarray(jax.jit(cap_m.apply)(params, x))
        assert out.shape == (2, 4)
        assert np.all(np.isfinite(out))

    def test_capacity_on_ep_mesh_matches_single_device(self):
        x = np.random.default_rng(5).standard_normal(
            (4, SEQ, DIM_IN)).astype(np.float32)
        m1, p1 = create_moe(seq_len=SEQ, input_dim=DIM_IN, dim=32, depth=1,
                            heads=2, num_experts=8, num_classes=4,
                            attention="full", dispatch="capacity")
        want = np.asarray(jax.jit(m1.apply)(p1, x))

        mesh = make_mesh(MeshSpec(dp=2, ep=4), devices=jax.devices()[:8])
        m2, p2 = create_moe(seq_len=SEQ, input_dim=DIM_IN, dim=32, depth=1,
                            heads=2, num_experts=8, num_classes=4,
                            attention="full", dispatch="capacity", mesh=mesh)
        with mesh:
            got = np.asarray(jax.jit(m2.apply)(p2, x))
        np.testing.assert_allclose(got, want, rtol=4e-2, atol=4e-2)


class TestTokenMode:
    """``vocab_size`` switches the family to (S,) token-id input with
    on-device embedding — the same production wire as the seqformer family,
    composed with expert parallelism."""

    def test_token_forward_matches_across_ep_mesh(self):
        toks = np.random.default_rng(5).integers(
            0, 40, size=(4, SEQ), dtype=np.int32)
        model_1d, params = create_moe(
            seq_len=SEQ, input_dim=DIM_IN, dim=32, depth=1, heads=2,
            num_experts=8, num_classes=4, attention="full", vocab_size=40)
        want = np.asarray(jax.jit(model_1d.apply)(params, toks))

        mesh = make_mesh(MeshSpec(dp=2, ep=4), devices=jax.devices()[:8])
        model_ep, params_ep = create_moe(
            seq_len=SEQ, input_dim=DIM_IN, dim=32, depth=1, heads=2,
            num_experts=8, num_classes=4, attention="full", vocab_size=40,
            mesh=mesh)
        with mesh:
            got = np.asarray(jax.jit(model_ep.apply)(params_ep, toks))
        np.testing.assert_allclose(got, want, rtol=3e-2, atol=3e-2)

    def test_token_servable_validates_and_scores(self):
        import io

        from ai4e_tpu.runtime import build_servable

        sv = build_servable(
            "moe", name="moetok", seq_len=SEQ, dim=32, depth=1, heads=2,
            num_experts=8, num_classes=4, attention="full", buckets=(1,),
            vocab_size=40)
        assert sv.input_shape == (SEQ,)
        assert np.dtype(sv.input_dtype) == np.int32
        toks = np.random.default_rng(6).integers(
            0, 40, size=(SEQ,), dtype=np.uint16)
        buf = io.BytesIO(); np.save(buf, toks)
        ex = sv.preprocess(buf.getvalue(), "application/octet-stream")
        out = sv.postprocess(np.asarray(sv.apply_fn(sv.params, ex[None])[0]))
        assert 0 <= out["class_id"] < 4
        # Range violations fail the one task at preprocess.
        import pytest
        bad = np.full((SEQ,), 40, np.int64)
        buf = io.BytesIO(); np.save(buf, bad)
        with pytest.raises(ValueError, match=r"\[0, 40\)"):
            sv.preprocess(buf.getvalue(), "application/octet-stream")
