"""Disk-fault chaos + the crash-point sweep (docs/durability.md) — the
storage-layer acceptance scenarios of the durable-truth hardening:

(a) **crash-point sweep** — a journaled store is killed/restarted at
    EVERY record boundary and at seeded mid-record offsets (torn
    writes / lost page cache) across seeds 1/2/3/7/42 + the CI pin:
    every restart boots without crash-looping, 0 acknowledged-task
    loss (``fsync=always`` markers), no conflicting state, and a fresh
    replica absorbing the rebooted journal converges chain-head- and
    snapshot-identically;

(b) **degraded mode at the edge** — seeded ENOSPC mid-append + EIO on
    fsync flip an unsharded control plane to fenced read-only degraded
    mode: task creation answers the typed 503 +
    ``X-Shed-Reason: journal-degraded`` while reads keep serving, and
    ``recover()`` re-admits the node (traffic completes again);

(c) **disk faults composed with failover + rebalance** — on a 4-shard
    store under load with seeded HTTP faults, one shard's primary disk
    faults (torn ENOSPC append): the facade fails over to its replica
    at epoch+1 and traffic completes through it; a SECOND shard's
    primary is SIGKILLed (``kill_shard_primary``) and a slot is
    live-rebalanced (``move_slot``) on top — invariants clean per
    shard AND globally, replicas chain-converged with their primaries.

All seeded; the CI ``durability-smoke`` job runs this file JAX-free with
the pinned ``AI4E_CHAOS_SEED``.
"""

import asyncio
import errno
import os

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.chaos import (DiskFaultInjector, FaultInjector,
                            InvariantChecker, attach_journal_faults,
                            kill_shard_primary, rebalance_slot, sweep,
                            wrap_platform_http)
from ai4e_tpu.metrics import MetricsRegistry
from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
from ai4e_tpu.taskstore import TaskStatus

SEED = int(os.environ.get("AI4E_CHAOS_SEED", "20260803"))
SHARDS = 4


def run(coro):
    return asyncio.run(coro)


async def serve(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


async def _drain(checker, deadline_s=30.0):
    deadline = asyncio.get_running_loop().time() + deadline_s
    while asyncio.get_running_loop().time() < deadline:
        if all(tid in checker.terminal for tid in checker.accepted):
            return
        await asyncio.sleep(0.05)


def _completing_backend(platform):
    async def handler(request):
        tid = request.headers["taskId"]
        platform.store.update_status_if(
            tid, "created", f"completed - {len(await request.read())}b",
            TaskStatus.COMPLETED)
        return web.Response(text="ok")

    app = web.Application()
    app.router.add_post("/v1/be/x", handler)
    return app


@pytest.mark.chaos
@pytest.mark.durability
class TestCrashPointSweep:
    @pytest.mark.parametrize("seed", sorted({1, 2, 3, 7, 42, SEED % 1000}))
    def test_every_crash_point_reboots_clean_fsync_always(
            self, tmp_path, seed):
        """fsync=always: the ack marker is durable at ack time, so the
        sweep proves the LITERAL 0-acknowledged-task-loss claim at every
        boundary and mid-record offset."""
        points, violations = sweep(str(tmp_path), seed, fsync="always",
                                   ops=34, mid_points=10)
        assert points > 20
        assert violations == []

    def test_sweep_holds_under_fsync_never_file_shapes(self, tmp_path):
        """fsync=never (the default): the same byte-conditional contract
        — the rebooted state equals exactly the surviving prefix's
        acknowledged history (the residual window is WHICH prefix
        survives, never a half-applied or crash-looping store)."""
        points, violations = sweep(str(tmp_path), SEED, fsync="never",
                                   ops=30, mid_points=10)
        assert points > 20
        assert violations == []


@pytest.mark.chaos
@pytest.mark.durability
class TestDegradedEdge:
    def test_enospc_and_eio_degrade_then_recovery_readmits(self, tmp_path):
        async def main():
            metrics = MetricsRegistry()
            platform = LocalPlatform(PlatformConfig(
                journal_path=str(tmp_path / "journal"),
                taskstore_fsync="always",
                retry_delay=0.01), metrics=metrics)
            checker = InvariantChecker().attach(platform.store)
            be = await serve(_completing_backend(platform))
            platform.publish_async_api("/v1/pub/x",
                                       str(be.make_url("/v1/be/x")))
            gw = await serve(platform.gateway.app)
            await platform.start()
            try:
                # Healthy traffic first.
                resp = await gw.post("/v1/pub/x", data=b"before")
                assert resp.status == 200
                before = (await resp.json())["TaskId"]
                checker.note_accepted(before)
                await _drain(checker)

                # Seeded disk faults: EIO on the next fsync, then ENOSPC
                # torn appends for anything after.
                disk = DiskFaultInjector(seed=SEED)
                disk.add_rule(op="fsync", errno=errno.EIO)
                disk.add_rule(op="write", errno=errno.ENOSPC,
                              torn_bytes=20, times=None)
                attach_journal_faults(platform.store, disk)

                # Task creation now refuses with the TYPED 503 — nothing
                # is created or published (memory never runs ahead).
                resp = await gw.post("/v1/pub/x", data=b"doomed")
                assert resp.status == 503
                assert resp.headers["X-Shed-Reason"] == "journal-degraded"
                assert "X-Not-Primary" not in resp.headers
                assert platform.store.degraded
                assert disk.counts()  # the injector actually fired

                # Reads keep serving through the degradation.
                resp = await gw.get(f"/v1/taskmanagement/task/{before}")
                assert resp.status == 200
                assert metrics.counter(
                    "ai4e_gateway_requests_total", "").value(
                        route="/v1/pub/x",
                        outcome="journal_degraded") >= 1

                # Disk heals → recover() re-admits the node; traffic
                # completes end to end again.
                disk.clear()
                assert platform.store.recover()
                resp = await gw.post("/v1/pub/x", data=b"after")
                assert resp.status == 200
                checker.note_accepted((await resp.json())["TaskId"])
                await _drain(checker)
                checker.assert_ok()
            finally:
                await platform.stop()
                await gw.close()
                await be.close()

        run(main())


@pytest.mark.chaos
@pytest.mark.durability
class TestDegradedCacheHit:
    def test_cache_hit_on_degraded_store_answers_typed_503(self, tmp_path):
        """Review regression: the cache-hit path creates a real (memory-
        only) task record too, and its upsert caught only NotPrimaryError
        — on a journal-degraded store the duplicate request escaped the
        typed handler as a generic 500. It must fall through to the same
        503 + X-Shed-Reason the ordinary create path ships."""
        async def main():
            platform = LocalPlatform(PlatformConfig(
                journal_path=str(tmp_path / "journal"),
                result_cache=True,
                retry_delay=0.01), metrics=MetricsRegistry())

            # The cache fills from a completed task's RESULT — this
            # backend writes one (the shared completer only flips
            # status).
            async def handler(request):
                tid = request.headers["taskId"]
                platform.store.set_result(tid, b"cached-answer")
                platform.store.update_status_if(
                    tid, "created", "completed - ok",
                    TaskStatus.COMPLETED)
                return web.Response(text="ok")

            app = web.Application()
            app.router.add_post("/v1/be/x", handler)
            be = await serve(app)
            platform.publish_async_api("/v1/pub/x",
                                       str(be.make_url("/v1/be/x")))
            gw = await serve(platform.gateway.app)
            await platform.start()
            try:
                # Seed the cache with one completed request, then wait
                # until a duplicate actually rides it.
                resp = await gw.post("/v1/pub/x", data=b"dup-payload")
                assert resp.status == 200
                deadline = asyncio.get_running_loop().time() + 15.0
                hit = False
                while asyncio.get_running_loop().time() < deadline:
                    r = await gw.post("/v1/pub/x", data=b"dup-payload")
                    if r.headers.get("X-Cache") == "hit":
                        hit = True
                        break
                    await asyncio.sleep(0.05)
                assert hit, "cache never served the duplicate request"

                # Degrade the store with a non-cached write.
                disk = DiskFaultInjector(seed=SEED)
                disk.add_rule(op="write", errno=errno.ENOSPC, times=None)
                attach_journal_faults(platform.store, disk)
                r = await gw.post("/v1/pub/x", data=b"not-cached")
                assert r.status == 503
                assert platform.store.degraded

                # The DUPLICATE request — a cache hit — now refuses with
                # the same typed 503, never a 500.
                r = await gw.post("/v1/pub/x", data=b"dup-payload")
                assert r.status == 503
                assert r.headers["X-Shed-Reason"] == "journal-degraded"
                assert "X-Not-Primary" not in r.headers
            finally:
                await platform.stop()
                await gw.close()
                await be.close()

        run(main())


@pytest.mark.chaos
@pytest.mark.durability
class TestDiskFaultsComposedWithFailoverAndRebalance:
    def test_degraded_shard_fails_over_kill_and_rebalance_on_top(
            self, tmp_path):
        async def main():
            platform = LocalPlatform(PlatformConfig(
                task_shards=SHARDS,
                journal_path=str(tmp_path / "journal"),
                shard_tail_interval=0.02,
                resilience=True,
                retry_delay=0.01,
                lease_seconds=2.0,
                resilience_retry_base_s=0.001,
                resilience_failure_threshold=3,
                resilience_recovery_seconds=0.1,
            ), metrics=MetricsRegistry())
            checker = InvariantChecker(
                shard_of=platform.store.shard_for).attach(platform.store)
            be = await serve(_completing_backend(platform))
            platform.publish_async_api("/v1/pub/x",
                                       str(be.make_url("/v1/be/x")))
            injector = FaultInjector(seed=SEED)
            injector.add_rule(error_rate=0.15, error_status=500,
                              drop_rate=0.05)
            wrap_platform_http(platform, injector)
            gw = await serve(platform.gateway.app)
            await platform.start()
            try:
                async def accept(n):
                    for _ in range(n):
                        resp = await gw.post("/v1/pub/x", data=b"payload")
                        assert resp.status == 200
                        checker.note_accepted(
                            (await resp.json())["TaskId"])

                await accept(16)

                # Disk-fault one shard's primary: torn ENOSPC appends +
                # EIO on any fsync. The NEXT write routed there flips it
                # degraded and the facade promotes its replica inline —
                # the journal FILE (all acknowledged writes) is the
                # durable truth the replica drains.
                victim = platform.store.shard_for(
                    sorted(checker.accepted)[0])
                pre_epoch = platform.store.groups[victim].epoch
                disk = DiskFaultInjector(seed=SEED)
                disk.add_rule(op="write", errno=errno.ENOSPC,
                              torn_bytes=25, times=None)
                disk.add_rule(op="fsync", errno=errno.EIO, times=None)
                attach_journal_faults(
                    platform.store.groups[victim].active, disk)

                # Traffic continues: the degraded shard fails over, the
                # other shards never notice. Routing is hash-random, so
                # trickle bounded extra writes until one lands on the
                # victim and trips the inline promotion.
                await accept(12)
                for _ in range(16):
                    if platform.store.groups[victim].epoch > pre_epoch:
                        break
                    await accept(4)
                await _drain(checker)
                assert platform.store.groups[victim].epoch == pre_epoch + 1
                assert not platform.store.groups[victim].dead

                # Compose a PROCESS kill on a second shard mid-traffic.
                others = [i for i in range(SHARDS) if i != victim]
                killed = others[0]
                kill_shard_primary(platform, killed)
                await accept(12)
                for _ in range(16):
                    if platform.store.groups[killed].epoch >= 1:
                        break
                    await accept(4)
                await _drain(checker)
                assert platform.store.groups[killed].epoch >= 1

                # And a live rebalance on top: move one accepted task's
                # slot between the two untouched shards (src may be any
                # shard — including a promoted one, whose journal must
                # accept the migration records).
                store = platform.store
                target = sorted(checker.accepted)[-1]
                slot = store.ring.slot_for(target)
                src = store.ring.shard_of_slot(slot)
                dest = next(i for i in range(SHARDS) if i != src)
                rebalance_slot(platform, slot, dest)
                assert store.ring.shard_of_slot(slot) == dest
                await accept(8)
                await _drain(checker)

                # Verdicts: global + per shard, zero lost / zero dup,
                # and every surviving replica chain-converged with its
                # primary.
                checker.assert_ok()
                for i in range(SHARDS):
                    checker.assert_shard_ok(i)
                per_shard = checker.by_shard()
                assert sum(s["accepted"]
                           for s in per_shard.values()) == len(
                               checker.accepted)
                assert len(checker.accepted) >= 48
                for shard, stats in sorted(per_shard.items()):
                    assert stats["terminal"] == stats["accepted"], (
                        shard, stats)
                    assert stats["duplicates"] == 0, (shard, stats)
                checker.assert_replicas_converged(store)
                # Both injectors actually fired.
                assert injector.counts().get("error", 0) > 0
                assert disk.counts()
            finally:
                await platform.stop()
                await gw.close()
                await be.close()

        run(main())
