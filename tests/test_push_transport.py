"""Push (webhook) transport tests — the reference's `eventgrid` TRANSPORT_TYPE
(``deploy_infrastructure.sh:13-27``): topic publish → HTTP push to the webhook
dispatcher → backend POST, with subscription-validation handshake
(``BackendWebhook.cs:47-55``), 429 pass-through retry (``:69-72``), and the
TTL/max-attempts delivery policy (``deploy_event_grid_subscription.sh:37``)."""

import asyncio

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.broker.push import (
    PushTopic,
    SubscriptionError,
    VALIDATION_EVENT,
    WebhookDispatcher,
)
from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
from ai4e_tpu.service import LocalTaskManager
from ai4e_tpu.taskstore import InMemoryTaskStore


def run(coro):
    return asyncio.run(coro)


async def serve(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


async def poll_until(client, task_id, predicate, tries=400, delay=0.02):
    body = None
    for _ in range(tries):
        resp = await client.get(f"/v1/taskmanagement/task/{task_id}")
        body = await resp.json()
        if predicate(body):
            return body
        await asyncio.sleep(delay)
    return body


class TestHandshake:
    def test_webhook_echoes_validation_code(self):
        async def main():
            store = InMemoryTaskStore()
            webhook = WebhookDispatcher(LocalTaskManager(store))
            client = await serve(webhook.app)
            try:
                resp = await client.post("/api/events", json=[{
                    "EventType": VALIDATION_EVENT, "ValidationCode": "c0de"}])
                assert resp.status == 200
                assert (await resp.json()) == {"validationResponse": "c0de"}
            finally:
                await client.close()

        run(main())

    def test_subscribe_rejects_bad_echo(self):
        async def main():
            async def bad_handler(request):
                return web.json_response({"validationResponse": "WRONG"})

            app = web.Application()
            app.router.add_post("/api/events", bad_handler)
            client = await serve(app)
            topic = PushTopic()
            try:
                with pytest.raises(SubscriptionError):
                    await topic.subscribe(
                        "bad", str(client.make_url("/api/events")))
                assert topic._subscriptions == []
            finally:
                await topic.aclose()
                await client.close()

        run(main())


class TestPushE2E:
    def test_full_async_lifecycle_over_push(self):
        async def main():
            platform = LocalPlatform(PlatformConfig(
                transport="push", retry_delay=0.05))
            svc = platform.make_service("detector", prefix="v1/detector")

            @svc.api_async_func("/detect")
            def detect(taskId, body, content_type):
                asyncio.run(platform.task_manager.complete_task(
                    taskId, f"completed - {len(body)} bytes scored"))

            svc_client = await serve(svc.app)
            backend_uri = str(svc_client.make_url("/v1/detector/detect"))
            platform.publish_async_api("/v1/camera-trap/detect", backend_uri)
            gw_client = await serve(platform.gateway.app)
            await platform.start()
            try:
                resp = await gw_client.post("/v1/camera-trap/detect",
                                            data=b"JPEGDATA")
                assert resp.status == 200
                created = await resp.json()
                assert created["Status"] == "created"
                final = await poll_until(
                    gw_client, created["TaskId"],
                    lambda b: "completed" in b["Status"])
                assert final["Status"] == "completed - 8 bytes scored"
            finally:
                await platform.stop()
                await gw_client.close()
                await svc_client.close()

        run(main())

    def test_backpressure_retries_via_topic(self):
        # Saturated (cap-1) backend: webhook passes 429/503 back to the topic,
        # whose backoff schedule retries the delivery until it lands.
        async def main():
            platform = LocalPlatform(PlatformConfig(
                transport="push", retry_delay=0.05,
                push_max_attempts=50))
            svc = platform.make_service("slow", prefix="v1/slow")
            import threading
            gate = threading.Semaphore(1)

            @svc.api_async_func("/work", maximum_concurrent_requests=1)
            def work(taskId, body, content_type):
                with gate:
                    import time as _t
                    _t.sleep(0.05)
                asyncio.run(platform.task_manager.complete_task(
                    taskId, "completed"))

            svc_client = await serve(svc.app)
            platform.publish_async_api(
                "/v1/public/work", str(svc_client.make_url("/v1/slow/work")))
            gw_client = await serve(platform.gateway.app)
            await platform.start()
            try:
                ids = []
                for _ in range(4):
                    resp = await gw_client.post("/v1/public/work", data=b"x")
                    ids.append((await resp.json())["TaskId"])
                for tid in ids:
                    final = await poll_until(
                        gw_client, tid, lambda b: "completed" in b["Status"])
                    assert "completed" in final["Status"], final
            finally:
                await platform.stop()
                await gw_client.close()
                await svc_client.close()

        run(main())

    def test_exhausted_delivery_fails_task(self):
        # Unreachable backend: after max_attempts the event dead-letters and
        # the platform fails the task (terminal, not stuck non-terminal).
        async def main():
            platform = LocalPlatform(PlatformConfig(
                transport="push", retry_delay=0.02, push_max_attempts=2))
            platform.publish_async_api(
                "/v1/public/never", "http://127.0.0.1:1/v1/never")
            gw_client = await serve(platform.gateway.app)
            await platform.start()
            try:
                resp = await gw_client.post("/v1/public/never", data=b"x")
                tid = (await resp.json())["TaskId"]
                final = await poll_until(
                    gw_client, tid, lambda b: "failed" in b["Status"])
                assert "failed" in final["Status"], final
            finally:
                await platform.stop()
                await gw_client.close()

        run(main())

    def test_unroutable_subject_fails_task(self):
        async def main():
            platform = LocalPlatform(PlatformConfig(transport="push"))
            # Route registered on the gateway only — the webhook has no
            # backend mapping for it.
            platform.gateway.add_async_route(
                "/v1/public/ghost", "http://127.0.0.1:1/v1/ghost/run")
            gw_client = await serve(platform.gateway.app)
            await platform.start()
            try:
                resp = await gw_client.post("/v1/public/ghost", data=b"x")
                tid = (await resp.json())["TaskId"]
                final = await poll_until(
                    gw_client, tid, lambda b: "failed" in b["Status"])
                assert "no backend route" in final["Status"], final
            finally:
                await platform.stop()
                await gw_client.close()

        run(main())

    def test_pipeline_over_push(self):
        # §3.4 pipelining rides the push transport too: stage-1 republishes
        # under the same TaskId; the webhook routes stage-2 to its backend and
        # the store replays the original body.
        async def main():
            platform = LocalPlatform(PlatformConfig(
                transport="push", retry_delay=0.05))
            seen = {}
            det = platform.make_service("det", prefix="v1/det")
            cls = platform.make_service("cls", prefix="v1/cls")

            @det.api_async_func("/detect")
            def detect(taskId, body, content_type):
                asyncio.run(platform.task_manager.add_pipeline_task(
                    taskId, cls_backend))

            @cls.api_async_func("/classify")
            def classify(taskId, body, content_type):
                seen["stage2_body"] = body
                asyncio.run(platform.task_manager.complete_task(
                    taskId, "completed - classified"))

            det_client = await serve(det.app)
            cls_client = await serve(cls.app)
            det_backend = str(det_client.make_url("/v1/det/detect"))
            cls_backend = str(cls_client.make_url("/v1/cls/classify"))
            platform.publish_async_api("/v1/pipeline/detect", det_backend)
            platform.webhook.add_route("/v1/cls/classify", cls_backend)
            gw_client = await serve(platform.gateway.app)
            await platform.start()
            try:
                resp = await gw_client.post("/v1/pipeline/detect",
                                            data=b"ORIGINAL-IMG")
                tid = (await resp.json())["TaskId"]
                final = await poll_until(
                    gw_client, tid, lambda b: "completed" in b["Status"])
                assert final["Status"] == "completed - classified"
                assert seen["stage2_body"] == b"ORIGINAL-IMG"
            finally:
                await platform.stop()
                await gw_client.close()
                await det_client.close()
                await cls_client.close()

        run(main())


class TestBinaryContentMode:
    def test_task_events_ship_raw_bytes(self):
        # Task deliveries use binary content mode: metadata in headers, body
        # raw on the wire — NO JSON/surrogateescape round trip (the measured
        # r3 push-vs-queue 3x gap on ~100-200 kB binary payloads). The
        # backend must receive byte-identical data with the taskId header.
        async def main():
            received = {}

            async def backend(request):
                received["body"] = await request.read()
                received["task_id"] = request.headers.get("taskId")
                received["content_type"] = request.headers.get("Content-Type")
                return web.Response(status=200)

            app = web.Application()
            app.router.add_post("/v1/m/score", backend)
            be_client = await serve(app)
            store = InMemoryTaskStore()
            webhook = WebhookDispatcher(LocalTaskManager(store))
            webhook.add_route("/v1/m/score",
                              str(be_client.make_url("/v1/m/score")))
            wh_client = await serve(webhook.app)
            topic = PushTopic(retry_delay=0.02)
            topic.bind_loop(asyncio.get_event_loop())
            await topic.subscribe("wh", str(wh_client.make_url("/api/events")))
            # Binary payload that would be mangled or bloated by JSON
            # escaping: every byte value, twice.
            payload = bytes(range(256)) * 2
            from ai4e_tpu.taskstore import APITask
            task = store.upsert(APITask(
                endpoint="http://edge/v1/m/score", body=payload,
                content_type="application/octet-stream"))
            topic.publish(task)
            await topic.drain(timeout=5.0)
            assert received["body"] == payload
            assert received["task_id"] == task.task_id
            assert received["content_type"] == "application/octet-stream"

        run(main())

    def test_non_latin1_subject_delivers(self):
        # ADVICE r4: aiohttp refuses non-latin-1 header values, so an
        # unencoded subject (endpoint + query with non-ASCII) would fail
        # every binary-mode delivery until the TTL dead-letters the task.
        # The subject header is percent-encoded; the round trip is exact —
        # including for subjects that already contain '%'.
        async def main():
            received = {}

            async def backend(request):
                received["body"] = await request.read()
                received["query"] = request.query_string
                return web.Response(status=200)

            app = web.Application()
            app.router.add_post("/v1/m/score", backend)
            be_client = await serve(app)
            store = InMemoryTaskStore()
            webhook = WebhookDispatcher(LocalTaskManager(store))
            webhook.add_route("/v1/m/score",
                              str(be_client.make_url("/v1/m/score")))
            wh_client = await serve(webhook.app)
            topic = PushTopic(retry_delay=0.02, ttl_seconds=2.0)
            topic.bind_loop(asyncio.get_event_loop())
            dead = []
            topic.set_dead_letter_handler(lambda ev: dead.append(ev.id))
            await topic.subscribe("wh", str(wh_client.make_url("/api/events")))
            from ai4e_tpu.taskstore import APITask
            task = store.upsert(APITask(
                endpoint="http://edge/v1/m/score?región=añejo&pct=5%25",
                body=b"payload"))
            topic.publish(task)
            await topic.drain(timeout=5.0)
            assert received.get("body") == b"payload", (
                "non-latin-1 subject never delivered")
            assert dead == []

        run(main())

    def test_structured_envelope_still_accepted(self):
        # External publishers (and the reference's Event Grid shape) POST
        # structured JSON envelopes; the webhook keeps accepting them.
        async def main():
            received = {}

            async def backend(request):
                received["body"] = await request.read()
                return web.Response(status=200)

            app = web.Application()
            app.router.add_post("/v1/m/score", backend)
            be_client = await serve(app)
            store = InMemoryTaskStore()
            webhook = WebhookDispatcher(LocalTaskManager(store))
            webhook.add_route("/v1/m/score",
                              str(be_client.make_url("/v1/m/score")))
            wh_client = await serve(webhook.app)
            resp = await wh_client.post("/api/events", json=[{
                "Id": "tid-1", "Subject": "http://edge/v1/m/score",
                "EventType": "ai4e.task.created", "Data": "hello"}])
            assert resp.status == 200
            assert received["body"] == b"hello"

        run(main())

    def test_delivery_window_bounds_in_flight(self):
        # The in-flight window caps concurrent POSTs: with window=2 and a
        # gate that holds deliveries open, at most 2 are ever in the
        # subscriber at once while the rest queue on the semaphore.
        async def main():
            in_flight = {"now": 0, "max": 0}
            gate = asyncio.Event()

            async def slow_subscriber(request):
                await request.read()
                in_flight["now"] += 1
                in_flight["max"] = max(in_flight["max"], in_flight["now"])
                await gate.wait()
                in_flight["now"] -= 1
                return web.Response(status=200)

            async def handshake_or_slow(request):
                if request.headers.get("X-AI4E-Event-Type"):
                    return await slow_subscriber(request)
                body = await request.json()
                return web.json_response(
                    {"validationResponse": body[0]["ValidationCode"]})

            app = web.Application()
            app.router.add_post("/api/events", handshake_or_slow)
            sub_client = await serve(app)
            topic = PushTopic(retry_delay=0.02, window=2)
            topic.bind_loop(asyncio.get_event_loop())
            await topic.subscribe("wh",
                                  str(sub_client.make_url("/api/events")))
            from ai4e_tpu.taskstore import APITask
            store = InMemoryTaskStore()
            for i in range(6):
                topic.publish(store.upsert(APITask(
                    endpoint=f"http://edge/v1/m/{i}", body=b"x")))
            await asyncio.sleep(0.3)
            assert in_flight["max"] <= 2, in_flight
            gate.set()
            await topic.drain(timeout=5.0)
            assert in_flight["max"] == 2, in_flight

        run(main())


class TestPreStartBuffering:
    def test_task_accepted_before_start_is_delivered(self):
        # The gateway may accept a task before platform.start() completes the
        # subscription handshake; the topic buffers and flushes — the same
        # contract as the queue broker (which buffers pre-bind).
        async def main():
            platform = LocalPlatform(PlatformConfig(
                transport="push", retry_delay=0.05))
            svc = platform.make_service("svc", prefix="v1/svc")

            @svc.api_async_func("/work")
            def work(taskId, body, content_type):
                asyncio.run(platform.task_manager.complete_task(
                    taskId, "completed - buffered"))

            svc_client = await serve(svc.app)
            platform.publish_async_api(
                "/v1/public/work", str(svc_client.make_url("/v1/svc/work")))
            gw_client = await serve(platform.gateway.app)
            try:
                # POST BEFORE start(): no subscription exists yet.
                resp = await gw_client.post("/v1/public/work", data=b"x")
                created = await resp.json()
                assert created["Status"] == "created", created
                await platform.start()
                final = await poll_until(
                    gw_client, created["TaskId"],
                    lambda b: "completed" in b["Status"])
                assert final["Status"] == "completed - buffered"
            finally:
                await platform.stop()
                await gw_client.close()
                await svc_client.close()

        run(main())


class TestConfigPlumbing:
    def test_transport_type_from_env(self):
        from ai4e_tpu.config import FrameworkConfig
        cfg = FrameworkConfig.from_env({
            "AI4E_PLATFORM_TRANSPORT": "push",
            "AI4E_PLATFORM_PUSH_MAX_ATTEMPTS": "7",
        })
        pc = cfg.to_platform_config()
        assert pc.transport == "push"
        assert pc.push_max_attempts == 7

    def test_unknown_transport_rejected(self):
        with pytest.raises(ValueError, match="unknown transport"):
            LocalPlatform(PlatformConfig(transport="carrier-pigeon"))
