"""Typed config: defaults, env overrides, precedence, parse failures."""

import pytest

from ai4e_tpu.config import (
    ConfigError,
    FrameworkConfig,
    PlatformSection,
    RuntimeSection,
    section_from_env,
)


class TestSections:
    def test_defaults_match_reference_capacity_values(self):
        cfg = FrameworkConfig.from_env(env={})
        # setup_env.sh:65,74 / host.json:5-9
        assert cfg.platform.retry_delay == 60.0
        assert cfg.platform.max_delivery_count == 1440
        assert cfg.platform.dispatcher_concurrency == 1
        # TaskQueueLogger.cs:19 / TaskProcessLogger.cs:21
        assert cfg.observability.queue_depth_interval == 30.0
        assert cfg.observability.process_depth_interval == 300.0

    def test_env_overrides_parse_types(self):
        env = {
            "AI4E_PLATFORM_RETRY_DELAY": "0.25",
            "AI4E_PLATFORM_MAX_DELIVERY_COUNT": "7",
            "AI4E_PLATFORM_NATIVE_BROKER": "true",
            "AI4E_PLATFORM_JOURNAL_PATH": "/tmp/j.jsonl",
            "AI4E_RUNTIME_BUCKETS": "2, 4,16",
            "AI4E_RUNTIME_CHECKPOINT_DIR": "",
        }
        cfg = FrameworkConfig.from_env(env=env)
        assert cfg.platform.retry_delay == 0.25
        assert cfg.platform.max_delivery_count == 7
        assert cfg.platform.native_broker is True
        assert cfg.platform.journal_path == "/tmp/j.jsonl"
        assert cfg.runtime.buckets == (2, 4, 16)
        assert cfg.runtime.checkpoint_dir is None  # "" → None for Optional

    def test_explicit_overrides_beat_env(self):
        env = {"AI4E_PLATFORM_RETRY_DELAY": "9.0"}
        sec = PlatformSection.from_env(env=env, retry_delay=0.1)
        assert sec.retry_delay == 0.1

    def test_bool_forms(self):
        for raw, want in [("1", True), ("Yes", True), ("on", True),
                          ("0", False), ("false", False), ("", False)]:
            sec = PlatformSection.from_env(
                env={"AI4E_PLATFORM_NATIVE_BROKER": raw})
            assert sec.native_broker is want, raw

    def test_malformed_value_fails_loudly(self):
        with pytest.raises(ConfigError, match="AI4E_PLATFORM_RETRY_DELAY"):
            PlatformSection.from_env(
                env={"AI4E_PLATFORM_RETRY_DELAY": "soon"})
        with pytest.raises(ConfigError, match="not a boolean"):
            PlatformSection.from_env(
                env={"AI4E_PLATFORM_NATIVE_BROKER": "maybe"})

    def test_to_platform_config_round_trip(self):
        sec = PlatformSection.from_env(
            env={"AI4E_PLATFORM_RETRY_DELAY": "0.5"})
        pc = sec.to_platform_config()
        assert pc.retry_delay == 0.5
        assert pc.max_delivery_count == 1440

    def test_misspelled_field_fails_loudly(self):
        with pytest.raises(ConfigError, match="AI4E_PLATFORM_MAX_DELIVERY"):
            PlatformSection.from_env(
                env={"AI4E_PLATFORM_MAX_DELIVERY": "7"})  # _COUNT missing

    def test_misspelled_section_fails_loudly(self):
        from ai4e_tpu.config import FrameworkConfig
        with pytest.raises(ConfigError, match="AI4E_OBSERVABILTY_TRACE"):
            FrameworkConfig.from_env(
                env={"AI4E_OBSERVABILTY_TRACE_ENABLED": "0"})  # typo'd section

    def test_generic_helper_ignores_unrelated_env(self):
        sec = section_from_env(RuntimeSection,
                               env={"AI4E_PLATFORM_RETRY_DELAY": "1"},
                               prefix="AI4E_RUNTIME_")
        assert sec == RuntimeSection()

    def test_real_environ_default(self, monkeypatch):
        monkeypatch.setenv("AI4E_SERVICE_PORT", "9999")
        cfg = FrameworkConfig.from_env()
        assert cfg.service.port == 9999

    def test_to_dict_serialisable(self):
        import json
        json.dumps(FrameworkConfig.from_env(env={}).to_dict())

    def test_observability_overrides_reach_platform_config(self):
        cfg = FrameworkConfig.from_env(env={
            "AI4E_OBSERVABILITY_QUEUE_DEPTH_INTERVAL": "5",
            "AI4E_OBSERVABILITY_PROCESS_DEPTH_INTERVAL": "60",
        })
        pc = cfg.to_platform_config()
        assert pc.queue_depth_interval == 5.0
        assert pc.process_depth_interval == 60.0

    def test_observability_apply_configures_tracer(self, tmp_path):
        from ai4e_tpu.observability import configure_tracer, get_tracer
        cfg = FrameworkConfig.from_env(env={
            "AI4E_OBSERVABILITY_TRACE_ENABLED": "0",
            "AI4E_OBSERVABILITY_TRACE_EXPORT_PATH":
                str(tmp_path / "spans.jsonl"),
        })
        try:
            cfg.observability.apply()
            assert get_tracer().sample_rate == 0.0
            assert get_tracer().exporter is not None
        finally:
            configure_tracer(exporter=None, sample_rate=None)
