"""Tracing: span lifecycle, propagation, sampling, exporters; depth logger."""

import asyncio
import json

import pytest

from ai4e_tpu.metrics import MetricsRegistry
from ai4e_tpu.observability import (
    InMemoryExporter,
    JsonlExporter,
    SAMPLED_HEADER,
    SPAN_HEADER,
    TRACE_HEADER,
    DepthLogger,
    Tracer,
    device_trace,
)
from ai4e_tpu.taskstore import APITask, InMemoryTaskStore, TaskStatus


@pytest.fixture
def exporter():
    return InMemoryExporter()


@pytest.fixture
def tracer(exporter):
    return Tracer("test-svc", exporter=exporter, metrics=MetricsRegistry())


class TestSpans:
    def test_root_span_exported_with_ids(self, tracer, exporter):
        with tracer.span("work", task_id="t-1", foo="bar"):
            pass
        (s,) = exporter.spans
        assert s.name == "work" and s.service == "test-svc"
        assert s.task_id == "t-1" and s.attrs == {"foo": "bar"}
        assert len(s.trace_id) == 32 and len(s.span_id) == 16
        assert s.parent_id is None and s.status == "ok"
        assert s.duration >= 0

    def test_nested_spans_share_trace(self, tracer, exporter):
        with tracer.span("outer"):
            with tracer.span("inner"):
                pass
        inner, outer = exporter.spans  # inner closes first
        assert inner.trace_id == outer.trace_id
        assert inner.parent_id == outer.span_id

    def test_error_recorded_and_reraised(self, tracer, exporter):
        with pytest.raises(ValueError):
            with tracer.span("boom"):
                raise ValueError("nope")
        (s,) = exporter.spans
        assert s.status == "error" and "ValueError: nope" in s.error

    def test_header_propagation_across_tracers(self, exporter):
        a = Tracer("svc-a", exporter=exporter, metrics=MetricsRegistry())
        b = Tracer("svc-b", exporter=exporter, metrics=MetricsRegistry())
        with a.span("upstream"):
            headers = a.headers()
            assert set(headers) == {TRACE_HEADER, SPAN_HEADER, SAMPLED_HEADER}
        with b.span("downstream", headers=headers):
            pass
        up = exporter.spans[0]
        down = next(s for s in exporter.spans if s.name == "downstream")
        assert down.trace_id == up.trace_id
        assert down.parent_id == up.span_id

    def test_contextvar_isolation_across_asyncio_tasks(self, tracer, exporter):
        async def leg(name):
            with tracer.span(name):
                await asyncio.sleep(0.01)

        async def main():
            await asyncio.gather(leg("a"), leg("b"))

        asyncio.run(main())
        a, b = exporter.spans
        assert a.trace_id != b.trace_id  # parallel tasks don't nest
        assert a.parent_id is None and b.parent_id is None

    def test_sampling_deterministic_and_inherited(self, exporter):
        t = Tracer("s", exporter=exporter, sample_rate=0.0,
                   metrics=MetricsRegistry())
        with t.span("dropped"):
            with t.span("child"):
                pass
        assert exporter.spans == []
        # unsampled context still propagates for downstream consistency
        t2 = Tracer("s2", exporter=exporter, metrics=MetricsRegistry())
        with t2.span("kept", headers={TRACE_HEADER: "ab" * 16,
                                      SAMPLED_HEADER: "0"}):
            pass
        assert exporter.spans == []  # sampled=0 inherited from headers

    def test_rate_zero_beats_inherited_sampled_header(self, exporter):
        """trace_enabled=0 must hold even behind a B3 mesh that stamps
        x-b3-sampled:1 on every request."""
        t = Tracer("s", exporter=exporter, sample_rate=0.0,
                   metrics=MetricsRegistry())
        with t.span("in", headers={TRACE_HEADER: "cd" * 16,
                                   SAMPLED_HEADER: "1"}):
            pass
        assert exporter.spans == []

    def test_span_duration_metric(self, exporter):
        reg = MetricsRegistry()
        t = Tracer("s", exporter=exporter, metrics=reg)
        with t.span("timed"):
            pass
        hist = reg.histogram("ai4e_span_seconds")
        assert hist.quantile(0.5, name="timed", service="s") >= 0

    def test_component_tracers_follow_global_reconfigure(self, exporter):
        """Tracers built without explicit settings (the service/gateway/
        dispatcher default) pick up configure_tracer() made AFTER their
        construction."""
        from ai4e_tpu.observability import configure_tracer
        t = Tracer("late-bound", metrics=MetricsRegistry())
        try:
            configure_tracer(exporter=exporter)
            with t.span("work"):
                pass
            assert [s.name for s in exporter.spans] == ["work"]
            configure_tracer(sample_rate=0.0)
            with t.span("dropped"):
                pass
            assert len(exporter.spans) == 1
        finally:
            configure_tracer(exporter=None, sample_rate=None)

    def test_jsonl_exporter_round_trip(self, tmp_path):
        path = str(tmp_path / "spans.jsonl")
        exp = JsonlExporter(path)
        t = Tracer("s", exporter=exp, metrics=MetricsRegistry())
        with t.span("a", task_id="t-9"):
            pass
        exp.close()
        (line,) = open(path).read().splitlines()
        d = json.loads(line)
        assert d["name"] == "a" and d["task_id"] == "t-9"

    def test_device_trace_noop_without_profiler(self):
        with device_trace("batch"):
            x = 1 + 1
        assert x == 2


class TestDepthLogger:
    def _store_with_tasks(self):
        store = InMemoryTaskStore()
        t1 = store.upsert(APITask(endpoint="http://x/v1/api", body=b"1"))
        store.upsert(APITask(endpoint="http://x/v1/api", body=b"2"))
        store.update_status(t1.task_id, "running", TaskStatus.RUNNING)
        return store

    def test_sample_queue_depth(self):
        store = self._store_with_tasks()
        reg = MetricsRegistry()
        dl = DepthLogger(store, metrics=reg)
        depths = dl.sample_queue_depth()
        assert depths == {"/v1/api": 1}
        g = reg.gauge("ai4e_task_depth")
        assert g.value(endpoint="/v1/api", status=TaskStatus.CREATED) == 1.0

    def test_sample_process_depths(self):
        store = self._store_with_tasks()
        reg = MetricsRegistry()
        dl = DepthLogger(store, metrics=reg)
        dl.sample_process_depths()
        g = reg.gauge("ai4e_task_depth")
        assert g.value(endpoint="/v1/api", status=TaskStatus.RUNNING) == 1.0
        assert g.value(endpoint="/v1/api", status=TaskStatus.COMPLETED) == 0.0

    def test_timers_run_and_stop(self):
        store = self._store_with_tasks()
        reg = MetricsRegistry()
        dl = DepthLogger(store, metrics=reg,
                         queue_interval=0.01, process_interval=0.01)

        async def main():
            await dl.start()
            await asyncio.sleep(0.05)
            await dl.stop()

        asyncio.run(main())
        g = reg.gauge("ai4e_task_depth")
        assert g.value(endpoint="/v1/api", status=TaskStatus.CREATED) == 1.0
        assert dl._tasks == []


class TestEndToEndTrace:
    def test_async_path_emits_taskid_keyed_spans(self):
        """gateway create_task → dispatcher dispatch → service endpoint spans
        all carry the same TaskId; dispatch parents the endpoint span."""
        from aiohttp.test_utils import TestClient, TestServer

        from ai4e_tpu.observability import configure_tracer, get_tracer
        from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig

        exporter = InMemoryExporter()
        old = get_tracer().exporter
        configure_tracer(exporter=exporter)
        try:
            async def main():
                platform = LocalPlatform(PlatformConfig(retry_delay=0.05))
                svc = platform.make_service("echo", prefix="v1/echo")

                @svc.api_async_func("/run")
                async def run(taskId=None, body=None, content_type=None):
                    await svc.task_manager.complete_task(taskId)

                server = TestServer(svc.app)
                await server.start_server()
                backend = f"http://127.0.0.1:{server.port}/v1/echo/run"
                platform.publish_async_api("/v1/echo/run", backend_uri=backend)
                await platform.start()

                gw = TestServer(platform.gateway.app)
                await gw.start_server()
                async with TestClient(gw) as client:
                    resp = await client.post("/v1/echo/run", data=b"{}")
                    task_id = (await resp.json())["TaskId"]
                    for _ in range(100):
                        r = await client.get(
                            f"/v1/taskmanagement/task/{task_id}")
                        if (await r.json())["Status"] == "completed":
                            break
                        await asyncio.sleep(0.02)
                await platform.stop()
                await server.close()
                return task_id

            task_id = asyncio.run(main())
        finally:
            configure_tracer(exporter=old)

        spans = exporter.by_task(task_id)
        names = {s.name for s in spans}
        assert "dispatch" in names and "/run" in names
        dispatch = next(s for s in spans if s.name == "dispatch")
        endpoint = next(s for s in spans if s.name == "/run")
        assert endpoint.trace_id == dispatch.trace_id
        assert endpoint.parent_id == dispatch.span_id


class TestOtlpExporter:
    """OTLP/HTTP span sink (VERDICT r2 #8): spans batch to a collector as
    ExportTraceServiceRequest JSON; a dead collector never blocks serving."""

    def _collector(self):
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        received = []

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(int(self.headers["Content-Length"]))
                received.append((self.path, _json.loads(body)))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        import threading
        threading.Thread(target=server.serve_forever, daemon=True).start()
        return server, received

    def test_spans_land_as_otlp_json(self):
        from ai4e_tpu.observability.otlp import OtlpHttpExporter
        from ai4e_tpu.observability.tracing import Tracer

        server, received = self._collector()
        try:
            exporter = OtlpHttpExporter(
                f"http://127.0.0.1:{server.server_address[1]}/v1/traces",
                flush_interval=0.1)
            tracer = Tracer("svc-a", exporter=exporter)
            with tracer.span("create_task", task_id="tid-1", route="/v1/x"):
                pass
            with tracer.span("boom", task_id="tid-2"):
                try:
                    raise ValueError("nope")
                except ValueError:
                    pass
            exporter.close()
            assert exporter.exported == 2 and exporter.export_errors == 0
            path, body = received[0]
            assert path == "/v1/traces"
            resource = body["resourceSpans"][0]
            svc_attr = resource["resource"]["attributes"][0]
            assert svc_attr == {"key": "service.name",
                                "value": {"stringValue": "svc-a"}}
            spans = resource["scopeSpans"][0]["spans"]
            assert len(spans) == 2
            first = spans[0]
            assert len(first["traceId"]) == 32 and len(first["spanId"]) == 16
            attrs = {a["key"]: a["value"]["stringValue"]
                     for a in first["attributes"]}
            assert attrs["ai4e.task_id"] == "tid-1"
            assert attrs["route"] == "/v1/x"
            assert int(first["endTimeUnixNano"]) >= int(
                first["startTimeUnixNano"])
        finally:
            server.shutdown()
            server.server_close()

    def test_error_span_carries_otlp_error_status(self):
        from ai4e_tpu.observability.otlp import span_to_otlp
        from ai4e_tpu.observability.tracing import Span

        span = Span(name="n", service="s", trace_id="ab" * 16,
                    span_id="cd" * 8, status="error", error="KeyError: x",
                    start=100.0, duration=0.5)
        otlp = span_to_otlp(span)
        assert otlp["status"] == {"code": 2, "message": "KeyError: x"}

    def test_dead_collector_drops_batches_without_raising(self):
        from ai4e_tpu.observability.otlp import OtlpHttpExporter
        from ai4e_tpu.observability.tracing import Span

        exporter = OtlpHttpExporter("http://127.0.0.1:1/v1/traces",
                                    flush_interval=0.05, timeout=0.2)
        for i in range(5):
            exporter.export(Span(name=f"s{i}", service="s",
                                 trace_id="ab" * 16, span_id="cd" * 8))
        exporter.close()
        assert exporter.export_errors >= 1
        assert exporter.exported == 0

    def test_overflow_sheds_oldest(self):
        from ai4e_tpu.observability.otlp import OtlpHttpExporter
        from ai4e_tpu.observability.tracing import Span

        exporter = OtlpHttpExporter("http://127.0.0.1:1/v1/traces",
                                    flush_interval=30.0, max_queue=3,
                                    max_batch=100, timeout=0.2)
        for i in range(5):
            exporter.export(Span(name=f"s{i}", service="s",
                                 trace_id="ab" * 16, span_id="cd" * 8))
        assert exporter.dropped == 2
        names = [s.name for s in exporter._queue]
        assert names == ["s2", "s3", "s4"]  # oldest shed first
        exporter.close()

    def test_fanout_survives_one_sink_failing(self):
        from ai4e_tpu.observability import (FanoutExporter, InMemoryExporter,
                                            Span)

        class Broken:
            def export(self, span):
                raise RuntimeError("sink down")

        good = InMemoryExporter()
        fan = FanoutExporter([Broken(), good])
        fan.export(Span(name="n", service="s", trace_id="t", span_id="i"))
        assert len(good.spans) == 1

    def test_ids_normalized_to_otlp_widths(self):
        """Client-supplied B3 ids (64-bit or garbage) must not poison the
        whole OTLP batch — ids normalize to exactly 32/16 hex chars."""
        from ai4e_tpu.observability.otlp import span_to_otlp
        from ai4e_tpu.observability.tracing import Span

        b3_64bit = span_to_otlp(Span(name="n", service="s",
                                     trace_id="0123456789abcdef",
                                     span_id="cd" * 8))
        assert b3_64bit["traceId"] == "0" * 16 + "0123456789abcdef"
        garbage = span_to_otlp(Span(name="n", service="s",
                                    trace_id="not-hex-at-all!",
                                    span_id="also bad",
                                    parent_id="bad too"))
        for key, width in (("traceId", 32), ("spanId", 16),
                           ("parentSpanId", 16)):
            v = garbage[key]
            assert len(v) == width and int(v, 16) >= 0, (key, v)


class TestOtlpExportEdgeCases:
    """Export-path edges (observability PR satellite): shutdown flush
    drains everything queued across multiple batches, export after close
    is a no-op, and a collector that comes back after being down gets
    subsequent spans (lost batches counted, serving never blocked)."""

    def _collector(self, fail_first: int = 0):
        import json as _json
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        received = []
        state = {"fail": fail_first}

        class Handler(BaseHTTPRequestHandler):
            def do_POST(self):
                body = self.rfile.read(int(self.headers["Content-Length"]))
                if state["fail"] > 0:
                    state["fail"] -= 1
                    self.send_response(503)
                    self.end_headers()
                    return
                received.append(_json.loads(body))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        server = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        import threading
        threading.Thread(target=server.serve_forever, daemon=True).start()
        return server, received, state

    @staticmethod
    def _span(i):
        from ai4e_tpu.observability.tracing import Span
        return Span(name=f"s{i}", service="svc", trace_id="ab" * 16,
                    span_id="cd" * 8, start=100.0 + i, duration=0.01)

    def test_close_flushes_queue_across_multiple_batches(self):
        """Shutdown flush: a queue deeper than one batch drains FULLY on
        close — the shutdown-time spans are the interesting ones."""
        from ai4e_tpu.observability.otlp import OtlpHttpExporter
        server, received, _ = self._collector()
        try:
            exporter = OtlpHttpExporter(
                f"http://127.0.0.1:{server.server_address[1]}/v1/traces",
                flush_interval=60.0, max_batch=4)  # interval never fires
            for i in range(10):
                exporter.export(self._span(i))
            exporter.close()
            assert exporter.exported == 10
            total = sum(
                len(scope["spans"])
                for body in received
                for rs in body["resourceSpans"]
                for scope in rs["scopeSpans"])
            assert total == 10
            assert len(received) >= 3  # 4+4+2: batch bound respected
        finally:
            server.shutdown()
            server.server_close()

    def test_export_after_close_is_noop(self):
        from ai4e_tpu.observability.otlp import OtlpHttpExporter
        server, received, _ = self._collector()
        try:
            exporter = OtlpHttpExporter(
                f"http://127.0.0.1:{server.server_address[1]}/v1/traces",
                flush_interval=0.05)
            exporter.close()
            exporter.export(self._span(0))
            exporter.close()  # idempotent
            assert exporter.exported == 0
            assert received == []
        finally:
            server.shutdown()
            server.server_close()

    def test_partial_outage_drops_failed_batch_keeps_later_ones(self):
        """A 5xx-answering collector loses THAT batch (counted — no
        retry convoy behind a dead sink) while later batches flow once
        it recovers."""
        import time as _time

        from ai4e_tpu.observability.otlp import OtlpHttpExporter
        server, received, state = self._collector(fail_first=1)
        try:
            exporter = OtlpHttpExporter(
                f"http://127.0.0.1:{server.server_address[1]}/v1/traces",
                flush_interval=0.05, timeout=2.0)
            exporter.export(self._span(0))
            deadline = _time.time() + 5.0
            while exporter.export_errors == 0 and _time.time() < deadline:
                _time.sleep(0.01)
            assert exporter.export_errors == 1
            assert state["fail"] == 0
            exporter.export(self._span(1))
            exporter.close()
            assert exporter.exported == 1  # the post-recovery span only
            (body,) = received
            (span,) = body["resourceSpans"][0]["scopeSpans"][0]["spans"]
            assert span["name"] == "s1"
        finally:
            server.shutdown()
            server.server_close()

    def test_urlopen_uses_status_not_exception_for_2xx_only(self):
        """A 4xx answer is an error path too (urlopen raises HTTPError):
        counted as an export error, spans lost, thread alive."""
        import time as _time

        from ai4e_tpu.observability.otlp import OtlpHttpExporter
        server, received, state = self._collector(fail_first=10**9)
        try:
            exporter = OtlpHttpExporter(
                f"http://127.0.0.1:{server.server_address[1]}/v1/traces",
                flush_interval=0.05, timeout=2.0)
            exporter.export(self._span(0))
            deadline = _time.time() + 5.0
            while exporter.export_errors == 0 and _time.time() < deadline:
                _time.sleep(0.01)
            assert exporter.export_errors >= 1 and exporter.exported == 0
            # The export thread survived and still accepts work.
            exporter.export(self._span(1))
            exporter.close()
            assert received == []
        finally:
            server.shutdown()
            server.server_close()
