"""Control-plane HA tests — journal-follower replication + failover
(VERDICT r3 #3). The reference's availability came from managed network
Redis (``RedisConnection.cs:12-38``, ``deploy_cache_prerequisites.sh:15-31``);
here a standby replica tails the primary's journal stream
(``taskstore/replication.py``), refuses writes until promoted, and a
watchdog promotes it when the primary dies. The headline test is the
kill-the-store e2e: tasks created before the kill complete after failover
with results intact."""

import asyncio
import json

import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.service.task_manager import HttpResultStore, HttpTaskManager
from ai4e_tpu.taskstore import (
    APITask,
    FollowerTaskStore,
    JournaledTaskStore,
    NotPrimaryError,
    TaskStatus,
)
from ai4e_tpu.taskstore.http import make_app
from ai4e_tpu.taskstore.replication import FailoverWatchdog, JournalReplicator


def run(coro):
    return asyncio.run(coro)


async def serve(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


async def wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval)
    return predicate()


def primary_store(tmp_path, name="primary.jsonl", **kw):
    return JournaledTaskStore(str(tmp_path / name), **kw)


def follower_store(tmp_path, name="follower.jsonl", **kw):
    return FollowerTaskStore(str(tmp_path / name), **kw)


class TestFollowerSync:
    def test_follower_mirrors_tasks_transitions_and_results(self, tmp_path):
        async def main():
            primary = primary_store(tmp_path)
            pri_client = await serve(make_app(primary))
            follower = follower_store(tmp_path)
            repl = JournalReplicator(
                follower, str(pri_client.make_url("")), poll_wait=0.2)
            repl.start()
            try:
                t1 = primary.upsert(APITask(
                    endpoint="http://edge/v1/landcover/classify",
                    body=b"tile-1"))
                t2 = primary.upsert(APITask(
                    endpoint="http://edge/v1/species/classify",
                    body=b"img-2", content_type="image/jpeg"))
                primary.update_status(t1.task_id, "running",
                                      TaskStatus.RUNNING)
                primary.set_result(t1.task_id, b'{"histogram": {"0": 9}}')
                primary.update_status(t1.task_id, "completed",
                                      TaskStatus.COMPLETED)

                ok = await wait_for(
                    lambda: (follower.set_len("/v1/landcover/classify",
                                              "completed") == 1
                             and follower.set_len("/v1/species/classify",
                                                  "created") == 1))
                assert ok, follower.depths()
                assert (follower.get(t1.task_id).to_dict()
                        == primary.get(t1.task_id).to_dict())
                assert follower.get_result(t1.task_id) == (
                    b'{"histogram": {"0": 9}}', "application/json")
                # Original bodies replicate too — the promoted follower must
                # be able to replay payloads for redelivery.
                assert follower.get_original_body(t2.task_id) == b"img-2"
                assert follower.get(t2.task_id).content_type == "image/jpeg"
            finally:
                await repl.aclose()
                await pri_client.close()
                primary.close()
                follower.close()

        run(main())

    def test_generation_change_resyncs_follower(self, tmp_path):
        # Primary compaction rewrites the journal (byte offsets die);
        # the follower detects the generation bump and resyncs from the
        # rewritten snapshot — state identical, nothing duplicated.
        async def main():
            primary = primary_store(tmp_path)
            pri_client = await serve(make_app(primary))
            follower = follower_store(tmp_path)
            repl = JournalReplicator(
                follower, str(pri_client.make_url("")), poll_wait=0.2)
            repl.start()
            try:
                ids = []
                for i in range(5):
                    t = primary.upsert(APITask(
                        endpoint="http://edge/v1/e/run", body=b"x%d" % i))
                    ids.append(t.task_id)
                for tid in ids[:3]:
                    primary.update_status(tid, "completed",
                                          TaskStatus.COMPLETED)
                await wait_for(lambda: follower.set_len("/v1/e/run",
                                                        "completed") == 3)
                gen_before = primary.journal_generation
                primary.compact()
                assert primary.journal_generation == gen_before + 1
                # Post-compaction mutations only exist in the new file.
                t_new = primary.upsert(APITask(
                    endpoint="http://edge/v1/e/run", body=b"after-compact"))
                ok = await wait_for(
                    lambda: (repl.generation == primary.journal_generation
                             and t_new.task_id in
                             {t.task_id for t in follower.snapshot()}))
                assert ok, (repl.generation, primary.journal_generation)
                assert ({t.task_id for t in follower.snapshot()}
                        == {t.task_id for t in primary.snapshot()})
                assert (follower.set_len("/v1/e/run", "completed") == 3)
            finally:
                await repl.aclose()
                await pri_client.close()
                primary.close()
                follower.close()

        run(main())

    def test_follower_restart_replays_its_own_journal(self, tmp_path):
        async def main():
            primary = primary_store(tmp_path)
            pri_client = await serve(make_app(primary))
            follower = follower_store(tmp_path)
            repl = JournalReplicator(
                follower, str(pri_client.make_url("")), poll_wait=0.2)
            repl.start()
            t = primary.upsert(APITask(endpoint="http://edge/v1/e/run",
                                       body=b"payload"))
            primary.set_result(t.task_id, b"res")
            await wait_for(
                lambda: follower.get_result(t.task_id) is not None)
            await repl.aclose()
            follower.close()
            await pri_client.close()
            primary.close()
            # Restart: the absorbed journal is byte-compatible with the
            # ordinary replay machinery.
            reborn = follower_store(tmp_path)
            assert reborn.get(t.task_id).task_id == t.task_id
            assert reborn.get_result(t.task_id) == (
                b"res", "application/json")
            assert reborn.get_original_body(t.task_id) == b"payload"
            reborn.close()

        run(main())


class TestStandbyLongPoll:
    def test_replicated_completion_wakes_standby_waiters(self, tmp_path):
        """A client long-polling the STANDBY's gateway must wake when the
        task completes on the PRIMARY: replicated Slim transitions fire the
        follower's own listeners (absorb_lines → _notify), so standby reads
        are first-class, not poll-until-timeout."""
        async def main():
            from ai4e_tpu.gateway.router import Gateway

            primary = primary_store(tmp_path)
            pri_client = await serve(make_app(primary))
            follower = follower_store(tmp_path)
            gw = Gateway(follower)
            gw_client = await serve(gw.app)
            repl = JournalReplicator(
                follower, str(pri_client.make_url("")), poll_wait=0.2)
            repl.start()
            try:
                t = primary.upsert(APITask(
                    endpoint="http://edge/v1/e/run", body=b"x"))
                ok = await wait_for(lambda: t.task_id in
                                    {x.task_id for x in follower.snapshot()})
                assert ok, "task never replicated to the standby"
                waiter = asyncio.create_task(gw_client.get(
                    f"/v1/taskmanagement/task/{t.task_id}",
                    params={"wait": "20"}))
                await asyncio.sleep(0.1)
                t0 = asyncio.get_event_loop().time()
                primary.update_status(t.task_id, "completed - done",
                                      TaskStatus.COMPLETED)
                resp = await asyncio.wait_for(waiter, timeout=10)
                woke_after = asyncio.get_event_loop().time() - t0
                body = await resp.json()
                assert "completed" in body["Status"], body
                # Event-driven wake, not the 20 s poll timeout.
                assert woke_after < 5.0, woke_after
            finally:
                await repl.aclose()
                await pri_client.close()
                await gw_client.close()
                primary.close()
                follower.close()

        run(main())


class TestWriteFence:
    def test_follower_refuses_writes_until_promoted(self, tmp_path):
        follower = follower_store(tmp_path)
        try:
            with pytest.raises(NotPrimaryError):
                follower.upsert(APITask(endpoint="http://e/v1/x", body=b"b"))
            follower.promote()
            task = follower.upsert(APITask(endpoint="http://e/v1/x",
                                           body=b"b"))
            assert follower.get(task.task_id).status == TaskStatus.CREATED
        finally:
            follower.close()

    def test_http_surface_maps_fence_to_503(self, tmp_path):
        async def main():
            follower = follower_store(tmp_path)
            client = await serve(make_app(follower))
            try:
                resp = await client.post(
                    "/v1/taskstore/upsert",
                    data=json.dumps({"Endpoint": "http://e/v1/x",
                                     "Body": "b"}))
                assert resp.status == 503
                assert (await resp.json())["error"] == "not primary"
                # Manual failover via the surface.
                resp = await client.post("/v1/taskstore/promote")
                assert resp.status == 200
                resp = await client.post(
                    "/v1/taskstore/upsert",
                    data=json.dumps({"Endpoint": "http://e/v1/x",
                                     "Body": "b"}))
                assert resp.status == 200
                role = await (await client.get("/v1/taskstore/role")).json()
                assert role["role"] == "primary"
            finally:
                await client.close()
                follower.close()

        run(main())


class TestStandbyPlatform:
    def test_standby_platform_promotes_and_dispatches(self, tmp_path):
        """Platform-level failover: a standby LocalPlatform (replicate_from)
        refuses edge writes while the primary lives, then — primary killed —
        its watchdog promotes the store, starts the transport, and re-seeds
        every replicated unfinished task into dispatch, which completes them
        end to end."""
        async def main():
            from ai4e_tpu.platform_assembly import (LocalPlatform,
                                                    PlatformConfig)

            primary = primary_store(tmp_path)
            pri_client = await serve(make_app(primary))

            standby = LocalPlatform(PlatformConfig(
                journal_path=str(tmp_path / "standby.jsonl"),
                replicate_from=str(pri_client.make_url("")),
                failover_interval=0.1, failover_down_after=2,
                retry_delay=0.05))
            svc = standby.make_service("echo", prefix="v1/echo")
            completed = []

            @svc.api_async_func("/run")
            def run_endpoint(taskId, body, content_type):
                completed.append(body)
                asyncio.run(standby.task_manager.complete_task(
                    taskId, "completed - echoed"))

            svc_client = await serve(svc.app)
            backend = str(svc_client.make_url("/v1/echo/run"))
            standby.publish_async_api("/v1/public/run", backend)
            gw_client = await serve(standby.gateway.app)
            await standby.start()
            try:
                # While the primary lives: reads OK, writes 503.
                resp = await gw_client.post("/v1/public/run", data=b"x")
                assert resp.status == 503, await resp.text()
                # Two tasks land on the PRIMARY (as the primary's gateway
                # would record them) and replicate over.
                ids = [primary.upsert(APITask(
                    endpoint=backend, body=b"replicated-%d" % i,
                    publish=True)).task_id for i in range(2)]
                await wait_for(
                    lambda: len(standby.store.unfinished_tasks()) == 2)

                await pri_client.close()
                primary.close()
                await asyncio.wait_for(standby.watchdog.promoted.wait(),
                                       timeout=10)

                # Promotion re-seeded dispatch: both tasks complete HERE.
                for tid in ids:
                    ok = await wait_for(
                        lambda t=tid: "completed" in
                        standby.store.get(t).status)
                    assert ok, standby.store.get(tid).to_dict()
                assert sorted(completed) == [b"replicated-0",
                                             b"replicated-1"]
                # And the promoted gateway now accepts new tasks.
                resp = await gw_client.post("/v1/public/run", data=b"new")
                assert resp.status == 200
                tid = (await resp.json())["TaskId"]
                ok = await wait_for(
                    lambda: "completed" in standby.store.get(tid).status)
                assert ok
            finally:
                await standby.stop()
                await gw_client.close()
                await svc_client.close()

        run(main())


class TestMidPipelineFailover:
    def test_handed_off_task_completes_on_promoted_standby(self, tmp_path):
        """A composite task killed MID-PIPELINE survives: stage 1 completed
        on the primary and republished the task to stage 2 (endpoint
        rewrite + empty body), then the primary died. The promoted standby
        must re-seed the stage-2 task WITH the replicated original body
        (the ``{taskId}_ORIG`` replay, ``CacheConnectorUpsert.cs:144-176``)
        so stage 2 receives the real payload."""
        async def main():
            from ai4e_tpu.platform_assembly import (LocalPlatform,
                                                    PlatformConfig)

            primary = primary_store(tmp_path)
            pri_client = await serve(make_app(primary))

            standby = LocalPlatform(PlatformConfig(
                journal_path=str(tmp_path / "standby.jsonl"),
                replicate_from=str(pri_client.make_url("")),
                failover_interval=0.1, failover_down_after=2,
                retry_delay=0.05))
            svc = standby.make_service("cls", prefix="v1/cls")
            stage2_bodies = []

            @svc.api_async_func("/classify")
            def classify(taskId, body, content_type):
                stage2_bodies.append((body, content_type))
                asyncio.run(standby.task_manager.complete_task(
                    taskId, "completed - classified"))

            svc_client = await serve(svc.app)
            stage2_backend = str(svc_client.make_url("/v1/cls/classify"))
            standby.publish_async_api("/v1/public/classify", stage2_backend)
            await standby.start()
            try:
                # On the PRIMARY: stage-1 lifecycle up to the handoff.
                t = primary.upsert(APITask(
                    endpoint="http://edge/v1/det/detect",
                    body=b"ORIGINAL-IMG", content_type="image/jpeg",
                    publish=True))
                primary.update_status(t.task_id, "running - det",
                                      TaskStatus.RUNNING)
                # Handoff: endpoint rewritten to stage 2, empty body →
                # the store replays the original (same upsert the
                # task manager's add_pipeline_task performs).
                primary.upsert(APITask(
                    task_id=t.task_id, endpoint=stage2_backend, body=b"",
                    status=TaskStatus.CREATED,
                    backend_status=TaskStatus.CREATED, publish=True))
                ok = await wait_for(
                    lambda: standby.store.get(t.task_id).endpoint
                    == stage2_backend if t.task_id in
                    {x.task_id for x in standby.store.snapshot()} else False)
                assert ok, "handoff never replicated"

                await pri_client.close()
                primary.close()
                await asyncio.wait_for(standby.watchdog.promoted.wait(),
                                       timeout=10)

                ok = await wait_for(
                    lambda: "completed" in standby.store.get(t.task_id).status)
                assert ok, standby.store.get(t.task_id).to_dict()
                # Stage 2 received the ORIGINAL payload with its type.
                assert stage2_bodies == [(b"ORIGINAL-IMG", "image/jpeg")]
            finally:
                await standby.stop()
                await svc_client.close()

        run(main())


class TestKillTheStore:
    def test_tasks_survive_primary_death_and_complete_on_follower(
            self, tmp_path):
        """THE HA acceptance test (VERDICT r3 #3 done-criterion): tasks
        created before the primary dies complete after failover, results
        from before the kill stay readable."""
        async def main():
            primary = primary_store(tmp_path)
            pri_client = await serve(make_app(primary))
            follower = follower_store(tmp_path)
            fol_client = await serve(make_app(follower))
            repl = JournalReplicator(
                follower, str(pri_client.make_url("")), poll_wait=0.2)
            repl.start()
            promoted_seen = []
            watchdog = FailoverWatchdog(
                repl, interval=0.1, down_after=2,
                on_promote=lambda: promoted_seen.append(True))

            # Store clients with the replica list — gateway/worker view.
            urls = [str(pri_client.make_url("")),
                    str(fol_client.make_url(""))]
            manager = HttpTaskManager(urls, failover_delay=0.1)
            results = HttpResultStore(urls, failover_delay=0.1)
            try:
                # Phase 1 (primary alive): one task completes WITH result,
                # two are still pending when the primary dies.
                done = await manager.add_task(
                    "http://edge/v1/landcover/classify", b"tile-done")
                await results.set_result(done["TaskId"], b'{"ok": 1}')
                await manager.complete_task(done["TaskId"], "completed")
                pending = []
                for i in range(2):
                    rec = await manager.add_task(
                        "http://edge/v1/landcover/classify",
                        b"tile-pending-%d" % i)
                    pending.append(rec["TaskId"])
                await wait_for(
                    lambda: follower.set_len("/v1/landcover/classify",
                                             "created") == 2)
                watchdog.start()

                # Phase 2: kill the primary process outright.
                await pri_client.close()
                primary.close()
                await asyncio.wait_for(watchdog.promoted.wait(), timeout=10)
                assert promoted_seen and follower.role == "primary"

                # Phase 3: the pending tasks are present on the new primary
                # with replayed bodies — what the platform re-dispatches.
                unfinished = {t.task_id: t for t in
                              follower.unfinished_tasks()}
                assert set(pending) <= set(unfinished)
                assert unfinished[pending[0]].body.startswith(b"tile-pending")
                # A worker (store clients fail over) completes them.
                for tid in pending:
                    await results.set_result(tid, b'{"ok": 2}')
                    await manager.complete_task(tid, "completed")
                for tid in pending:
                    rec = await manager.get_task_status(tid)
                    assert "completed" in rec["Status"], rec
                # Results from BEFORE the kill are intact after failover.
                assert (await results.get_result(done["TaskId"]))[0] \
                    == b'{"ok": 1}'
                rec = await manager.get_task_status(done["TaskId"])
                assert "completed" in rec["Status"]
            finally:
                await watchdog.stop()
                await repl.aclose()
                await manager.close()
                await results.close()
                await fol_client.close()
                follower.close()

        run(main())


class TestStoreClientFailoverPatience:
    def test_replica_patience_covers_default_promotion_window(self):
        """The live failover drive measured tasks whose inference succeeded
        being FailTask'd because the store client's replica patience
        (~1.5 s) expired inside the promotion window; patience must cover
        the DEFAULT watchdog's detection (failover_down_after ×
        failover_interval = 6 s) with margin. Lowering these defaults is
        a deliberate act, not a drive-by (scripts/ha_failover_drive.py,
        bench_results/r5-cpu/ha_failover_drive.json)."""
        from ai4e_tpu.config import PlatformSection
        from ai4e_tpu.service.task_manager import HttpTaskManager

        tm = HttpTaskManager(["http://a", "http://b"])
        patience = tm._failover_cycles * tm._failover_delay
        section = PlatformSection()
        detection = section.failover_down_after * section.failover_interval
        assert patience > detection + 2.0, (
            f"replica patience {patience}s must exceed watchdog detection "
            f"{detection}s plus promotion margin")
