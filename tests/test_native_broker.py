"""Native (C++) broker core: same contract as InMemoryBroker — FIFO, leases,
redelivery, dead-lettering, prefix routing — plus a full platform e2e run on
the native engine."""

import asyncio

import pytest

from ai4e_tpu.broker.native import NativeBroker, build_library
from ai4e_tpu.taskstore import APITask


def run(coro):
    return asyncio.run(coro)


@pytest.fixture(scope="module", autouse=True)
def built():
    build_library()


def make_broker(**kw):
    b = NativeBroker(**kw)
    b.register_queue("/v1/api")
    return b


class TestNativeQueueSemantics:
    def test_fifo_roundtrip(self):
        async def main():
            broker = make_broker()
            try:
                for i in range(3):
                    broker.publish(APITask(task_id=f"t{i}", endpoint="/v1/api",
                                           body=f"B{i}".encode()))
                got = []
                for _ in range(3):
                    msg = await broker.receive("/v1/api", timeout=2)
                    got.append((msg.task_id, msg.body))
                    broker.complete(msg)
                assert got == [("t0", b"B0"), ("t1", b"B1"), ("t2", b"B2")]
                assert await broker.receive("/v1/api", timeout=0.05) is None
            finally:
                broker.close()

        run(main())

    def test_abandon_redelivers(self):
        async def main():
            broker = make_broker()
            try:
                broker.publish(APITask(task_id="t", endpoint="/v1/api"))
                msg = await broker.receive("/v1/api", timeout=2)
                assert msg.delivery_count == 1
                assert broker.abandon(msg)
                msg2 = await broker.receive("/v1/api", timeout=2)
                assert (msg2.task_id, msg2.delivery_count) == ("t", 2)
            finally:
                broker.close()

        run(main())

    def test_dead_letter_after_max_and_handler_fires(self):
        async def main():
            dead = []
            broker = make_broker(max_delivery_count=2)
            broker.bind_loop(asyncio.get_running_loop())
            broker.set_dead_letter_handler(lambda m: dead.append(m.task_id))
            try:
                broker.publish(APITask(task_id="t", endpoint="/v1/api"))
                m1 = await broker.receive("/v1/api", timeout=2)
                assert broker.abandon(m1)
                m2 = await broker.receive("/v1/api", timeout=2)
                assert not broker.abandon(m2)  # exhausted → dead letter
                await asyncio.sleep(0.05)      # handler marshalled to loop
                assert dead == ["t"]
            finally:
                broker.close()

        run(main())

    def test_lease_expiry_redelivers(self):
        async def main():
            broker = make_broker(lease_seconds=0.05)
            try:
                broker.publish(APITask(task_id="t", endpoint="/v1/api"))
                msg = await broker.receive("/v1/api", timeout=2)
                assert msg is not None  # consumer "crashes"
                await asyncio.sleep(0.1)
                msg2 = await broker.receive("/v1/api", timeout=2)
                assert msg2.task_id == "t"
                assert msg2.delivery_count == 2
            finally:
                broker.close()

        run(main())

    def test_prefix_routing(self):
        async def main():
            broker = make_broker()
            try:
                # endpoint extends registered queue path → same queue
                broker.publish(APITask(
                    task_id="t", endpoint="http://h/v1/api/opB?x=1"))
                msg = await broker.receive("/v1/api", timeout=2)
                assert msg.task_id == "t"
                assert "opB" in msg.endpoint
            finally:
                broker.close()

        run(main())

    def test_binary_body_fidelity(self):
        async def main():
            broker = make_broker()
            payload = bytes(range(256)) * 100
            try:
                broker.publish(APITask(task_id="t", endpoint="/v1/api",
                                       body=payload))
                msg = await broker.receive("/v1/api", timeout=2)
                assert msg.body == payload
            finally:
                broker.close()

        run(main())

    def test_depths(self):
        async def main():
            broker = make_broker()
            try:
                for i in range(4):
                    broker.publish(APITask(task_id=f"t{i}", endpoint="/v1/api"))
                assert broker.depths() == {"/v1/api": 4}
            finally:
                broker.close()

        run(main())


class TestNativePlatformE2E:
    def test_async_lifecycle_on_native_broker(self):
        from aiohttp.test_utils import TestClient, TestServer

        from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig

        async def main():
            platform = LocalPlatform(PlatformConfig(
                retry_delay=0.05, native_broker=True))
            svc = platform.make_service("det", prefix="v1/det")

            @svc.api_async_func("/detect")
            def detect(taskId, body, content_type):
                asyncio.run(platform.task_manager.complete_task(
                    taskId, f"completed - {len(body)} bytes"))

            svc_client = TestClient(TestServer(svc.app))
            await svc_client.start_server()
            platform.publish_async_api(
                "/v1/public/detect", str(svc_client.make_url("/v1/det/detect")))
            gw_client = TestClient(TestServer(platform.gateway.app))
            await gw_client.start_server()
            await platform.start()
            try:
                resp = await gw_client.post("/v1/public/detect", data=b"IMAGE")
                tid = (await resp.json())["TaskId"]
                final = None
                for _ in range(400):
                    poll = await gw_client.get(f"/v1/taskmanagement/task/{tid}")
                    final = await poll.json()
                    if "completed" in final["Status"]:
                        break
                    await asyncio.sleep(0.02)
                assert final["Status"] == "completed - 5 bytes"
            finally:
                await platform.stop()
                platform.broker.close()
                await gw_client.close()
                await svc_client.close()

        run(main())
