"""SSE hub chunk hardening + resume (pipeline/events.py, gateway SSE;
docs/streaming.md):

- bounded per-task CHUNK replay: the newest ``chunk_replay`` chunks are
  kept, older ones drop behind a single synthetic ``truncated`` marker —
  a slow client attaching mid-stream can never hold unbounded token
  history;
- ``Last-Event-ID`` resume on reconnect: replay restarts strictly after
  the client's last consumed event id, through the hub
  (``subscribe(after_seq=)``) and the gateway route (header or
  ``?lastEventId=``);
- the streaming soak (marked ``slow``): a long token stream through
  engine → hub stays inside the bounded buffers.
"""

import asyncio
import json
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.metrics.registry import MetricsRegistry
from ai4e_tpu.pipeline.events import CHUNK, TERMINAL, TRUNCATED, TaskEventHub
from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
from ai4e_tpu.taskstore import APITask


def run(coro):
    return asyncio.run(coro)


def chunk(i):
    return {"stage": "lm", "index": i, "data": {"token": i}}


class TestChunkBoundedReplay:
    def _hub(self, **kw):
        kw.setdefault("metrics", MetricsRegistry())
        return TaskEventHub(**kw)

    def test_tail_ring_keeps_newest_chunks(self):
        hub = self._hub(chunk_replay=3)
        hub.track("t")
        for i in range(10):
            hub.publish("t", CHUNK, chunk(i))
        events = hub.replay("t")
        kinds = [(e["event"], e["data"].get("index")) for e in events
                 if e["event"] == CHUNK]
        assert kinds == [(CHUNK, 7), (CHUNK, 8), (CHUNK, 9)]

    def test_truncated_marker_precedes_surviving_chunks(self):
        hub = self._hub(chunk_replay=3)
        hub.track("t")
        for i in range(10):
            hub.publish("t", CHUNK, chunk(i))
        events = hub.replay("t")
        assert events[0]["event"] == TRUNCATED
        assert events[0]["data"]["dropped_chunks"] == 7
        # The marker sits at the last dropped seq, so a client resuming
        # FROM the marker id gets exactly the surviving chunks.
        assert events[0]["seq"] == 7
        assert [e["seq"] for e in events[1:]] == [8, 9, 10]

    def test_no_marker_under_the_cap(self):
        hub = self._hub(chunk_replay=8)
        hub.track("t")
        for i in range(5):
            hub.publish("t", CHUNK, chunk(i))
        assert all(e["event"] == CHUNK for e in hub.replay("t"))

    def test_resume_past_dropped_range_gets_no_marker(self):
        hub = self._hub(chunk_replay=3)
        hub.track("t")
        for i in range(10):
            hub.publish("t", CHUNK, chunk(i))
        # Client already consumed through seq 8: only seq 9/10 replay,
        # and the truncation (through seq 7) is invisible to it.
        events = hub.replay("t", after_seq=8)
        assert [e["seq"] for e in events] == [9, 10]
        assert all(e["event"] == CHUNK for e in events)

    def test_non_chunk_events_keep_first_n_and_order(self):
        hub = self._hub(replay=4, chunk_replay=2)
        hub.track("t")
        hub.publish("t", "status", {"Status": "running"})
        for i in range(6):
            hub.publish("t", CHUNK, chunk(i))
        hub.publish("t", "stage", {"stage": "lm", "state": "completed"})
        events = hub.replay("t")
        kinds = [e["event"] for e in events]
        # status (seq 1) survives; chunks truncated to the 2 newest; the
        # stage event appended within the non-chunk cap.
        assert kinds == ["status", TRUNCATED, CHUNK, CHUNK, "stage"]

    def test_subscribe_resume_skips_consumed_and_dedups_live(self):
        async def main():
            hub = self._hub(chunk_replay=16)
            hub.track("t")
            for i in range(4):
                hub.publish("t", CHUNK, chunk(i))  # seqs 1..4
            stream = hub.subscribe("t", after_seq=2)
            got = [await stream.next_event(timeout=1.0) for _ in range(2)]
            hub.publish("t", TERMINAL, {"Status": "completed"})
            got.append(await stream.next_event(timeout=1.0))
            assert await stream.next_event(timeout=1.0) is None
            return got

        got = run(main())
        assert [e["seq"] for e in got] == [3, 4, 5]
        assert got[-1]["event"] == TERMINAL


class TestGatewayLastEventIdResume:
    def _parse_sse(self, text):
        events, current = [], {}
        for line in text.splitlines():
            if line.startswith("id: "):
                current["id"] = int(line[4:])
            elif line.startswith("event: "):
                current["event"] = line[7:]
            elif line.startswith("data: "):
                current["data"] = json.loads(line[6:])
            elif line == "" and current:
                events.append(current)
                current = {}
        return events

    def test_reconnect_resumes_after_last_event_id(self):
        async def main():
            platform = LocalPlatform(PlatformConfig(
                pipeline=True, pipeline_chunk_replay=4))
            hub = platform.task_events
            platform.store.upsert(APITask(task_id="t-1", endpoint="/v1/x",
                                          body=b"", publish=False))
            hub.track("t-1")
            for i in range(10):
                hub.publish("t-1", CHUNK, chunk(i))  # seqs 1..10
            platform.store.update_status("t-1", "completed - 10 tokens")
            gw = await serve_gw(platform)
            try:
                # Fresh attach: truncated marker then the surviving tail.
                r1 = await gw.get("/v1/taskmanagement/task/t-1/events",
                                  params={"wait": "2"})
                fresh = self._parse_sse(await r1.text())
                # Reconnect with Last-Event-ID past the drop: no marker,
                # only events after the resume point.
                r2 = await gw.get("/v1/taskmanagement/task/t-1/events",
                                  params={"wait": "2"},
                                  headers={"Last-Event-ID": "8"})
                resumed = self._parse_sse(await r2.text())
                # Query-param spelling for non-EventSource clients.
                r3 = await gw.get("/v1/taskmanagement/task/t-1/events",
                                  params={"wait": "2", "lastEventId": "8"})
                q_resumed = self._parse_sse(await r3.text())
                r4 = await gw.get("/v1/taskmanagement/task/t-1/events",
                                  headers={"Last-Event-ID": "bogus"})
                return fresh, resumed, q_resumed, r4.status
            finally:
                await gw.close()
                await platform.stop()

        fresh, resumed, q_resumed, bad = run(main())
        fresh_types = [e["event"] for e in fresh]
        assert TRUNCATED in fresh_types
        assert fresh_types[-1] == TERMINAL
        chunk_ids = [e["id"] for e in fresh if e["event"] == CHUNK]
        assert chunk_ids == [7, 8, 9, 10]  # the 4 newest survive
        resumed_chunks = [e["id"] for e in resumed if e["event"] == CHUNK]
        assert resumed_chunks == [9, 10]
        assert TRUNCATED not in [e["event"] for e in resumed]
        assert [e["id"] for e in q_resumed if e["event"] == CHUNK] == [9, 10]
        assert bad == 400

    def test_live_stream_resume_mid_decode(self):
        """Attach, consume a few chunks, disconnect, reconnect with
        Last-Event-ID — the resumed stream continues where the client
        stopped, not from the beginning."""

        async def main():
            platform = LocalPlatform(PlatformConfig(pipeline=True))
            hub = platform.task_events
            platform.store.upsert(APITask(task_id="t-2", endpoint="/v1/x",
                                          body=b"", publish=False))
            hub.track("t-2")
            for i in range(3):
                hub.publish("t-2", CHUNK, chunk(i))  # seqs 1..3
            gw = await serve_gw(platform)
            try:
                r1 = await gw.get("/v1/taskmanagement/task/t-2/events",
                                  params={"wait": "0.2"})
                first = self._parse_sse(await r1.text())
                last_id = max(e["id"] for e in first)
                for i in range(3, 6):
                    hub.publish("t-2", CHUNK, chunk(i))  # seqs 4..6
                platform.store.update_status("t-2", "completed - done")
                r2 = await gw.get("/v1/taskmanagement/task/t-2/events",
                                  params={"wait": "2"},
                                  headers={"Last-Event-ID": str(last_id)})
                resumed = self._parse_sse(await r2.text())
                return last_id, resumed
            finally:
                await gw.close()
                await platform.stop()

        last_id, resumed = run(main())
        assert last_id == 3
        resumed_chunks = [e["data"]["index"] for e in resumed
                          if e["event"] == CHUNK]
        assert resumed_chunks == [3, 4, 5]
        assert resumed[-1]["event"] == TERMINAL


async def serve_gw(platform):
    client = TestClient(TestServer(platform.gateway.app))
    await client.start_server()
    await platform.start()
    return client


@pytest.mark.slow
class TestStreamingSoak:
    def test_long_stream_stays_inside_bounded_buffers(self):
        """>30s streaming soak (hence the slow marker): a decode engine
        pushing a long token stream through the hub must keep the
        per-task buffer bounded and the SSE consumer live throughout."""
        from ai4e_tpu.runtime.decode import DecodeEngine
        from tests.test_decode import FakeBackend

        async def main():
            hub = TaskEventHub(replay=64, chunk_replay=32,
                               metrics=MetricsRegistry())
            hub.track("soak")
            backend = FakeBackend(slots=2, max_len=100_000, step_s=0.004)
            engine = DecodeEngine(backend, metrics=MetricsRegistry())
            await engine.start()
            seen = []
            stream = hub.subscribe("soak")

            async def consume():
                while True:
                    event = await stream.next_event(timeout=10.0)
                    if event is None:
                        return
                    seen.append(event["seq"])

            consumer = asyncio.ensure_future(consume())
            t0 = time.monotonic()
            total = 0
            while time.monotonic() - t0 < 32.0:
                out = await engine.submit(
                    [1], 200,
                    on_token=lambda i, t: hub.publish(
                        "soak", CHUNK, chunk(i)))
                total += len(out)
            hub.publish("soak", TERMINAL, {"Status": "completed"})
            await consumer
            await engine.stop()
            engine.pool.check_conservation()
            buffered = hub.replay("soak")
            return total, seen, buffered

        total, seen, buffered = run(main())
        assert total >= 1000
        # The live consumer saw a strictly increasing stream…
        assert all(b > a for a, b in zip(seen, seen[1:]))
        # …while the replay buffer stayed bounded no matter the volume.
        assert len(buffered) <= 64 + 32 + 1
