"""DCT-truncation host↔device wire (``ops/dct.py``): JPEG-grade h2d
compression (0.375 B/px — 4× less than yuv420) whose device decode is dense
linear algebra. Fidelity bar, same discipline as the yuv wire: the trained
checkpoints must predict identically (species) / equivalently (detector)
through the compressed wire, or the wire doesn't ship for that family."""

import io

import numpy as np

from ai4e_tpu.ops.dct import (
    dct_nbytes,
    dct_to_rgb,
    dct_to_rgb_numpy,
    rgb_to_dct,
)
from tests.test_yuv_wire import _load_manifest, _smooth_image


class TestCodec:
    def test_sizes_eight_x_vs_rgb(self):
        flat = rgb_to_dct(_smooth_image())
        assert flat.shape == (dct_nbytes(64, 64),)
        assert flat.dtype == np.int8
        assert flat.nbytes * 8 == 64 * 64 * 3  # 0.375 B/px at K=4

    def test_roundtrip_psnr_on_smooth_content(self):
        img = _smooth_image()
        back = dct_to_rgb_numpy(rgb_to_dct(img), 64, 64).astype(np.float32)
        mse = float(np.mean((back - img.astype(np.float32)) ** 2))
        psnr = 10 * np.log10(255.0 ** 2 / max(mse, 1e-9))
        assert psnr > 30.0, f"PSNR {psnr:.1f} dB too low for smooth content"

    def test_flat_blocks_are_near_lossless(self):
        """Per-16×16-flat content (flat across BOTH the luma block grid and
        the subsampled chroma's): only DC coefficients are nonzero, so
        truncation costs nothing and the error is quantization-only."""
        rng = np.random.default_rng(1)
        blocks = rng.integers(30, 226, size=(4, 4, 3), dtype=np.uint8)
        img = np.repeat(np.repeat(blocks, 16, axis=0), 16, axis=1)
        back = dct_to_rgb_numpy(rgb_to_dct(img), 64, 64).astype(np.float32)
        assert float(np.abs(back - img.astype(np.float32)).max()) <= 14.0

    def test_output_range_and_dtype_device(self):
        img = _smooth_image(seed=3)
        out = np.asarray(dct_to_rgb(rgb_to_dct(img)[None], 64, 64))
        assert out.dtype == np.float32
        assert out.min() >= 0.0 and out.max() <= 1.0

    def test_host_inverse_matches_device_inverse(self):
        img = _smooth_image(seed=9)
        flat = rgb_to_dct(img)
        host = dct_to_rgb_numpy(flat, 64, 64).astype(np.float32)
        device = np.asarray(dct_to_rgb(flat[None], 64, 64))[0] * 255.0
        assert np.abs(host - device).max() <= 1.0  # rounding only

    def test_bad_dims_rejected(self):
        import pytest
        with pytest.raises(ValueError, match="divisible by 16"):
            rgb_to_dct(np.zeros((56, 64, 3), np.uint8))
        with pytest.raises(ValueError, match="uint8"):
            rgb_to_dct(np.zeros((64, 64, 3), np.float32))


class TestUnetDctWire:
    def test_servable_end_to_end_matches_rgb_path(self):
        """Same weights, both wires: class histograms agree to within the
        codec's boundary-pixel noise (land-cover content is large flat
        regions — exactly where DCT truncation is nearly free)."""
        from ai4e_tpu.runtime import ModelRuntime, build_servable

        tile = 64
        rgb = build_servable("unet", name="lc-rgb", tile=tile,
                             widths=[8, 16], num_classes=4, buckets=(8,))
        dct = build_servable("unet", name="lc-dct", tile=tile,
                             widths=[8, 16], num_classes=4, buckets=(8,),
                             wire="dct")
        dct.params = rgb.params
        runtime = ModelRuntime()
        runtime.register(rgb)
        runtime.register(dct)

        rng = np.random.default_rng(7)
        blocks = rng.integers(0, 255, size=(8, 8, 3), dtype=np.uint8)
        img = np.repeat(np.repeat(blocks, 8, axis=0), 8, axis=1)
        batch_rgb = np.repeat(img[None], 8, axis=0)
        batch_dct = np.stack([rgb_to_dct(img)] * 8)

        out_rgb = runtime.run_batch("lc-rgb", batch_rgb)
        out_dct = runtime.run_batch("lc-dct", batch_dct)
        c_rgb = np.asarray(out_rgb["counts"][0], np.int64)
        c_dct = np.asarray(out_dct["counts"][0], np.int64)
        total = tile * tile
        disagreement = int(np.abs(c_rgb - c_dct).sum()) // 2
        assert disagreement <= total * 0.05, (
            f"{disagreement}/{total} pixels changed class", c_rgb, c_dct)

    def test_preprocess_converts_npy_rgb_payload(self):
        from ai4e_tpu.runtime import build_servable

        servable = build_servable("unet", name="lc", tile=64,
                                  widths=[8], num_classes=4, buckets=(1,),
                                  wire="dct")
        buf = io.BytesIO()
        np.save(buf, _smooth_image())
        flat = servable.preprocess(buf.getvalue(), "application/octet-stream")
        assert flat.shape == servable.input_shape
        assert flat.dtype == np.int8

    def test_indivisible_size_rejected_at_build_time(self):
        import pytest

        from ai4e_tpu.runtime import build_servable
        with pytest.raises(ValueError, match="divisible"):
            build_servable("detector", image_size=56, wire="dct",
                           widths=[8], buckets=(1,))

    def test_dct_requires_fused_ingestion_everywhere(self):
        import pytest

        from ai4e_tpu.runtime import build_servable
        for family, flag in (("unet", "fused_postprocess"),
                             ("resnet", "fused_normalize"),
                             ("detector", "fused_normalize")):
            with pytest.raises(ValueError, match=flag):
                build_servable(family, wire="dct", **{flag: False})


class TestNativeCodecParity:
    def test_native_matches_numpy_exactly(self):
        """The C++ encoder (native/dct_codec.cpp) must reproduce the numpy
        reference within 1 quant LSB on every coefficient (measured
        bit-exact on this toolchain — both paths share the same float32
        color math, round-half-to-even, and passed-in quant tables)."""
        from ai4e_tpu.ops.dct import _get_native_encode, _rgb_to_dct_numpy

        if _get_native_encode() is None:
            import pytest
            pytest.skip("native dct codec did not build in this environment")
        rng = np.random.default_rng(123)
        for h, w in ((64, 64), (128, 64), (16, 16)):
            img = rng.integers(0, 256, (h, w, 3), np.uint8)
            a = rgb_to_dct(img).astype(int)
            b = _rgb_to_dct_numpy(img).astype(int)
            assert np.abs(a - b).max() <= 1, (h, w)

    def test_native_output_decodes_identically(self):
        """End to end: a native-encoded wire must decode to the same image
        the numpy-encoded wire does (the device decode path is shared)."""
        from ai4e_tpu.ops.dct import _get_native_encode, _rgb_to_dct_numpy

        if _get_native_encode() is None:
            import pytest
            pytest.skip("native dct codec did not build in this environment")
        img = _smooth_image(seed=11)
        a = dct_to_rgb_numpy(rgb_to_dct(img), 64, 64).astype(int)
        b = dct_to_rgb_numpy(_rgb_to_dct_numpy(img), 64, 64).astype(int)
        assert np.abs(a - b).max() <= 1


class TestFidelityBoundary:
    """VERDICT r4 #6: the color/shape tasks pass any truncation, so their
    gates can't fail — these gates CAN. Class information lives in the
    u∈{2,3} DCT bands (``species_fine_batch``): kept by the shipped K=4
    wire, provably destroyed at K=2, crushed by 4×-coarser quantization."""

    def test_texture_bands_survive_k4_not_k2(self):
        # Pure codec property, checkpoint-free: an exact u=3 luma grating
        # (period 16/3 px) must survive the shipped K=4 roundtrip with most
        # of its amplitude, and be FLATTENED by K=2.
        x = np.arange(64, dtype=np.float32)
        wave = 0.2 * np.cos(np.pi * 3 * (2 * x + 1) / 16.0)
        img01 = np.clip(0.45 + np.broadcast_to(wave[None, :], (64, 64)), 0, 1)
        img = np.round(img01[..., None] * 255).astype(np.uint8)
        img = np.repeat(img, 3, axis=-1)

        def roundtrip_amplitude(k):
            back = dct_to_rgb_numpy(rgb_to_dct(img, k=k), 64, 64, k=k)
            row = back[32, :, 1].astype(np.float32)
            return float(row.max() - row.min())

        original = 0.4 * 255  # peak-to-peak of the grating
        amp4 = roundtrip_amplitude(4)
        amp2 = roundtrip_amplitude(2)
        assert amp4 >= 0.6 * original, (amp4, original)
        assert amp2 <= 0.15 * original, (
            f"K=2 should flatten a u=3 grating; kept {amp2:.1f} of "
            f"{original:.1f}")

    def test_fine_texture_gate_has_measured_failure_boundary(self):
        """The TRAINED fine-texture classifier through the wire: the
        shipped K=4/q50 config passes its gate; K=2 and coarse
        quantization demonstrably FAIL it — a gate with a measured
        failure boundary instead of a saturated task's blind pass.

        Measured boundary (r5, 32 held-out images, seed 43):

        ====  =======  ========
        k     quality  accuracy
        ====  =======  ========
        —     —        0.875     (direct; held-out eval 0.883)
        4     50       0.875     (shipped wire: costs nothing)
        3     50       0.531     (u=3 bands dropped)
        2     50       0.063     (all texture bands dropped → chance)
        4     10       0.781     (≈5× tables: faint classes eroding)
        4     6        0.688     (≈8× tables: faint texture zeroed)
        ====  =======  ========
        """
        import os

        from ai4e_tpu.checkpoint import load_params
        from ai4e_tpu.runtime import ModelRuntime, build_servable
        from ai4e_tpu.train.make_checkpoints import species_fine_batch

        repo, manifest = _load_manifest()
        if "species_fine" not in manifest:
            import pytest
            pytest.skip("no species_fine checkpoint (run the factory with "
                        "--only species_fine)")
        ckpt = os.path.join(repo, "checkpoints", "species_fine")
        kwargs = {k: v for k, v in manifest["species_fine"]["kwargs"].items()
                  if k != "labels"}
        size = kwargs.pop("image_size", 64)
        kwargs.update(image_size=size, buckets=(32,))
        rgb = build_servable("resnet", name="spf-rgb", **kwargs)
        rgb.params = load_params(ckpt, like=rgb.params)
        runtime = ModelRuntime()
        runtime.register(rgb)

        img, labels = species_fine_batch(np.random.default_rng(43), 32, size)
        u8 = np.clip(np.round(img * 255), 0, 255).astype(np.uint8)

        def accuracy(batch) -> float:
            out = np.argmax(np.asarray(runtime.run_batch("spf-rgb", batch)),
                            axis=-1)
            return float((out == labels).mean())

        def through_wire(k, quality=50) -> float:
            back = np.stack([
                np.clip(np.round(dct_to_rgb_numpy(
                    rgb_to_dct(s, k=k, quality=quality), size, size,
                    k=k, quality=quality)), 0, 255).astype(np.uint8)
                for s in u8])
            return accuracy(back)

        direct = accuracy(u8)
        k4 = through_wire(4)
        k2 = through_wire(2)
        coarse = through_wire(4, quality=6)
        assert direct >= 0.80, f"checkpoint not competent: {direct}"
        # Shipped config: the wire costs a sliver, not the task.
        assert k4 >= direct - 0.06, (direct, k4)
        # Failure boundary, truncation side: u≥2 bands gone → the 8 classes
        # collapse to chance (0.125).
        assert k2 <= 0.35, f"K=2 should break the gate; accuracy {k2}"
        # Failure boundary, quantization side: ≈8× tables zero the faint
        # classes' coefficients — the gate measurably degrades.
        assert coarse <= direct - 0.10, (direct, coarse)


class TestTrainedModelFidelity:
    def test_species_checkpoint_classifies_identically_over_dct(self):
        """The TRAINED species classifier must assign the same (correct)
        labels through the dct wire as through rgb8 — the serving gate for
        shipping the compressed wire on this family."""
        import os

        from ai4e_tpu.checkpoint import load_params
        from ai4e_tpu.runtime import ModelRuntime, build_servable
        from ai4e_tpu.train.make_checkpoints import species_batch

        repo, manifest = _load_manifest()
        ckpt = os.path.join(repo, "checkpoints", "species")
        kwargs = {k: v for k, v in manifest["species"]["kwargs"].items()
                  if k != "labels"}
        size = kwargs.pop("image_size", 64)
        kwargs.update(image_size=size, buckets=(8,))
        rgb = build_servable("resnet", name="sp-rgb", **kwargs)
        dct = build_servable("resnet", name="sp-dct", wire="dct", **kwargs)
        rgb.params = load_params(ckpt, like=rgb.params)
        dct.params = rgb.params
        runtime = ModelRuntime()
        runtime.register(rgb)
        runtime.register(dct)

        img, labels = species_batch(np.random.default_rng(42), 8, size)
        batch_u8 = np.clip(np.round(img * 255), 0, 255).astype(np.uint8)
        flat = np.stack([rgb_to_dct(x) for x in batch_u8])

        out_rgb = np.argmax(np.asarray(runtime.run_batch("sp-rgb", batch_u8)),
                            axis=-1)
        out_dct = np.argmax(np.asarray(runtime.run_batch("sp-dct", flat)),
                            axis=-1)
        np.testing.assert_array_equal(out_rgb, labels)  # checkpoint is real
        np.testing.assert_array_equal(out_dct, labels)  # dct wire costs nothing

    def test_trained_detector_finds_same_animals_over_dct(self):
        """TRAINED megadetector through the dct wire: same synthetic scenes,
        equivalent above-threshold detections (the shipped-checkpoint
        criterion, as in the yuv gate)."""
        import os

        from ai4e_tpu.checkpoint import load_params
        from ai4e_tpu.runtime import ModelRuntime, build_servable
        from ai4e_tpu.train.make_checkpoints import (detection_accuracy,
                                                     detector_batch)

        repo, manifest = _load_manifest()
        ckpt = os.path.join(repo, "checkpoints", "megadetector")
        mk = dict(manifest["megadetector"]["kwargs"])
        size = mk.pop("image_size", 128)
        kwargs = dict(image_size=size, buckets=(8,),
                      score_threshold=0.2, **mk)
        rgb = build_servable("detector", name="det-rgb", **kwargs)
        dct = build_servable("detector", name="det-dct", wire="dct",
                             **kwargs)
        rgb.params = load_params(ckpt, like=rgb.params)
        dct.params = rgb.params
        runtime = ModelRuntime()
        runtime.register(rgb)
        runtime.register(dct)

        img, targets = detector_batch(np.random.default_rng(5), 8, size)
        batch_u8 = np.clip(np.round(img * 255), 0, 255).astype(np.uint8)
        flat = np.stack([rgb_to_dct(x) for x in batch_u8])
        out_rgb = runtime.run_batch("det-rgb", batch_u8)
        out_dct = runtime.run_batch("det-dct", flat)

        rgb_hits, total = detection_accuracy(out_rgb, targets,
                                             wh_rel_tolerance=0.5)
        dct_hits, _ = detection_accuracy(out_dct, targets,
                                         wh_rel_tolerance=0.5)
        assert total > 0, "scene generator produced no objects"
        assert rgb_hits >= 0.8 * total, (rgb_hits, total)  # checkpoint real
        # The dct wire may flip at most one borderline object vs rgb.
        assert dct_hits >= rgb_hits - 1, (dct_hits, rgb_hits, total)
