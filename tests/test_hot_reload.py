"""Zero-downtime checkpoint hot reload — POST {prefix}/models/{name}/reload.

The reference updates a model by building and rolling a new container image
(`APIs/Charts/templates/async-gpu`); here jitted programs take params as an
argument, so new weights swap in between batches with no restart and no
recompile. These tests pin the whole loop: serve → retrain (new checkpoint
on disk) → reload over HTTP → predictions change, version bumps — plus the
guards (tree mismatch 409, unknown model 404, no checkpoint 400).
"""

import asyncio
import io

import numpy as np
from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.checkpoint import save_params
from ai4e_tpu.runtime import (InferenceWorker, MicroBatcher, ModelRuntime,
                              build_servable)


def run(coro):
    return asyncio.run(coro)


def _payload():
    buf = io.BytesIO()
    np.save(buf, np.arange(16, dtype=np.float32))
    return buf.getvalue()


async def _worker_client(servable):
    runtime = ModelRuntime()
    runtime.register(servable)
    batcher = MicroBatcher(runtime, max_wait_ms=1.0)
    worker = InferenceWorker("w", runtime, batcher, prefix="v1/echo")
    worker.serve_model(servable, sync_path="/run")
    await batcher.start()
    client = TestClient(TestServer(worker.service.app))
    await client.start_server()
    return client, batcher, runtime


class TestHotReload:
    def test_reload_swaps_weights_and_bumps_version(self, tmp_path):
        async def main():
            servable = build_servable("echo", name="echo", size=16,
                                      buckets=(4,))
            # A "retrained" checkpoint: same tree, scale 3.0 instead of 1.0.
            ckpt = str(tmp_path / "echo_v2")
            save_params(ckpt, {"scale": np.float32(3.0)})

            client, batcher, runtime = await _worker_client(servable)
            try:
                resp = await client.post("/v1/echo/run", data=_payload())
                before = (await resp.json())["echo"]
                assert before[:3] == [0.0, 1.0, 2.0]

                resp = await client.post("/v1/echo/models/echo/reload",
                                         json={"checkpoint": ckpt})
                body = await resp.json()
                assert resp.status == 200, body
                assert body["params_version"] == 2
                assert body["checkpoint"] == ckpt

                resp = await client.post("/v1/echo/run", data=_payload())
                after = (await resp.json())["echo"]
                assert after[:3] == [0.0, 3.0, 6.0]  # new weights serve

                # Introspection reflects the rollout.
                models = (await (await client.get("/v1/echo/models")).json())
                (entry,) = models["models"]
                assert entry["params_version"] == 2
                assert entry["checkpoint"] == ckpt

                # A second reload of the SAME path (no body: reuses the
                # recorded checkpoint) bumps again — operators re-push the
                # same path after retraining in place.
                resp = await client.post("/v1/echo/models/echo/reload")
                assert (await resp.json())["params_version"] == 3
            finally:
                await batcher.stop()
                await client.close()

        run(main())

    def test_mismatched_tree_is_409_and_serving_unchanged(self, tmp_path):
        async def main():
            servable = build_servable("echo", name="echo", size=16,
                                      buckets=(4,))
            ckpt = str(tmp_path / "wrong")
            save_params(ckpt, {"scale": np.zeros((3, 3), np.float32)})

            client, batcher, _ = await _worker_client(servable)
            try:
                resp = await client.post("/v1/echo/models/echo/reload",
                                         json={"checkpoint": ckpt})
                assert resp.status in (400, 409)  # shape mismatch refused
                resp = await client.post("/v1/echo/run", data=_payload())
                assert (await resp.json())["echo"][:3] == [0.0, 1.0, 2.0]
            finally:
                await batcher.stop()
                await client.close()

        run(main())

    def test_unknown_model_404_and_no_checkpoint_400(self):
        async def main():
            servable = build_servable("echo", name="echo", size=16,
                                      buckets=(4,))
            client, batcher, _ = await _worker_client(servable)
            try:
                resp = await client.post("/v1/echo/models/nope/reload")
                assert resp.status == 404
                # echo was built in-memory: no checkpoint recorded.
                resp = await client.post("/v1/echo/models/echo/reload")
                assert resp.status == 400
            finally:
                await batcher.stop()
                await client.close()

        run(main())
