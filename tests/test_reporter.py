"""RequestReporter tests — the cross-replica in-flight counter
(``ProcessManager/RequestReporter/CurrentProcessingUpsert.cs:26-113`` /
``CurrentProcessingGet.cs:27-78``) and the in-service fire-and-forget client
(``ai4e_service.py:135-156``)."""

import asyncio

from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.metrics import MetricsRegistry, ProcessingCounters
from ai4e_tpu.metrics.reporter import (
    ProcessingReporterClient,
    RequestReporterService,
)
from ai4e_tpu.service import APIService


def run(coro):
    return asyncio.run(coro)


async def serve(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


class TestCounters:
    def test_adjust_and_value(self):
        c = ProcessingCounters(MetricsRegistry())
        assert c.adjust("gpu", "/v1/detect", increment=1) == 1
        assert c.adjust("gpu", "/v1/detect", increment=1) == 2
        assert c.adjust("gpu", "/v1/detect", decrement=1) == 1
        assert c.value("gpu", "/v1/detect") == 1
        assert c.value("gpu", "/v1/other") == 0

    def test_gauge_export(self):
        reg = MetricsRegistry()
        c = ProcessingCounters(reg)
        c.adjust("gpu", "/v1/detect", increment=3)
        text = reg.render_prometheus()
        assert "ai4e_current_requests" in text
        assert "3" in text


class TestReporterService:
    def test_upsert_and_get_roundtrip(self):
        async def main():
            svc = RequestReporterService(metrics=MetricsRegistry())
            client = await serve(svc.app)
            try:
                resp = await client.post("/v1/processing", json={
                    "Cluster": "gpu", "Path": "/v1/detect",
                    "IncrementBy": 2, "DecrementBy": 0})
                assert resp.status == 200
                assert (await resp.json())["CurrentRequests"] == 2

                resp = await client.get(
                    "/v1/processing",
                    params={"cluster": "gpu", "path": "/v1/detect"})
                assert (await resp.json())["CurrentRequests"] == 2
            finally:
                await client.close()

        run(main())

    def test_missing_path_rejected(self):
        async def main():
            svc = RequestReporterService(metrics=MetricsRegistry())
            client = await serve(svc.app)
            try:
                resp = await client.post("/v1/processing", json={"Cluster": "x"})
                assert resp.status == 400
                resp = await client.get("/v1/processing")
                assert resp.status == 400
            finally:
                await client.close()

        run(main())


class TestServiceIntegration:
    def test_service_reports_cross_replica_counts(self):
        # Two replicas of the same API reporting to one reporter: the
        # aggregated counter sees the sum — the signal the reference's HPA
        # custom metric scales on (appinsights-metric.yaml:1-7).
        async def main():
            reporter_svc = RequestReporterService(metrics=MetricsRegistry())
            rep_client_http = await serve(reporter_svc.app)
            uri = str(rep_client_http.make_url("/"))

            import threading
            release = threading.Event()
            replicas, clients = [], []
            for i in range(2):
                reporter = ProcessingReporterClient(uri, cluster="tpu")
                svc = APIService(f"echo{i}", prefix="v1/echo",
                                 metrics=MetricsRegistry(), reporter=reporter)

                @svc.api_sync_func("/run")
                def handler(body, content_type):
                    release.wait(timeout=5.0)
                    return {"ok": True}

                replicas.append((svc, reporter))
                clients.append(await serve(svc.app))

            try:
                # One in-flight request per replica, held open by the event.
                posts = [asyncio.create_task(c.post("/v1/echo/run", data=b"x"))
                         for c in clients]
                # Wait for the increments to land on the reporter.
                for _ in range(100):
                    await asyncio.sleep(0.02)
                    if reporter_svc.counters.value("tpu", "/v1/echo/run") == 2:
                        break
                assert reporter_svc.counters.value("tpu", "/v1/echo/run") == 2

                release.set()
                for p in posts:
                    resp = await p
                    assert resp.status == 200
                for svc, reporter in replicas:
                    await reporter.drain()
                assert reporter_svc.counters.value("tpu", "/v1/echo/run") == 0
            finally:
                release.set()
                for svc, reporter in replicas:
                    await reporter.close()
                for c in clients:
                    await c.close()
                await rep_client_http.close()

        run(main())

    def test_dead_reporter_does_not_break_requests(self):
        async def main():
            reporter = ProcessingReporterClient("http://127.0.0.1:1",
                                                cluster="tpu")
            svc = APIService("echo", prefix="v1/echo",
                             metrics=MetricsRegistry(), reporter=reporter)

            @svc.api_sync_func("/run")
            def handler(body, content_type):
                return {"ok": True}

            client = await serve(svc.app)
            try:
                resp = await client.post("/v1/echo/run", data=b"x")
                assert resp.status == 200
            finally:
                await reporter.close()
                await client.close()

        run(main())


class TestConfig:
    def test_reporter_config_from_env(self):
        from ai4e_tpu.config import FrameworkConfig
        cfg = FrameworkConfig.from_env({
            "AI4E_SERVICE_REPORTER_URI": "http://reporter:9000",
            "AI4E_SERVICE_CLUSTER": "tpu-v5e",
        })
        assert cfg.service.reporter_uri == "http://reporter:9000"
        assert cfg.service.cluster == "tpu-v5e"
