"""The shared closed-loop measurement client (utils/loadclient.py — used by
bench.py and examples/loadgen.py) against a live aiohttp app that exhibits
the production failure modes it must survive: 503 backpressure, error
responses, non-JSON bodies, vanished (404) tasks, and tasks stuck
non-terminal. A load tool pointed at a deployment must record these as
failures and keep running, never crash or hang."""

import asyncio
import itertools

import pytest
from aiohttp import ClientSession, TCPConnector, web

from ai4e_tpu.utils.loadclient import run_closed_loop


def run(coro):
    return asyncio.run(coro)


async def _serve(app):
    runner = web.AppRunner(app)
    await runner.setup()
    site = web.TCPSite(runner, "127.0.0.1", 0)
    await site.start()
    return runner, runner.addresses[0][1]


class TestSyncMode:
    def test_mixed_outcomes_counted_not_raised(self):
        """200s count completed; 500s and non-JSON error bodies count
        failed; 503 is backpressure (retried, never a failure)."""
        outcomes = itertools.cycle([200, 500, 503, 200])

        async def main():
            async def handler(request):
                status = next(outcomes)
                if status == 503:
                    return web.Response(status=503, text="busy")
                if status == 500:
                    return web.Response(status=500, text="boom not json")
                return web.json_response({"ok": True})

            app = web.Application()
            app.router.add_post("/api", handler)
            runner, port = await _serve(app)
            try:
                async with ClientSession(
                        connector=TCPConnector(limit=0)) as session:
                    window = await run_closed_loop(
                        session, post_url=f"http://127.0.0.1:{port}/api",
                        payload=b"x", headers={}, mode="sync",
                        concurrency=4, duration=0.8, ramp=0.2)
            finally:
                await runner.cleanup()
            return window

        window = run(main())
        assert window["completed"] > 0
        assert window["failed"] > 0
        assert window["p50_latency_ms"] >= 0

    def test_connection_error_is_a_failure_not_a_crash(self):
        async def main():
            async with ClientSession(
                    connector=TCPConnector(limit=0)) as session:
                # Nothing listens on this port: every request errors.
                return await run_closed_loop(
                    session, post_url="http://127.0.0.1:9/never",
                    payload=b"x", headers={}, mode="sync",
                    concurrency=2, duration=0.5, ramp=0.1)

        window = run(main())
        assert window["completed"] == 0
        assert window["failed"] > 0


class TestAsyncMode:
    def _app(self, *, task_status):
        """Task API: POST creates a task, GET reports ``task_status``."""
        counter = itertools.count()

        async def post(request):
            return web.json_response({"TaskId": str(next(counter))})

        async def status(request):
            st = task_status(request.match_info["tid"])
            if st is None:
                return web.Response(status=404, text="Task not found.")
            return web.json_response({"TaskId": request.match_info["tid"],
                                      "Status": st})

        app = web.Application()
        app.router.add_post("/api", post)
        app.router.add_get("/task/{tid}", status)
        return app

    def _drive(self, app, **kw):
        async def main():
            runner, port = await _serve(app)
            try:
                async with ClientSession(
                        connector=TCPConnector(limit=0)) as session:
                    return await run_closed_loop(
                        session, post_url=f"http://127.0.0.1:{port}/api",
                        payload=b"x", headers={}, mode="async",
                        status_url_for=lambda tid:
                            f"http://127.0.0.1:{port}/task/{tid}",
                        concurrency=3, duration=0.8, ramp=0.2, **kw)
            finally:
                await runner.cleanup()

        return run(main())

    def test_completed_and_failed_tasks_counted(self):
        window = self._drive(self._app(
            task_status=lambda tid: "completed - done" if int(tid) % 2
            else "failed - bad"))
        assert window["completed"] > 0
        assert window["failed"] > 0

    def test_vanished_task_404_is_a_failure_not_a_crash(self):
        window = self._drive(self._app(task_status=lambda tid: None))
        assert window["completed"] == 0
        assert window["failed"] > 0

    def test_stuck_task_hits_deadline_instead_of_hanging(self):
        window = self._drive(
            self._app(task_status=lambda tid: "running - forever"),
            task_timeout=0.3, poll_wait=0.1)
        assert window["completed"] == 0
        assert window["failed"] > 0

    def test_requires_status_url(self):
        async def main():
            async with ClientSession() as session:
                with pytest.raises(ValueError):
                    await run_closed_loop(session, post_url="http://x",
                                          payload=b"", headers={},
                                          mode="async")

        run(main())


class TestThrottleBackpressure:
    def test_429_is_backpressure_not_failure(self):
        """A rate-limited deployment throttles the load tool; throttled
        requests re-enter (honoring a capped Retry-After), never counting
        as failures."""
        import itertools as _it

        outcomes = _it.cycle([429, 200, 200])

        async def main():
            async def handler(request):
                if next(outcomes) == 429:
                    return web.Response(status=429, text="slow down",
                                        headers={"Retry-After": "1"})
                return web.json_response({"ok": True})

            app = web.Application()
            app.router.add_post("/api", handler)
            runner, port = await _serve(app)
            try:
                async with ClientSession(
                        connector=TCPConnector(limit=0)) as session:
                    return await run_closed_loop(
                        session, post_url=f"http://127.0.0.1:{port}/api",
                        payload=b"x", headers={}, mode="sync",
                        concurrency=4, duration=1.0, ramp=0.2)
            finally:
                await runner.cleanup()

        window = run(main())
        assert window["completed"] > 0
        assert window["failed"] == 0  # throttling is not failure


class TestLoadgenHonesty:
    """ISSUE 11 satellite: the window JSON must record OFFERED vs ACHIEVED
    rate and a client-side error taxonomy, so a CPU-bound run can't
    silently report a lower rate as if it were the target."""

    def test_closed_loop_reports_offered_and_error_taxonomy(self):
        outcomes = itertools.cycle([200, 500])

        async def main():
            async def handler(request):
                status = next(outcomes)
                if status == 500:
                    return web.Response(status=500, text="boom")
                return web.json_response({"ok": True})

            app = web.Application()
            app.router.add_post("/v1/echo", handler)
            runner, port = await _serve(app)
            try:
                async with ClientSession(
                        connector=TCPConnector(limit=0)) as session:
                    window = await run_closed_loop(
                        session, post_url=f"http://127.0.0.1:{port}/v1/echo",
                        payload=b"x", headers={}, mode="sync",
                        concurrency=4, duration=0.6, ramp=0.2)
            finally:
                await runner.cleanup()
            # Offered counts every attempt; achieved only completions —
            # with every other request a 500, offered ≈ 2× completed.
            assert window["offered"] >= window["completed"]
            assert window["offered_rate"] >= window["achieved_rate"]
            assert window["client_errors"].get("http_500", 0) > 0
            assert window["achieved_rate"] == window["value"]

        run(main())

    def test_open_loop_offers_the_target_rate_and_reports_saturation(self):
        """The open loop schedules starts by the clock: a slow platform
        still sees the target offered rate, and starts the client could
        not even launch (max_inflight) are recorded as client_saturated
        — never silently dropped."""
        from ai4e_tpu.utils.loadclient import run_open_loop

        async def main():
            accepted, terminal = [], []

            async def post(request):
                return web.json_response({"TaskId": "t-%d" % len(accepted)})

            async def poll(request):
                # Answer terminal instantly — the pacing under test is
                # the POST schedule, not the platform.
                return web.json_response({"Status": "completed"})

            app = web.Application()
            app.router.add_post("/v1/echo", post)
            app.router.add_get("/v1/task/{tid}", poll)
            runner, port = await _serve(app)
            base = f"http://127.0.0.1:{port}"
            try:
                async with ClientSession(
                        connector=TCPConnector(limit=0)) as session:
                    window = await run_open_loop(
                        session, post_url=f"{base}/v1/echo", payload=b"x",
                        headers={}, rate=200.0,
                        status_url_for=lambda t: f"{base}/v1/task/{t}",
                        duration=1.0, ramp=0.3, max_inflight=64,
                        on_accepted=accepted.append,
                        on_terminal=lambda t, s: terminal.append((t, s)))
            finally:
                await runner.cleanup()
            # The offered rate tracks the target (clock-scheduled), within
            # scheduler slack on a busy box.
            assert window["offered_rate"] > 100.0
            assert window["target_rate"] == 200.0
            assert window["total_offered"] >= window["total_launched"]
            assert len(accepted) == window["total_launched"]
            assert len(terminal) >= window["total_completed"]

        run(main())

    def test_open_loop_client_saturation_is_taxonomized(self):
        from ai4e_tpu.utils.loadclient import run_open_loop

        async def main():
            async def post(request):
                return web.json_response({"TaskId": "t"})

            async def poll(request):
                await asyncio.sleep(2.0)  # tasks outlive the client budget
                return web.json_response({"Status": "created"})

            app = web.Application()
            app.router.add_post("/v1/echo", post)
            app.router.add_get("/v1/task/{tid}", poll)
            runner, port = await _serve(app)
            base = f"http://127.0.0.1:{port}"
            try:
                async with ClientSession(
                        connector=TCPConnector(limit=0)) as session:
                    window = await run_open_loop(
                        session, post_url=f"{base}/v1/echo", payload=b"x",
                        headers={}, rate=300.0,
                        status_url_for=lambda t: f"{base}/v1/task/{t}",
                        duration=0.8, ramp=0.2, max_inflight=4,
                        task_timeout=0.5)
            finally:
                await runner.cleanup()
            # 4 pollers wedge instantly; every further offered start is
            # recorded against the CLIENT, not hidden.
            assert window["total_errors"].get("client_saturated", 0) > 0
            assert window["total_offered"] > window["total_launched"]

        run(main())
