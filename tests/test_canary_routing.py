"""Weighted canary backends (`utils/backends.py`) — traffic splitting for
model rollouts across the sync proxy, the queue dispatcher, and the push
webhook. The reference's Istio tier could weight subsets but its shipped
routing never did; here `"backends": [{uri, weight}, ...]` in routes.json
splits every delivery independently, and combined with the worker's
hot-reload endpoint forms the canary→fleet rollout loop.
"""

import asyncio
import random
from collections import Counter

from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

import pytest

from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
from ai4e_tpu.utils.backends import normalize_backends, pick_backend


def run(coro):
    return asyncio.run(coro)


class TestNormalize:
    def test_forms(self):
        assert normalize_backends("http://a/v1/x") == [("http://a/v1/x", 1.0)]
        assert normalize_backends(
            [{"uri": "http://a/v1/x", "weight": 9},
             "http://b/v1/x",
             ("http://c/v1/x", 0)]) == [
            ("http://a/v1/x", 9.0), ("http://b/v1/x", 1.0),
            ("http://c/v1/x", 0.0)]

    def test_path_mismatch_rejected(self):
        # Queue identity, task Endpoint recording, and rebase are all
        # path-derived — a path mismatch must fail at registration, not
        # silently split a queue.
        with pytest.raises(ValueError, match="share one endpoint path"):
            normalize_backends(["http://a/v1/x", "http://b/v1/OTHER"])

    def test_degenerate_sets_rejected(self):
        with pytest.raises(ValueError):
            normalize_backends([])
        with pytest.raises(ValueError, match="weight 0"):
            normalize_backends([("http://a/v1/x", 0), ("http://b/v1/x", 0)])
        with pytest.raises(ValueError, match="negative"):
            normalize_backends([("http://a/v1/x", -1)])

    def test_pick_distribution(self):
        backends = normalize_backends(
            [("http://a/v1/x", 9), ("http://b/v1/x", 1)])
        rng = random.Random(0)
        counts = Counter(pick_backend(backends, rng) for _ in range(2000))
        assert 1650 <= counts["http://a/v1/x"] <= 1950  # ~90%
        assert counts["http://b/v1/x"] == 2000 - counts["http://a/v1/x"]

    def test_zero_weight_entry_never_picked(self):
        backends = normalize_backends(
            [("http://live/v1/x", 1), ("http://drained/v1/x", 0)])
        rng = random.Random(1)
        assert all(pick_backend(backends, rng) == "http://live/v1/x"
                   for _ in range(200))


async def _counting_service(name, hits, task_manager):
    """Minimal async backend that records which instance served each task."""
    app = web.Application()

    async def handle(request):
        tid = request.headers.get("taskId", "")
        hits[name].append(tid)
        await task_manager.complete_task(tid, f"completed - by {name}")
        return web.json_response({"ok": name})

    app.router.add_post("/v1/split/run-async", handle)
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


class TestCanaryDispatch:
    def test_async_deliveries_split_and_drain(self):
        """weight (1, 0): every task to A; flip to (0, 1): every task to B —
        the blue/green rollout flip, through the REAL gateway → store →
        queue → dispatcher path."""
        async def main():
            platform = LocalPlatform(PlatformConfig(retry_delay=0.05))
            hits = {"A": [], "B": []}
            a = await _counting_service("A", hits, platform.task_manager)
            b = await _counting_service("B", hits, platform.task_manager)
            a_uri = str(a.make_url("/v1/split/run-async"))
            b_uri = str(b.make_url("/v1/split/run-async"))
            platform.publish_async_api(
                "/v1/public/split",
                [{"uri": a_uri, "weight": 1}, {"uri": b_uri, "weight": 0}])
            gw = await TestClient(TestServer(platform.gateway.app)).__aenter__()
            await platform.start()
            try:
                for _ in range(6):
                    await gw.post("/v1/public/split", data=b"x")
                for _ in range(200):
                    if len(hits["A"]) + len(hits["B"]) >= 6:
                        break
                    await asyncio.sleep(0.02)
                assert len(hits["A"]) == 6 and not hits["B"]

                # The flip: re-weight by swapping the dispatcher's backend
                # set (what a routes.json update + restart does; in-place
                # here to pin the mechanism).
                (dispatcher,) = platform.dispatchers.dispatchers.values()
                dispatcher.backends = normalize_backends(
                    [{"uri": a_uri, "weight": 0}, {"uri": b_uri, "weight": 1}])
                for _ in range(6):
                    await gw.post("/v1/public/split", data=b"x")
                for _ in range(200):
                    if len(hits["B"]) >= 6:
                        break
                    await asyncio.sleep(0.02)
                assert len(hits["B"]) == 6 and len(hits["A"]) == 6
            finally:
                await platform.stop()
                await gw.close()
                await a.close()
                await b.close()

        run(main())


class TestCanarySyncProxy:
    def test_sync_requests_split_across_backends(self):
        async def main():
            platform = LocalPlatform(PlatformConfig())
            seen = Counter()

            def backend_app(name):
                app = web.Application()

                async def handle(_request):
                    seen[name] += 1
                    return web.json_response({"served_by": name})

                app.router.add_post("/v1/split/run", handle)
                return app

            a = await TestClient(TestServer(backend_app("A"))).__aenter__()
            b = await TestClient(TestServer(backend_app("B"))).__aenter__()
            platform.publish_sync_api(
                "/v1/public/run",
                [{"uri": str(a.make_url("/v1/split/run")), "weight": 1},
                 {"uri": str(b.make_url("/v1/split/run")), "weight": 1}])
            gw = await TestClient(TestServer(platform.gateway.app)).__aenter__()
            try:
                for _ in range(40):
                    resp = await gw.post("/v1/public/run", data=b"x")
                    assert resp.status == 200
                # 50/50 over 40 requests: both sides must serve
                # (P[one side takes all] = 2^-39).
                assert seen["A"] > 0 and seen["B"] > 0
                assert seen["A"] + seen["B"] == 40
            finally:
                await gw.close()
                await a.close()
                await b.close()

        run(main())


class TestCanaryPushWebhook:
    def test_webhook_targets_split_by_weight(self):
        """The push transport's webhook honors weighted routes too — the
        same canary semantics on the Event Grid analogue."""
        from ai4e_tpu.broker.push import WebhookDispatcher
        from ai4e_tpu.service import LocalTaskManager
        from ai4e_tpu.taskstore import InMemoryTaskStore

        webhook = WebhookDispatcher(LocalTaskManager(InMemoryTaskStore()))
        webhook.add_route(
            "/v1/split/run-async",
            [{"uri": "http://fleet:1/v1/split/run-async", "weight": 1},
             {"uri": "http://canary:1/v1/split/run-async", "weight": 1}])
        targets = Counter(
            webhook._target_for("http://edge/v1/split/run-async?x=1")
            for _ in range(60))
        assert targets["http://fleet:1/v1/split/run-async?x=1"] > 0
        assert targets["http://canary:1/v1/split/run-async?x=1"] > 0
        assert sum(targets.values()) == 60


class TestCanaryObservability:
    def test_dispatch_counter_carries_backend_label(self):
        """The rollout loop is "watch the canary's error rate, then
        promote" — the dispatch counter must break out by target host or a
        canary's failures vanish into the fleet's numbers."""
        from urllib.parse import urlparse

        from ai4e_tpu.metrics import DEFAULT_REGISTRY

        async def main():
            platform = LocalPlatform(PlatformConfig(retry_delay=0.05))
            hits = {"A": []}
            a = await _counting_service("A", hits, platform.task_manager)
            a_uri = str(a.make_url("/v1/split/run-async"))
            host = urlparse(a_uri).netloc
            counter = DEFAULT_REGISTRY.counter(
                "ai4e_dispatch_total", "Dispatch attempts by outcome")
            before = counter.value(outcome="delivered",
                                   queue="/v1/split/run-async", backend=host)
            platform.publish_async_api("/v1/public/split", a_uri)
            gw = await TestClient(TestServer(platform.gateway.app)).__aenter__()
            await platform.start()
            try:
                for _ in range(3):
                    await gw.post("/v1/public/split", data=b"x")
                # Poll on the COUNTER: the backend handler returns before
                # the dispatcher reads the response and increments, so
                # polling on hits would race the third increment.
                after = before
                for _ in range(200):
                    after = counter.value(outcome="delivered",
                                          queue="/v1/split/run-async",
                                          backend=host)
                    if after - before >= 3:
                        break
                    await asyncio.sleep(0.02)
                assert after - before == 3
            finally:
                await platform.stop()
                await gw.close()
                await a.close()

        run(main())
