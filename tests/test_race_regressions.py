"""Interleaving regression suite over the platform's hot critical sections
(docs/concurrency.md) — the ai4e-race dynamic prong's "first run".

Three layers, all deterministic (fixed seed, virtual clock):

- **regressions for the stale-guard defects AIL007 found and this PR
  fixed** (dispatcher dead-letter clobber, cache-complete clobber,
  permanent-fail clobber): the FIXED code passes every schedule in the
  budget; for the two method-sized defects a verbatim pre-fix revert
  (taken from the PR 4 tree) is demonstrated caught by the explorer;
- **replays of the PR 3/PR 4 hand-found races on clean reverts**
  (completed→expired clobber, push ``_forward`` double execution, the
  half-open probe-slot leak): each pre-fix body, verbatim from git
  history, is caught within the schedule budget while current code runs
  race-free under the same budget;
- **clean drives over the remaining hot sections** (taskstore
  reaper/redrive vs completion, rescache single-flight + generation
  fencing, breaker transitions, ``GradientLimiter``) — the sections whose
  first explorer run found nothing, pinned so refactors keep it that way;

plus the documentation test for the REMOTE-store residual window
(``TracedTaskManager(hop=True)``): probe-then-write over an HTTP hop has
an irreducible one-suspension window — the accepted platform contract
whose cure is the store's atomic conditional verbs — and this suite
proves both halves (the window is reachable; ``update_status_if`` closes
it).

The chaos invariant enforced throughout: once a task reaches a terminal
canonical status, that canonical status never changes again — the
client-visible double-outcome ``chaos/invariants.py`` rejects, here
checked per explored schedule instead of per seeded run.
"""

import asyncio
import random

import pytest

aiohttp = pytest.importorskip(
    "aiohttp")  # broker imports it; the race-smoke job installs it (no JAX)

from ai4e_tpu.admission.controller import GradientLimiter
from ai4e_tpu.analysis.race import (TracedTaskManager, explore_interleavings,
                                    yield_point)
from ai4e_tpu.broker.dispatcher import AWAITING_STATUS, Dispatcher
from ai4e_tpu.broker.push import PushEvent, WebhookDispatcher
from ai4e_tpu.broker.queue import EndpointQueue, InMemoryBroker, Message
from ai4e_tpu.metrics.registry import MetricsRegistry
from ai4e_tpu.rescache.cache import ResultCache
from ai4e_tpu.resilience.breaker import CircuitBreaker
from ai4e_tpu.resilience.health import BackendHealth, ResiliencePolicy
from ai4e_tpu.service.task_manager import LocalTaskManager
from ai4e_tpu.taskstore import APITask, InMemoryTaskStore, TaskStatus
from ai4e_tpu.taskstore.reaper import TaskReaper

pytestmark = pytest.mark.race

SEED = 20260803
SCHEDULES = 60


class TerminalInvariant:
    """Once terminal, a task's canonical status never changes again."""

    def __init__(self, store):
        self.violations = []
        # Seed from current state: a task that is ALREADY terminal when
        # the invariant attaches (the lost-response replays) must count
        # any later canonical change as a clobber.
        self._terminal_as = {
            t.task_id: t.canonical_status for t in store.snapshot()
            if t.canonical_status in TaskStatus.TERMINAL}
        store.add_listener(self._on_change)

    def _on_change(self, task):
        prev = self._terminal_as.get(task.task_id)
        cur = task.canonical_status
        if prev is not None and cur != prev:
            self.violations.append(
                (task.task_id, f"{prev} -> {cur} ({task.status!r})"))
        if cur in TaskStatus.TERMINAL:
            self._terminal_as[task.task_id] = cur

    def check(self):
        assert not self.violations, (
            f"terminal status clobbered: {self.violations}")


def _seeded_task(store, broker, task_id="t1", queue="/v1/q",
                 status=TaskStatus.CREATED, deadline_at=0.0):
    task = store.upsert(APITask(task_id=task_id, endpoint=queue + "/op",
                                body=b"payload", publish=False))
    if status != TaskStatus.CREATED:
        store.update_status(task_id, status, status)
    if broker is not None:
        task.deadline_at = deadline_at
        broker.publish(task)
    return task


def _dispatcher(cls, broker, tm, queue="/v1/q", **kw):
    return cls(broker, queue, "http://backend", tm, retry_delay=0.001,
               metrics=MetricsRegistry(), rng=random.Random(0),
               resilience=BackendHealth(metrics=MetricsRegistry()), **kw)


# -- fake HTTP plumbing (the backend hop, with a real suspension) -------------


class _FakeResponse:
    def __init__(self, status):
        self.status = status
        self.headers = {}  # the dispatcher consults X-Draining

    async def read(self):
        return b""


class _FakePost:
    def __init__(self, backend, url):
        self.backend = backend
        self.url = url

    async def __aenter__(self):
        await yield_point()  # the network round trip
        return _FakeResponse(self.backend.execute(self.url))

    async def __aexit__(self, *exc):
        return False


class FakeBackend:
    """Stands in for ``SessionHolder``: ``execute`` runs per POST (counts
    executions, optionally completes the task like a real service shell),
    and the POST awaits one yield point — the suspension a real delivery
    always has."""

    def __init__(self, status=200, on_execute=None):
        self.status = status
        self.on_execute = on_execute
        self.executions = 0

    def execute(self, url):
        self.executions += 1
        if self.on_execute is not None:
            self.on_execute()
        return self.status

    # SessionHolder surface
    async def get(self):
        return self

    # session surface
    def post(self, url, **kwargs):
        return _FakePost(self, url)

    async def close(self):
        pass


class AsyncHopResultStore:
    """Duck-typed result store with the HTTP hop a remote deployment has
    (``HttpResultStore``): one suspension before the write lands."""

    def __init__(self, store):
        self.store = store

    async def set_result(self, task_id, payload,
                         content_type="application/json"):
        await yield_point()
        self.store.set_result(task_id, payload, content_type=content_type)


# -- this PR's fixes: dispatcher stale-guard clobbers -------------------------


class RevertedDeadLetterDispatcher(Dispatcher):
    """``_backpressure`` verbatim from the PR 4 tree — no terminal re-check
    before the dead-letter write (the AIL007 finding)."""

    async def _backpressure(self, msg, backend):
        if self.resilience is not None and await self._suppress_duplicate(msg):
            return
        self._dispatched.inc(outcome="backpressure", queue=self.queue_name,
                             backend=backend)
        await self._try_update(msg.task_id, AWAITING_STATUS,
                               TaskStatus.CREATED)
        await asyncio.sleep(self._redelivery_delay(msg))
        if not self.broker.abandon(msg):
            self._dispatched.inc(outcome="dead_letter",
                                 queue=self.queue_name, backend=backend)
            await self._try_update(msg.task_id, TaskStatus.DEAD_LETTER,
                                   TaskStatus.FAILED)


def _deadletter_scenario(cls):
    def make():
        store = InMemoryTaskStore()
        broker = InMemoryBroker(max_delivery_count=1)
        broker.register_queue("/v1/q")
        tm = TracedTaskManager(LocalTaskManager(store))
        d = _dispatcher(cls, broker, tm)
        _seeded_task(store, broker)
        invariant = TerminalInvariant(store)

        async def deliver():
            msg = await broker.receive("/v1/q", timeout=1.0)
            await d._backpressure(msg, "backend")

        async def completer():
            # The lost-response backend finishing mid-backoff: its own
            # response hop is the one suspension before the completion.
            await yield_point()
            await tm.update_task_status("t1", "completed",
                                        TaskStatus.COMPLETED)

        return [deliver(), completer()], invariant.check

    return make


class TestDeadLetterClobber:
    def test_fixed_dispatcher_race_free(self):
        report = explore_interleavings(_deadletter_scenario(Dispatcher),
                                       schedules=SCHEDULES, seed=SEED)
        assert report.ok, report.describe()

    def test_reverted_dispatcher_caught(self):
        report = explore_interleavings(
            _deadletter_scenario(RevertedDeadLetterDispatcher),
            schedules=SCHEDULES, seed=SEED)
        assert not report.ok
        assert "clobbered" in str(report.failures[0].error)


class RevertedCacheCompleteDispatcher(Dispatcher):
    """``_complete_from_cache`` tail verbatim from the PR 4 tree — the
    terminality probe runs BEFORE the result-store hop and is never
    re-checked after it."""

    async def _complete_from_cache(self, msg):
        key = getattr(msg, "cache_key", "")
        if self.result_cache is None or not key:
            return False
        found = self.result_cache.get(key, count=False)
        if found is None:
            return False
        if (self.task_manager is not None
                and await self.task_manager.is_terminal(msg.task_id)):
            self.broker.complete(msg)
            self._dispatched.inc(outcome="duplicate", queue=self.queue_name,
                                 backend="")
            return True
        if self.result_store is None:
            return False
        payload, ctype = found
        import inspect
        res = self.result_store.set_result(msg.task_id, payload,
                                           content_type=ctype)
        if inspect.isawaitable(res):
            await res
        self.broker.complete(msg)
        self._dispatched.inc(outcome="cache_hit", queue=self.queue_name,
                             backend="")
        await self._try_update(msg.task_id, "completed - served from cache",
                               TaskStatus.COMPLETED)
        return True


def _cache_complete_scenario(cls):
    def make():
        store = InMemoryTaskStore()
        broker = InMemoryBroker(max_delivery_count=4)
        broker.register_queue("/v1/q")
        tm = TracedTaskManager(LocalTaskManager(store))
        cache = ResultCache(metrics=MetricsRegistry())
        key = "/v1/q|deadbeef"
        cache.put(key, b"cached-result")
        d = _dispatcher(cls, broker, tm, result_cache=cache,
                        result_store=AsyncHopResultStore(store))
        _seeded_task(store, broker, status=TaskStatus.RUNNING)
        invariant = TerminalInvariant(store)

        async def deliver():
            msg = await broker.receive("/v1/q", timeout=1.0)
            msg.cache_key = key
            await d._complete_from_cache(msg)

        async def reaper_fail():
            # The reaper giving up on the stuck-running task — an atomic
            # conditional transition, exactly as taskstore.reaper does it.
            await yield_point()
            store.update_status_if(
                "t1", TaskStatus.RUNNING,
                "failed - no progress after 3 rescues",
                backend_status=TaskStatus.FAILED)

        return [deliver(), reaper_fail()], invariant.check

    return make


class TestCacheCompleteClobber:
    def test_fixed_dispatcher_race_free(self):
        report = explore_interleavings(_cache_complete_scenario(Dispatcher),
                                       schedules=SCHEDULES, seed=SEED)
        assert report.ok, report.describe()

    def test_reverted_dispatcher_caught(self):
        report = explore_interleavings(
            _cache_complete_scenario(RevertedCacheCompleteDispatcher),
            schedules=SCHEDULES, seed=SEED)
        assert not report.ok
        assert "failed -> completed" in str(report.failures[0].error)


class TestPermanentFailClobber:
    """The third AIL007 fix: ``_dispatch_one``'s permanent-failure write
    now re-checks terminality after the POST round trip. No revert replica
    (the method is the whole delivery loop); instead the regression is
    behavioral — remove the re-check and the clobber schedule fails this
    test, and the ``duplicate`` outcome proves the re-check actually fires
    in at least one explored schedule."""

    def test_fixed_dispatch_race_free_and_suppresses(self):
        duplicates = []

        def make():
            store = InMemoryTaskStore()
            broker = InMemoryBroker(max_delivery_count=4)
            broker.register_queue("/v1/q")
            tm = TracedTaskManager(LocalTaskManager(store))
            d = _dispatcher(Dispatcher, broker, tm)
            backend = FakeBackend(status=400)  # permanent-failure class
            d._sessions = backend
            _seeded_task(store, broker)
            invariant = TerminalInvariant(store)

            async def deliver():
                msg = await broker.receive("/v1/q", timeout=1.0)
                await d._dispatch_one(msg)

            async def completer():
                # A concurrent duplicate's execution completing while this
                # attempt's POST is in flight — guarded like the PR 4
                # service shell (probe + write, atomic in-process).
                await yield_point()
                if not await tm.is_terminal("t1"):
                    await tm.update_task_status("t1", "completed",
                                                TaskStatus.COMPLETED)

            def check():
                invariant.check()
                duplicates.append(d._dispatched.value(
                    outcome="duplicate", queue="/v1/q",
                    backend="backend"))

            return [deliver(), completer()], check

        report = explore_interleavings(make, schedules=SCHEDULES, seed=SEED)
        assert report.ok, report.describe()
        # The re-check must have fired (outcome=duplicate) in at least one
        # explored schedule — otherwise the window was never exercised and
        # this test proves nothing.
        assert any(duplicates), "no schedule exercised the re-check window"


# -- PR 3/PR 4 replays on clean reverts ---------------------------------------


class RevertedExpiredDispatcher(Dispatcher):
    """``_drop_expired`` verbatim from the PR 3 tree — no terminality
    probe: a lease-expiry redelivery of a COMPLETED task whose deadline
    passed was stamped ``expired`` (the completed→expired clobber PR 4
    fixed by hand)."""

    async def _drop_expired(self, msg):
        import time as _time
        deadline_at = getattr(msg, "deadline_at", 0.0)
        if not deadline_at or _time.time() < deadline_at:
            return False
        from ai4e_tpu.admission.deadline import expired_status
        self.broker.complete(msg)
        self._dispatched.inc(outcome="expired", queue=self.queue_name,
                             backend="")
        if self.admission is not None:
            self.admission.note_expired("dispatcher",
                                        getattr(msg, "priority", 1))
        await self._try_update(msg.task_id, expired_status("dispatcher"),
                               TaskStatus.EXPIRED)
        return True


def _expired_scenario(cls):
    def make():
        store = InMemoryTaskStore()
        broker = InMemoryBroker(max_delivery_count=4)
        broker.register_queue("/v1/q")
        tm = TracedTaskManager(LocalTaskManager(store))
        d = _dispatcher(cls, broker, tm)
        # The PR 3 incident shape: the task COMPLETED (lost-response
        # execution), then its lease-expiry redelivery pops with the
        # deadline already past.
        _seeded_task(store, broker, status=TaskStatus.COMPLETED,
                     deadline_at=1.0)
        invariant = TerminalInvariant(store)

        async def deliver():
            msg = await broker.receive("/v1/q", timeout=1.0)
            await d._drop_expired(msg)

        return [deliver()], invariant.check

    return make


class TestReplayCompletedExpiredClobber:
    def test_fixed_dispatcher_suppresses_duplicate(self):
        report = explore_interleavings(_expired_scenario(Dispatcher),
                                       schedules=20, seed=SEED)
        assert report.ok, report.describe()

    def test_pr3_revert_caught(self):
        report = explore_interleavings(
            _expired_scenario(RevertedExpiredDispatcher),
            schedules=20, seed=SEED)
        assert not report.ok
        assert "completed -> expired" in str(report.failures[0].error)


class RevertedWebhookDispatcher(WebhookDispatcher):
    """``_forward`` without the retried-delivery terminality suppression —
    the PR 3 tree's webhook (PR 4 added the ``attempts > 1`` guard): a
    retried delivery trailing a lost-response execution re-executed the
    task on the backend."""

    async def _forward(self, event):
        target = self._target_for(event.subject)
        if target is None:
            self._forwarded.inc(outcome="unroutable")
            await self._try_update(
                event.id, f"failed - no backend route for {event.subject}",
                TaskStatus.FAILED)
            return 200
        from urllib.parse import urlparse
        backend = urlparse(target).netloc
        session = await self._sessions.get()
        with self.tracer.span("webhook_dispatch", task_id=event.id) as span:
            headers = {"taskId": event.id,
                       "Content-Type": event.content_type,
                       **self.tracer.headers()}
            async with session.post(target, data=event.data,
                                    headers=headers) as resp:
                status = resp.status
                await resp.read()
            span.attrs["http_status"] = status
        if 200 <= status < 300:
            self._forwarded.inc(outcome="delivered", backend=backend)
            return 200
        self._forwarded.inc(outcome="failed", backend=backend)
        await self._try_update(event.id,
                               f"failed - backend returned {status}",
                               TaskStatus.FAILED)
        return 200


def _forward_scenario(cls):
    def make():
        store = InMemoryTaskStore()
        tm = TracedTaskManager(LocalTaskManager(store))
        wd = cls(tm, metrics=MetricsRegistry())
        wd.add_route("/v1/q", "http://backend")
        _seeded_task(store, None)
        backend = FakeBackend(
            status=200,
            on_execute=lambda: store.update_status(
                "t1", "completed", TaskStatus.COMPLETED))
        wd._sessions = backend

        def event(attempt):
            ev = PushEvent(id="t1", subject="/v1/q/op", data=b"payload")
            ev.attempts = attempt
            return ev

        async def topic_retry():
            # Attempt 1 executes; its response is "lost" upstream, so the
            # topic redelivers as attempt 2 after backoff.
            await wd._forward(event(1))
            await asyncio.sleep(10.0)  # topic backoff (virtual)
            await wd._forward(event(2))

        def check():
            assert backend.executions == 1, (
                f"task executed {backend.executions}x — the retried "
                "delivery re-ran a completed task on the backend")

        return [topic_retry()], check

    return make


class TestReplayPushForwardDoubleExecution:
    def test_fixed_webhook_suppresses_retry_of_completed_task(self):
        report = explore_interleavings(_forward_scenario(WebhookDispatcher),
                                       schedules=20, seed=SEED)
        assert report.ok, report.describe()

    def test_pr3_revert_caught(self):
        report = explore_interleavings(
            _forward_scenario(RevertedWebhookDispatcher),
            schedules=20, seed=SEED)
        assert not report.ok
        assert "executed 2x" in str(report.failures[0].error)


class LeakyBreaker(CircuitBreaker):
    """``available`` without the time-based probe-slot escape — the PR 3
    review find: a probe whose delivery was cancelled before any outcome
    was recorded pinned its slot, ejecting the backend forever."""

    def available(self, now=None):
        if self.state == "closed":
            return True
        now = self._clock() if now is None else now
        if self.state == "open":
            return (now - self._opened_at >= self.recovery_seconds
                    and self._probes_inflight < self.half_open_probes)
        return self._probes_inflight < self.half_open_probes


def _probe_leak_scenario(cls):
    def make():
        clock = [0.0]
        br = cls(failure_threshold=2, recovery_seconds=30.0,
                 clock=lambda: clock[0])

        async def trip_and_vanish():
            br.record_failure()
            await yield_point()
            br.record_failure()          # trips open
            clock[0] += 31.0             # cooldown elapses
            assert br.available()
            br.begin_probe()             # probe dispatched ...
            await yield_point()          # ... and its delivery is
            #                              cancelled: no outcome ever lands.

        async def later_probe():
            await yield_point()
            clock[0] += 62.0             # two more cooldowns of silence

        def check():
            # However the clock advances interleaved: after one more full
            # cooldown of silence past EVERYTHING above, the slot must be
            # free again.
            clock[0] += 31.0
            assert br.available(), (
                "probe slot leaked: backend ejected forever after a "
                "vanished probe")

        return [trip_and_vanish(), later_probe()], check

    return make


class TestReplayHalfOpenProbeSlotLeak:
    def test_fixed_breaker_frees_the_slot_by_time(self):
        report = explore_interleavings(_probe_leak_scenario(CircuitBreaker),
                                       schedules=20, seed=SEED)
        assert report.ok, report.describe()

    def test_pr3_revert_caught(self):
        report = explore_interleavings(_probe_leak_scenario(LeakyBreaker),
                                       schedules=20, seed=SEED)
        assert not report.ok
        assert "leaked" in str(report.failures[0].error)


# -- clean drives over the remaining hot sections -----------------------------


class TestTaskstoreReaperRedrive:
    def test_reaper_rescue_vs_completion_race_free(self):
        def make():
            store = InMemoryTaskStore()
            published = []
            store.set_publisher(published.append)
            tm = TracedTaskManager(LocalTaskManager(store))
            reaper = TaskReaper(store, running_timeout=0.0, interval=3600,
                                metrics=MetricsRegistry())
            _seeded_task(store, None, status=TaskStatus.RUNNING)
            invariant = TerminalInvariant(store)

            async def sweep():
                await yield_point()
                await reaper.sweep()

            async def completer():
                await yield_point()
                await tm.update_task_status("t1", "completed",
                                            TaskStatus.COMPLETED)

            def check():
                invariant.check()
                final = store.get("t1").canonical_status
                if final == TaskStatus.COMPLETED:
                    return  # completion won or survived the requeue
                # The rescue won: the task must be back in CREATED with
                # its replayed body published, never wedged.
                assert final == TaskStatus.CREATED
                assert published

            return [sweep(), completer()], invariant.check

        report = explore_interleavings(make, schedules=40, seed=SEED)
        assert report.ok, report.describe()

    def test_reaper_give_up_vs_completion_race_free(self):
        def make():
            store = InMemoryTaskStore()
            tm = TracedTaskManager(LocalTaskManager(store))
            reaper = TaskReaper(store, running_timeout=0.0, interval=3600,
                                max_requeues=0, metrics=MetricsRegistry())
            _seeded_task(store, None, status=TaskStatus.RUNNING)
            invariant = TerminalInvariant(store)

            async def sweep():
                await yield_point()
                await reaper.sweep()

            async def completer():
                # Guarded completion (the PR 4 service-shell idiom): the
                # reaper may have failed the task first; an unguarded
                # completed-stamp over it is the bug class, not this
                # fixture's subject.
                await yield_point()
                if not await tm.is_terminal("t1"):
                    await tm.update_task_status("t1", "completed",
                                                TaskStatus.COMPLETED)

            return [sweep(), completer()], invariant.check

        report = explore_interleavings(make, schedules=40, seed=SEED)
        assert report.ok, report.describe()


class TestRescacheInflight:
    def test_single_flight_has_exactly_one_leader(self):
        def make():
            cache = ResultCache(metrics=MetricsRegistry())
            key = "/v1/q|cafe"
            wins = []

            async def gateway(tid):
                await yield_point()
                if cache.register_inflight(key, tid):
                    wins.append(tid)
                else:
                    assert cache.leader_for(key) is not None

            def check():
                assert len(wins) == 1, f"leaders: {wins}"

            return [gateway("a"), gateway("b")], check

        report = explore_interleavings(make, schedules=40, seed=SEED)
        assert report.ok, report.describe()

    def test_generation_fencing_refuses_stale_fill(self):
        def make():
            cache = ResultCache(metrics=MetricsRegistry())
            key = "/v1/q|cafe"
            family = "/v1/q"
            captured = {}

            async def leader():
                captured["gen"] = cache.generation(key)
                await yield_point()  # computing on the old weights
                captured["ok"] = cache.put(key, b"result",
                                           if_generation=captured["gen"])

            async def reloader():
                await yield_point()
                cache.invalidate_family(family)

            def check():
                # Whatever the interleaving: a fill that landed must be
                # provably fresh — if the entry is present, no invalidation
                # has advanced the generation since the leader captured it.
                if cache.peek(key):
                    assert cache.generation(key) == captured["gen"], (
                        "stale fill served after invalidation")

            return [leader(), reloader()], check

        report = explore_interleavings(make, schedules=40, seed=SEED)
        assert report.ok, report.describe()

    def test_fill_inflight_vs_invalidate_race_free(self):
        def make():
            cache = ResultCache(metrics=MetricsRegistry())
            key = "/v1/q|cafe"
            cache.register_inflight(key, "t1")

            async def filler():
                await yield_point()  # the execution
                cache.fill_inflight(key, "t1", b"result")

            async def reloader():
                await yield_point()
                cache.invalidate_family("/v1/q")

            def check():
                # Invalidation after the fill drops the entry; before the
                # fill it clears the registration so the fill refuses.
                # Either way no stale entry AND no orphaned registration
                # blocking the next identical request forever... unless a
                # successful fill already released it.
                assert cache.leader_for(key) is None

            return [filler(), reloader()], check

        report = explore_interleavings(make, schedules=40, seed=SEED)
        assert report.ok, report.describe()


class TestBreakerTransitions:
    def test_concurrent_delivery_loops_trip_and_recover(self):
        def make():
            clock = [0.0]
            health = BackendHealth(
                ResiliencePolicy(failure_threshold=2, recovery_seconds=5.0),
                metrics=MetricsRegistry(), clock=lambda: clock[0],
                rng=random.Random(0))
            backends = [("http://b", 1)]

            async def failing_loop():
                for _ in range(2):
                    uri = health.pick(backends, None)
                    await yield_point()  # the POST
                    health.record_failure(uri)

            async def probing_loop():
                await yield_point()
                clock[0] += 6.0  # cooldown elapses
                uri = health.pick(backends, None)
                await yield_point()
                health.observe_status(uri, 200)

            def check():
                br = health.breaker_for("http://b")
                assert br.state in ("closed", "open", "half_open")
                assert 0 <= br._probes_inflight <= br.half_open_probes
                # However the loops interleaved, the backend must be
                # reachable again once a success lands or the cooldown
                # passes — never ejected forever.
                clock[0] += 6.0
                assert br.available()

            return [failing_loop(), probing_loop()], check

        report = explore_interleavings(make, schedules=60, seed=SEED)
        assert report.ok, report.describe()


class TestGradientLimiter:
    def test_concurrent_observe_and_backoff_keep_limit_bounded(self):
        def make():
            limiter = GradientLimiter(initial=8, min_limit=1, max_limit=64,
                                      window=4)

            async def observer():
                for rtt in (0.01, 0.02, 0.5, 0.01, 0.01):
                    limiter.observe(rtt, inflight=4)
                    await yield_point()

            async def backer():
                for _ in range(3):
                    await yield_point()
                    limiter.backoff()

            def check():
                assert 1 <= limiter.limit <= 64

            return [observer(), observer(), backer()], check

        report = explore_interleavings(make, schedules=60, seed=SEED)
        assert report.ok, report.describe()


# -- the documented remote-store residual window ------------------------------


class TestRemoteStoreResidualWindow:
    """docs/concurrency.md §"the residual window": over an HTTP store hop,
    probe-then-write is irreducibly non-atomic — one suspension separates
    the probe's answer from the write landing. The platform ACCEPTS that
    window for its probe-guarded cold paths and closes it where it must
    win with the store's atomic conditional verbs. Both halves proven
    here, so the paragraph can't rot."""

    def test_probe_then_write_window_is_reachable_over_a_hop(self):
        def make():
            store = InMemoryTaskStore()
            tm = TracedTaskManager(LocalTaskManager(store), hop=True)
            _seeded_task(store, None, status=TaskStatus.RUNNING)
            invariant = TerminalInvariant(store)

            async def prober_writer():
                if not await tm.is_terminal("t1"):
                    await tm.update_task_status("t1", "expired - deadline",
                                                TaskStatus.EXPIRED)

            async def completer():
                await tm.update_task_status("t1", "completed",
                                            TaskStatus.COMPLETED)

            return [prober_writer(), completer()], invariant.check

        report = explore_interleavings(make, schedules=40, seed=SEED)
        assert not report.ok, (
            "the documented residual window was not reachable — either the "
            "hop model changed or the docs are now wrong")

    def test_conditional_verb_closes_the_window(self):
        def make():
            store = InMemoryTaskStore()
            tm = TracedTaskManager(LocalTaskManager(store), hop=True)
            _seeded_task(store, None, status=TaskStatus.RUNNING)
            invariant = TerminalInvariant(store)

            async def conditional_writer():
                await yield_point()  # the request hop
                # The store-side atomic verb: transition only if still
                # running (what the HTTP surface's /update-if exposes).
                store.update_status_if("t1", TaskStatus.RUNNING,
                                       "expired - deadline",
                                       backend_status=TaskStatus.EXPIRED)

            async def completer():
                await yield_point()  # its own request hop
                store.update_status_if("t1", TaskStatus.RUNNING,
                                       "completed",
                                       backend_status=TaskStatus.COMPLETED)

            return [conditional_writer(), completer()], invariant.check

        report = explore_interleavings(make, schedules=40, seed=SEED)
        assert report.ok, report.describe()


# ---------------------------------------------------------------------------
# PR 6: sharded-store critical sections (docs/sharding.md)
# ---------------------------------------------------------------------------


class TestRebalanceHandoffRace:
    """The rebalance handoff's stale-owner window: a writer resolves the
    ring, suspends (the hop), and the slot moves before its write lands.
    The store-side ownership fence (``NotOwnerError``, checked under the
    old owner's lock — the same lock the ring flip holds) refuses the
    stale write and the ring re-route lands it on the new owner; with the
    fence disabled, the exact same schedules resurrect the task on the
    old owner — a divergent orphan copy no client read would ever see
    updated again."""

    @staticmethod
    def _scenario(fenced: bool):
        from ai4e_tpu.taskstore import NotOwnerError
        from ai4e_tpu.taskstore.sharding import ShardedTaskStore

        def make():
            store = ShardedTaskStore(2, slots=8)
            if not fenced:
                for g in store.groups:  # the pre-fence world, verbatim
                    g.active.set_write_fence(None)
            store.upsert(APITask(task_id="t-race", endpoint="/v1/q/op",
                                 body=b"b", publish=False))
            slot = store.ring.slot_for("t-race")
            src = store.ring.shard_of_slot(slot)
            dest = 1 - src

            async def stale_writer():
                # Remote-client shape: resolve the owner, hop, write — the
                # requeue/AWAITING upsert every transport cold path makes.
                owner = store.groups[store.ring.shard_for("t-race")].active
                await yield_point()  # the hop the flip can slot into
                retry = APITask(task_id="t-race", endpoint="/v1/q/op",
                                body=b"", status=AWAITING_STATUS,
                                backend_status=TaskStatus.CREATED,
                                publish=False)
                try:
                    owner.upsert(retry)
                except NotOwnerError:
                    # Fenced: re-route via a fresh ring lookup (what the
                    # facade's _route loop does).
                    store.upsert(retry)

            async def mover():
                await yield_point()
                store.move_slot(slot, dest)

            def check():
                src_store = store.groups[src].active
                dest_store = store.groups[dest].active
                assert "t-race" not in src_store._tasks, (
                    "stale-owner write resurrected the task on the old "
                    "owner after the handoff")
                assert dest_store.get("t-race").status == AWAITING_STATUS

            return [stale_writer(), mover()], check

        return make

    def test_fenced_handoff_race_free(self):
        report = explore_interleavings(self._scenario(fenced=True),
                                       schedules=SCHEDULES, seed=SEED)
        assert report.ok, report.describe()

    def test_unfenced_replica_caught(self):
        report = explore_interleavings(self._scenario(fenced=False),
                                       schedules=SCHEDULES, seed=SEED)
        assert not report.ok, (
            "the stale-owner window was not reachable without the fence — "
            "either move_slot stopped forgetting the range or the "
            "scenario no longer models the handoff")


class TestFeedAttachRace:
    """The change feed's attach window: a watcher reads a non-terminal
    status, suspends, and the terminal event fires before it attaches.
    ``wait_terminal`` checks the bounded replay map and registers the
    waiter under ONE lock, so the event is either replayed at attach or
    delivered to the future — a replica without the replay check misses
    the wakeup on exactly those schedules and waits out its (virtual)
    timeout."""

    @staticmethod
    def _scenario(feed_cls):
        from ai4e_tpu.taskstore.sharding import ShardedTaskStore

        def make():
            store = ShardedTaskStore(2, slots=8)
            feed = feed_cls(0)
            store.feeds = [feed, feed]  # both shards relay into one feed
            store.upsert(APITask(task_id="t-watch", endpoint="/v1/q/op",
                                 body=b"b", publish=False))
            results = []

            async def watcher():
                # The gateway's long-poll shape: read, then attach.
                record = store.get("t-watch")
                if record.canonical_status in TaskStatus.TERMINAL:
                    results.append(record)  # answered without waiting
                    return
                await yield_point()  # the window the event can fire in
                results.append(await feed.wait_terminal("t-watch", 30.0))

            async def completer():
                await yield_point()
                store.update_status("t-watch", "completed",
                                    TaskStatus.COMPLETED)

            def check():
                assert results and results[0] is not None, (
                    "watcher missed the terminal wakeup")
                assert results[0].canonical_status == "completed"

            return [watcher(), completer()], check

        return make

    def test_feed_attach_race_free(self):
        from ai4e_tpu.taskstore.feed import ShardChangeFeed
        report = explore_interleavings(self._scenario(ShardChangeFeed),
                                       schedules=SCHEDULES, seed=SEED)
        assert report.ok, report.describe()

    def test_replay_free_replica_caught(self):
        from ai4e_tpu.taskstore.feed import ShardChangeFeed

        class NoReplayFeed(ShardChangeFeed):
            """wait_terminal WITHOUT the replay-map consult — the naive
            register-then-wait a per-request listener would write."""

            async def wait_terminal(self, task_id, timeout):
                import asyncio as _asyncio
                loop = _asyncio.get_running_loop()
                fut = loop.create_future()
                entry = (loop, fut)
                with self._lock:  # registers, never checks _recent
                    self._waiters[task_id] = self._waiters.get(
                        task_id, frozenset()) | {entry}
                try:
                    return await _asyncio.wait_for(fut, timeout)
                except _asyncio.TimeoutError:
                    return None
                finally:
                    self._drop_waiter(task_id, entry)

        report = explore_interleavings(self._scenario(NoReplayFeed),
                                       schedules=SCHEDULES, seed=SEED)
        assert not report.ok, (
            "the attach-vs-event window was not reachable without the "
            "replay map — the scenario no longer models the race")


# -- PR 7: orchestration check-then-act surfaces (docs/orchestration.md) ------


class TestOrchestrationPlacementVsBreakerTrip:
    """The placement pipeline is estimator-read → decision → POST →
    outcome record: the decision's breaker evidence is one suspension
    stale by the time the outcome lands, and a concurrent delivery loop
    can trip (or recover) the same breaker mid-flight. The invariants a
    schedule must never break: a placement always lands inside the
    backend set, the half-open probe-slot accounting never leaks (the
    PR 3 leak class — a leaked slot ejects a backend forever), and the
    estimator's begin/end in-flight pairing survives every interleaving
    (the dispatcher releases in a finally)."""

    BACKENDS = [("http://tpu", 1.0), ("http://cpu", 1.0)]

    def _make(self):
        from ai4e_tpu.orchestration import Orchestrator, OrchestrationPolicy

        clock = [0.0]
        health = BackendHealth(
            ResiliencePolicy(failure_threshold=2, recovery_seconds=5.0),
            metrics=MetricsRegistry(), clock=lambda: clock[0],
            rng=random.Random(0))
        orch = Orchestrator(
            health,
            policy=OrchestrationPolicy(costs={"cpu": 1.0, "tpu": 3.0}),
            metrics=MetricsRegistry(), clock=lambda: clock[0])
        for _ in range(4):
            orch.observe("http://tpu", 0.01)
            orch.observe("http://cpu", 0.02)
        return clock, health, orch

    def test_placement_vs_trip_race_free(self):
        def make():
            clock, health, orch = self._make()
            placed = []

            async def placing_loop():
                # The dispatcher's attempt shape: place → (suspend: the
                # POST) → outcome, with the estimator's begin/end exactly
                # where _dispatch_one puts them (finally-released).
                for outcome_ok in (True, False):
                    base = orch.place(self.BACKENDS, deadline_at=0.0)
                    placed.append(base)
                    orch.begin(base)
                    try:
                        await yield_point()  # the POST round trip
                        if outcome_ok:
                            health.observe_status(base, 200)
                            orch.observe(base, 0.01)
                        else:
                            health.record_failure(base)
                    finally:
                        orch.end(base)

            async def tripping_loop():
                # A concurrent delivery loop melting the cheap tier: the
                # breaker trips while the placer is mid-POST.
                for _ in range(2):
                    await yield_point()
                    health.record_failure("http://cpu")
                clock[0] += 6.0  # cooldown elapses → half-open probes
                uri = orch.place(self.BACKENDS, deadline_at=0.0)
                await yield_point()
                health.observe_status(uri, 200)

            def check():
                for uri in ("http://tpu", "http://cpu"):
                    br = health.breaker_for(uri)
                    assert 0 <= br._probes_inflight <= br.half_open_probes
                    assert orch.estimator.inflight(uri) == 0, (
                        "estimator in-flight leaked")
                assert set(placed) <= {u for u, _ in self.BACKENDS}
                # However the trip interleaved, the set must stay
                # routable once the cooldown passes (no permanent
                # ejection — the PR 3 slot-leak symptom).
                clock[0] += 6.0
                assert any(health.breaker_for(u).available()
                           for u, _ in self.BACKENDS)

            return [placing_loop(), tripping_loop()], check

        report = explore_interleavings(make, schedules=SCHEDULES, seed=SEED)
        assert report.ok, report.describe()


class TestLadderHysteresisVsMetricsFlush:
    """Ladder step-up racing step-down racing a metrics flush: note()
    arrives from placement (event loop) and from the store-listener
    thread, while /metrics renders mid-transition. Per the
    docs/concurrency.md contract the transition critical section is a
    lock-protected sync block (no suspension points), so every explored
    schedule must observe: level within [0, 4], conservation (up steps −
    down steps == final level), and a flushed gauge that always equals a
    level the ladder actually held."""

    def test_step_up_vs_step_down_vs_flush(self):
        def make():
            from ai4e_tpu.orchestration import DegradationLadder

            clock = [0.0]
            reg = MetricsRegistry()
            ladder = DegradationLadder(up=0.5, down=0.1, hold_s=2.0,
                                       min_rate=0.01, tau_s=5.0,
                                       metrics=reg,
                                       clock=lambda: clock[0])
            seen_levels = []

            async def misser():
                for _ in range(8):
                    clock[0] += 1.0
                    ladder.note(miss=True)
                    seen_levels.append(ladder.level)
                    await yield_point()

            async def recoverer():
                for _ in range(20):
                    clock[0] += 0.5
                    ladder.note(miss=False)
                    seen_levels.append(ladder.level)
                    await yield_point()

            async def flusher():
                for _ in range(4):
                    await yield_point()
                    reg.render_prometheus()  # the metrics scrape
                    gauge = reg.gauge("ai4e_orchestration_ladder_level", "")
                    seen_levels.append(int(gauge.value()))

            def check():
                assert all(0 <= lvl <= 4 for lvl in seen_levels), seen_levels
                counter = reg.counter(
                    "ai4e_orchestration_ladder_transitions_total", "")
                ups = downs = 0
                for _, _, labels, v in counter.collect():
                    if labels.get("direction") == "up":
                        ups += v
                    else:
                        downs += v
                assert ups - downs == ladder.level, (
                    f"transition conservation broken: {ups} up, {downs} "
                    f"down, level {ladder.level}")
                gauge = reg.gauge("ai4e_orchestration_ladder_level", "")
                assert int(gauge.value()) == ladder.level

            return [misser(), recoverer(), flusher()], check

        report = explore_interleavings(make, schedules=SCHEDULES, seed=SEED)
        assert report.ok, report.describe()


class TestLadderSwapVsBatchCut:
    """The derived-ladder swap window (runtime/ladder.py, docs/
    device_path.md): a batch cut reads the servable's ladder tuple
    (``bucket_for``), suspends (the executor hop), and pads to the chosen
    bucket — while the deriver thread compiles a NEW ladder and swaps it
    in. The invariant: no request is ever padded to a bucket that has no
    compiled program. The fixed order — ``prepare_buckets`` warms every
    new bucket, THEN ``apply_ladder`` assigns the tuple (and refuses
    un-executed buckets), with the warm set append-only so old-ladder
    cuts stay compiled — is race-free over the schedule budget; the
    reverted order (assign first, compile after: the naive hot-swap)
    lets a cut pick a bucket whose first call would compile on the
    serving path, and is caught."""

    @staticmethod
    def _scenario(prepare_before_swap: bool):
        def make():
            # Warm set + serving ladder, mirroring ModelRuntime
            # (_executed_shapes is append-only; batch_buckets is swapped
            # in one assignment).
            state = {"ladder": (1, 8), "warm": {1, 8}}
            cold_pads: list[int] = []

            async def cutter():
                # Two cuts racing the swap: each reads the tuple, hops
                # to the executor, then pads — the exact _execute shape.
                for n in (3, 5):
                    ladder = state["ladder"]
                    await yield_point()  # run_in_executor hand-off
                    bucket = next((b for b in ladder if b >= n),
                                  ladder[-1])
                    if bucket not in state["warm"]:
                        cold_pads.append(bucket)
                    await yield_point()

            async def swapper():
                new = (4, 8)
                if prepare_before_swap:
                    for b in new:  # prepare_buckets: warm FIRST…
                        state["warm"].add(b)
                        await yield_point()  # compiles suspend freely
                    state["ladder"] = new  # …then the atomic assignment
                else:
                    state["ladder"] = new  # reverted: assign, then warm
                    await yield_point()
                    for b in new:
                        state["warm"].add(b)
                        await yield_point()

            def check():
                assert not cold_pads, (
                    f"batch padded to bucket(s) {cold_pads} with no "
                    "compiled program — a serving-path compile stall")

            return [cutter(), swapper()], check

        return make

    def test_prepare_then_swap_race_free(self):
        report = explore_interleavings(self._scenario(True),
                                       schedules=SCHEDULES, seed=SEED)
        assert report.ok, report.describe()

    def test_swap_before_prepare_caught(self):
        report = explore_interleavings(self._scenario(False),
                                       schedules=SCHEDULES, seed=SEED)
        assert not report.ok, (
            "the assign-before-compile window was not reachable — either "
            "the scenario no longer models the swap or the budget is "
            "too small")


# -- decode engine: KV-cache slot conservation (PR 14) ------------------------
#
# The continuous-batching engine (runtime/decode.py, docs/streaming.md)
# runs four verbs that all touch slot state: join-batch (admission
# prefill), decode-step, expiry-sweep, and hot-reload-invalidate
# (re-prefill). THE invariant: a slot is never double-assigned, never
# leaked, freed exactly once — SlotPool raises SlotError the moment any
# schedule violates it, and check_conservation() audits the end state.
# The engine imports neither JAX nor numpy, so this suite runs in the
# race-smoke job's toolchain-free environment against the REAL engine.

import time as _time

from ai4e_tpu.admission.deadline import DeadlineExceeded
from ai4e_tpu.runtime.decode import DecodeEngine


class _FakeDecodeBackend:
    """Async decode backend: every device call is a real suspension
    (yield_point), so the explorer owns every interleaving window the
    executor-thread hop opens in production."""

    def __init__(self, slots=2, max_len=64, eos_id=None):
        self.slots = slots
        self.max_len = max_len
        self.eos_id = eos_id
        self.name = "lm"
        self.params_version = 1
        self.resets = 0

    async def reset_cache(self):
        await yield_point()
        self.resets += 1

    async def prefill_into(self, slot, tokens):
        await yield_point()
        return int(tokens[-1]) + 1

    async def step(self, tokens, positions, active):
        await yield_point()
        return [int(t) + 1 for t in tokens]


class _SplitSweepEngine(DecodeEngine):
    """Verbatim pre-fix expiry sweep: the doomed set is selected, then
    each expiry suspends (publishing the expiry event) BEFORE releasing
    the slot — the guard and the release in different segments, the
    AIL007 shape. A cancel landing in the window retires the sequence
    first; the resumed sweep then releases a slot it no longer holds."""

    async def _tick(self):
        await self._check_reload()
        await self._sweep_split()
        await self._admit()
        await self._step()

    async def _sweep_split(self):
        now = _time.time()
        doomed = [(seq, seq.slot) for seq in self._active.values()
                  if not seq.done and seq.deadline_at
                  and seq.deadline_at <= now]
        for seq, slot in doomed:
            await yield_point()          # pre-fix: emitted the event first
            self._active.pop(slot, None)
            self.pool.release(slot)      # stale guard: freed exactly once?
            seq.slot = None
            seq.done = True
            if not seq.future.done():
                seq.future.set_exception(
                    DeadlineExceeded("decode", seq.deadline_at))


def _decode_drain(engine, results):
    """End-of-run drain: every leftover sequence is retired exactly once
    through the funnel, so an interrupted scenario still lets futures
    resolve and conservation be audited."""
    for seq in (list(engine._active.values()) + list(engine._queue)):
        engine._retire(seq, "cancelled", error=RuntimeError("drained"))
    results["drained"] = True


def _slot_conservation_scenario(engine_cls, ticks=120):
    """Join vs decode-step vs expiry-sweep vs cancel vs hot-reload:
    the full verb mix over a 2-slot pool."""

    def make():
        backend = _FakeDecodeBackend(slots=2, max_len=8)
        engine = engine_cls(backend, max_pending=8,
                            metrics=MetricsRegistry())
        results = {}

        async def driver():
            for _ in range(ticks):
                if results.get("stop"):
                    break
                # An idle tick has no suspension point — yield explicitly
                # so submitters are never starved past the tick budget
                # (the drain below would then resolve their futures with
                # the engine never having served them).
                await yield_point()
                await engine._tick()
            _decode_drain(engine, results)

        async def submit(tag, prompt, max_new, **kw):
            try:
                results[tag] = await engine.submit(prompt, max_new, **kw)
            except BaseException as exc:  # noqa: BLE001 — the outcome IS the result under exploration
                results[tag] = exc

        async def joiner():
            # Joins mid-decode of the first sequence under most
            # schedules — the continuous-batching admission window.
            await yield_point()
            await submit("b", [10], 2)

        async def expiring_then_cancel():
            # Arm a mid-decode expiry on the first active sequence, then
            # cancel it — the two release paths that must compose to
            # exactly one free.
            for _ in range(40):
                if engine._active:
                    break
                await yield_point()
            else:
                return
            seq = next(iter(engine._active.values()))
            seq.deadline_at = 1.0        # long past: next sweep dooms it
            await yield_point()
            engine.cancel(seq.future)

        async def reloader():
            await yield_point()
            backend.params_version += 1  # hot reload: cache invalidated

        async def finisher():
            # Let the driver stop once every waiter resolved.
            for _ in range(200):
                if "a" in results and "b" in results:
                    break
                await yield_point()
            results["stop"] = True

        coros = [driver(), submit("a", [1], 6), joiner(),
                 expiring_then_cancel(), reloader(), finisher()]

        def check():
            engine.pool.check_conservation()
            assert engine.pool.free_count == engine.pool.slots, (
                f"slot leak: {engine.pool.busy_count} busy after drain")
            assert not engine._active and not engine._queue
            assert "a" in results and "b" in results, results

        return coros, check

    return make


class TestDecodeSlotConservation:
    def test_fixed_engine_conserves_slots(self):
        report = explore_interleavings(
            _slot_conservation_scenario(DecodeEngine),
            schedules=SCHEDULES, seed=SEED)
        assert report.ok, report.describe()

    def test_split_sweep_revert_caught(self):
        report = explore_interleavings(
            _slot_conservation_scenario(_SplitSweepEngine),
            schedules=SCHEDULES, seed=SEED)
        assert not report.ok, (
            "the sweep-vs-cancel double-free window was not reachable — "
            "either the scenario no longer arms a mid-decode expiry or "
            "the budget is too small")
        assert any("Slot" in type(r.error).__name__
                   or "released" in str(r.error)
                   for r in report.failures), report.describe()


# ---------------------------------------------------------------------------
# PR 16: weighted-fair dequeue vs concurrent tenant weight update
# ---------------------------------------------------------------------------

class _SnapshotRebuildQueue(EndpointQueue):
    """The rejected reweight design, kept as the broken replica: apply a
    tenant weight change by snapshotting the per-tenant lanes, publishing
    the new policy (an await — the config push a multi-process deployment
    would make), then reinstalling rebuilt lanes. Any ``put`` that lands
    inside the publish window is clobbered by the stale snapshot: its seq
    stays in ``_ready_seqs`` but its message object is gone from every
    lane, so it is never delivered again — a silently lost task. The
    shipped design has no such window: ``TenantRegistry.set_weight`` is
    one dict write and ``_pop_fair`` reads the LIVE weight at every ring
    visit, so a reweight needs no queue surgery at all."""

    async def apply_weights(self, registry, tenant_id, weight) -> None:
        from collections import deque as _deque
        snapshot = {k: list(v) for k, v in self._lanes.items()}
        registry.set_weight(tenant_id, weight)
        await yield_point()  # the policy publish hop
        self._lanes = {k: _deque(v) for k, v in snapshot.items() if v}
        self._ring = _deque(self._lanes.keys())
        self._deficit = {}


class TestTenantFairDequeueVsWeightUpdate:
    """PR 16's DRR lanes under a concurrent operator reweight: producers
    for two tenants, a consumer draining by deficit round-robin, and an
    updater changing tenant ``a``'s weight mid-stream. The shipped
    live-read design delivers every message exactly once under every
    schedule and the deficit counters conserve (never negative, bounded
    by ``_DRR_COST`` + the largest quantum). The snapshot-rebuild replica
    loses concurrently-enqueued messages inside its publish window."""

    @staticmethod
    def _scenario(rebuild: bool):
        from ai4e_tpu.tenancy import Tenancy

        def make():
            tenancy = Tenancy.from_spec("a=ka:1,b=kb:1")
            cls = _SnapshotRebuildQueue if rebuild else EndpointQueue
            q = cls("/v1/q", fair=tenancy.lanes)
            seqs_a, seqs_b = (1, 2, 3), (10, 11)
            delivered: list[int] = []

            def _put(seq, tenant):
                q.put(Message(task_id=f"{tenant}{seq}", endpoint="/v1/q",
                              seq=seq, tenant=tenant))

            async def producer_a():
                for seq in seqs_a:
                    _put(seq, "a")
                    await yield_point()

            async def producer_b():
                for seq in seqs_b:
                    _put(seq, "b")
                    await yield_point()

            async def consumer():
                for _ in range(len(seqs_a) + len(seqs_b)):
                    msg = await q.receive(timeout=5.0)
                    assert msg is not None, (
                        "an enqueued message was never delivered — the "
                        "reweight lost it")
                    delivered.append(msg.seq)
                    q.complete(msg)

            async def updater():
                await yield_point()
                if rebuild:
                    await q.apply_weights(tenancy.registry, "a", 4.0)
                else:
                    # Shipped path: one synchronous dict write; the very
                    # next _pop_fair ring visit reads the new quantum.
                    tenancy.registry.set_weight("a", 4.0)

            def check():
                assert sorted(delivered) == sorted(seqs_a + seqs_b), (
                    f"exactly-once broken: delivered {sorted(delivered)}")
                for key, credit in q.deficits().items():
                    assert 0.0 <= credit < 1.0 + 4.0, (
                        f"deficit for lane {key!r} escaped its bound: "
                        f"{credit}")
                assert q.lane_depths() == {}

            return ([producer_a(), producer_b(), consumer(), updater()],
                    check)

        return make

    def test_live_weight_read_race_free(self):
        report = explore_interleavings(self._scenario(rebuild=False),
                                       schedules=SCHEDULES, seed=SEED)
        assert report.ok, report.describe()

    def test_snapshot_rebuild_replica_caught(self):
        report = explore_interleavings(self._scenario(rebuild=True),
                                       schedules=SCHEDULES, seed=SEED)
        assert not report.ok, (
            "the snapshot-rebuild lost-put window was not reachable — "
            "either the replica stopped rebuilding across an await or "
            "the schedule budget is too small")


# -- PR 17: mesh poisoned-row redelivery vs duplicate completion --------------


async def _reverted_whole_batch_fail(tm, batch):
    """Verbatim pre-mesh batch failure path: any bad row fails EVERY task
    in the batch, unconditionally — no per-row attribution and no
    terminal re-check before the write (the behavior
    ``runtime/mesh/redelivery.py`` replaced). A duplicate delivery that
    completed one of those tasks concurrently gets its COMPLETED
    clobbered to FAILED — a client-visible double outcome."""
    for tid in batch:
        await yield_point()  # the per-task store hop
        await tm.update_task_status(tid, "failed: mesh host degraded",
                                    TaskStatus.FAILED)


class TestMeshPoisonedRowRedelivery:
    """PR 17's degraded-batch contract (``docs/mesh_serving.md``): a
    poisoned row redelivers exactly its own task; the other rows
    complete; a concurrently-finishing duplicate delivery is suppressed
    against the terminal record — never a duplicate client-visible
    completion, never a whole-batch fail. Three racers: the worker's
    poison handling (REAL ``redeliver_poisoned``), a duplicate delivery
    completing the poisoned task on another replica, and the mesh
    coordinator flipping endpoint health over the same degrade."""

    @staticmethod
    def _scenario(fixed: bool):
        from ai4e_tpu.runtime.mesh import (EndpointHealth, MeshCoordinator,
                                           MeshLayout, RowPoisoned,
                                           redeliver_poisoned)

        def make():
            store = InMemoryTaskStore()
            tm = TracedTaskManager(LocalTaskManager(store))
            _seeded_task(store, None, task_id="t1")  # the poisoned row
            _seeded_task(store, None, task_id="t2")  # a clean row, same batch
            invariant = TerminalInvariant(store)
            health = EndpointHealth()
            coordinator = MeshCoordinator(MeshLayout(dp=2), health=health,
                                          process_count=2, unhealthy_after=2)
            completions = {"t1": 0, "t2": 0}

            async def _complete_if_fresh(tid):
                # Every completer is a redelivery consumer: conditional
                # transition, duplicate-suppressed against a record a
                # concurrent path may already have finished.
                res = await tm.update_task_status_if(
                    tid, TaskStatus.CREATED, "completed",
                    TaskStatus.COMPLETED)
                if res is not None:
                    completions[tid] += 1

            async def mesh_batch():
                # The worker's async path over a degraded batch: t1's
                # future failed with RowPoisoned, t2's row is clean.
                poison = RowPoisoned()
                assert "invalidated" in str(poison)
                if not fixed:
                    await _reverted_whole_batch_fail(tm, ("t1", "t2"))
                    return
                await _complete_if_fresh("t2")
                republished = await redeliver_poisoned(tm, "t1", "/v1/q/op")
                if republished:
                    # The broker redelivers; the consumer's completion is
                    # conditional like any redelivery consumer's.
                    await yield_point()
                    await _complete_if_fresh("t1")

            async def duplicate_completer():
                # A duplicate delivery of t1 finishing on another replica,
                # concurrent with the poison handling — its response hop
                # is the one suspension before the completion.
                await yield_point()
                await _complete_if_fresh("t1")

            async def health_flip():
                # The coordinator's view of the same degrade: two
                # consecutive poisoned gathers flip the endpoint
                # unhealthy (admission starts answering 500 so breakers
                # eject it); one clean gather heals it.
                for flags in ([0, 1], [0, 1], [0, 0]):
                    await yield_point()
                    coordinator.observe_poison(flags)

            def check():
                invariant.check()
                assert health.healthy, (
                    f"clean gather did not heal the endpoint: "
                    f"{health.reason}")
                if fixed:
                    assert completions == {"t1": 1, "t2": 1}, (
                        f"client-visible completions drifted (want exactly "
                        f"one per task): {completions}")

            return ([mesh_batch(), duplicate_completer(), health_flip()],
                    check)

        return make

    def test_fixed_poisoned_row_race_free(self):
        report = explore_interleavings(self._scenario(fixed=True),
                                       schedules=SCHEDULES, seed=SEED)
        assert report.ok, report.describe()

    def test_reverted_whole_batch_fail_caught(self):
        report = explore_interleavings(self._scenario(fixed=False),
                                       schedules=SCHEDULES, seed=SEED)
        assert not report.ok
        assert "clobbered" in str(report.failures[0].error)


# -- rollout drain: the two flip windows (PR 18) ------------------------------
#
# The drain state machine (rollout/drain.py, docs/deployment.md#drain)
# keeps two suspension-point-atomicity contracts, both stdlib-only so
# this job explores them against the REAL code: (1) the drain flip and
# the pending sweep are one synchronous step with the take-and-clear,
# so a concurrently scheduled batch cut can never deliver a device
# result into a future the sweep already failed; (2) the reload
# admission check and the in-flight registration are one synchronous
# step, so a weight swap can never complete on a worker that already
# reported itself drained.

from ai4e_tpu.rollout.drain import (ACTIVE, DRAINED, DrainingError,
                                    DrainState, drain_worker, retire_pending)


class _PendingEntry:
    __slots__ = ("task_id", "future")

    def __init__(self, task_id, future):
        self.task_id = task_id
        self.future = future


async def _reverted_retire_pending(pending_by_model):
    """The pre-fix sweep, verbatim: snapshot the queue, flush the pending
    gauge (an await), then clear and fail — the take-and-clear straddles
    a suspension point (AIL007's shape), so a batch cut landing inside
    the window owns futures this sweep is about to fail."""
    retired = 0
    for entries in list(pending_by_model.values()):
        taken = list(entries)
        await yield_point()  # the pending-gauge flush hop
        entries[:] = []
        for entry in taken:
            fut = getattr(entry, "future", entry)
            fut.set_exception(DrainingError())
            retired += 1
    return retired


class TestDrainFlipVsBatchCut:
    """Drain-flip vs in-flight batch completion: the flusher cuts a
    batch (synchronous take-and-clear, then the device hop, then results
    land in the taken futures) while the drain verb sweeps the same
    pending queues. Fixed (``retire_pending``: synchronous take-and-
    clear, ``done()``-guarded fail): every task gets exactly one client
    outcome — completed on this worker, redelivered to a peer, or
    refused at admission — and a redelivered task was never ALSO
    executed here. Reverted (await between snapshot and clear): a cut
    inside the window either double-resolves a future the sweep failed
    (InvalidStateError mid-drain) or executes a batch whose tasks the
    broker is simultaneously redelivering — a duplicate delivery."""

    @staticmethod
    def _scenario(fixed: bool):
        def make():
            pending = {"echo": []}
            state = DrainState(clock=lambda: 0.0)
            outcomes = {"t1": [], "t2": []}
            executed = []

            async def submitter():
                # Two submits through the batcher's admission gate: a
                # draining worker refuses (503 + X-Draining -> the
                # caller retries a peer), an active one queues.
                futs = {}
                for task_id in ("t1", "t2"):
                    if state.is_draining:
                        outcomes[task_id].append("refused")
                    else:
                        fut = asyncio.get_running_loop().create_future()
                        pending["echo"].append(_PendingEntry(task_id, fut))
                        futs[task_id] = fut
                    if task_id == "t1":
                        await yield_point()
                for task_id, fut in futs.items():
                    try:
                        await fut
                        outcomes[task_id].append("completed")
                    except DrainingError:
                        outcomes[task_id].append("redelivered")

            async def flusher():
                # One batch cut racing the drain: the take-and-clear is
                # one synchronous step (the real flusher's shape), the
                # device hop suspends, then results deliver.
                while True:
                    if pending["echo"]:
                        taken, pending["echo"][:] = (
                            list(pending["echo"]), [])
                        await yield_point()  # the device execute hop
                        for entry in taken:
                            executed.append(entry.task_id)
                            if not entry.future.done():
                                entry.future.set_result("ok")
                        return
                    if state.is_draining:
                        return
                    await yield_point()

            async def drainer():
                await yield_point()  # the drain verb arrives mid-traffic
                state.begin()
                if fixed:
                    retire_pending(pending)
                else:
                    await _reverted_retire_pending(pending)
                state.mark_drained()

            def check():
                for task_id, outs in outcomes.items():
                    assert len(outs) == 1, (
                        f"client outcome for {task_id} clobbered: {outs}")
                    if outs == ["redelivered"]:
                        assert task_id not in executed, (
                            f"{task_id} redelivered AND executed on the "
                            "draining worker — a duplicate delivery")

            return [submitter(), flusher(), drainer()], check

        return make

    def test_fixed_sweep_race_free(self):
        report = explore_interleavings(self._scenario(fixed=True),
                                       schedules=SCHEDULES, seed=SEED)
        assert report.ok, report.describe()

    def test_reverted_sweep_caught(self):
        report = explore_interleavings(self._scenario(fixed=False),
                                       schedules=SCHEDULES, seed=SEED)
        assert not report.ok, (
            "the snapshot-await-clear window was not reachable — either "
            "the scenario no longer models the sweep or the budget is "
            "too small")


async def _reverted_try_begin_reload(state):
    """The pre-fix reload admission, verbatim: the drain check and the
    in-flight registration straddled the reload-lock acquisition — one
    suspension between guard and guarded write (AIL007's shape). A drain
    that lands inside the window reads ``reloads_in_flight == 0``,
    reports the worker drained, and the swap then completes on a worker
    the rollout controller already moved past."""
    if state.is_draining:
        return False
    await yield_point()  # acquiring the reload serial lock
    state._reloads += 1
    return True


class TestDrainFlipVsReload:
    """Drain-flip vs concurrent hot reload: the reload verb races the
    drain verb on one worker. Fixed (``try_begin_reload``: check +
    register in one synchronous step): the reload either registers fully
    before the drain — which then waits for it — or is refused with 409
    while draining; ``drain_worker`` never reports a worker drained with
    a swap still in flight. Reverted (await between check and register):
    the drain completes inside the window and the swap lands on a worker
    that already reported itself drained."""

    @staticmethod
    def _scenario(fixed: bool):
        def make():
            state = DrainState(clock=lambda: 0.0)
            events = []

            async def reloader():
                await yield_point()  # the reload POST arrives
                if fixed:
                    admitted = state.try_begin_reload()
                else:
                    admitted = await _reverted_try_begin_reload(state)
                if not admitted:
                    events.append(("refused", state.state))  # the 409
                    return
                await yield_point()  # the weight swap itself
                events.append(("swapped", state.state))
                state.end_reload()

            async def drainer():
                res = await drain_worker(state, timeout_s=30.0,
                                         poll_s=0.01, clock=lambda: 0.0)
                events.append(("drained", res["clean"]))

            def check():
                assert ("drained", True) in events, (
                    f"drain never completed clean: {events}")
                for kind, detail in events:
                    if kind == "swapped":
                        assert detail != DRAINED, (
                            "weight swap completed on a worker that "
                            "already reported itself drained")
                    if kind == "refused":
                        assert detail != ACTIVE, (
                            "reload 409'd on an active worker")

            return [reloader(), drainer()], check

        return make

    def test_fixed_interlock_race_free(self):
        report = explore_interleavings(self._scenario(fixed=True),
                                       schedules=SCHEDULES, seed=SEED)
        assert report.ok, report.describe()

    def test_reverted_interlock_caught(self):
        report = explore_interleavings(self._scenario(fixed=False),
                                       schedules=SCHEDULES, seed=SEED)
        assert not report.ok, (
            "the check-await-register window was not reachable — either "
            "the scenario no longer models the admission or the budget "
            "is too small")
        assert "drained" in str(report.failures[0].error)


# -- PR 20: the drain-handler flush (AIL020 ledger-buffer-flush) --------------


class TestReplayDrainFlushLoss:
    """The PR 8/PR 18 composite AIL020 now pins statically: the worker's
    DrainingError handler stamps RETRY into the request's buffered
    hop-ledger and must flush it before redelivering. The reverted
    replica (stamp, redeliver, no flush) loses the draining timeline of
    exactly the retried task — the flight recorder's 100%% guarantee is
    about failed-and-retried requests above all. AIL020 catches the
    deletion syntactically (tests/test_analysis.py
    TestVerbatimRevertCaught); this replay shows the lost-timeline
    behavior it encodes."""

    def _scenario(self, flush_before_redeliver: bool):
        from ai4e_tpu.observability.ledger import RETRY, HopLedger

        def make():
            store = InMemoryTaskStore()
            tm = LocalTaskManager(store)
            task = store.upsert(APITask(endpoint="/v1/x", body=b"{}"))
            draining = {"on": False}
            redelivered: list[str] = []

            async def handler():
                buf = HopLedger()
                await yield_point()       # submit races the drain flip
                if draining["on"]:
                    buf.stamp(RETRY, "worker", reason="draining")
                    if flush_before_redeliver:
                        events = buf.drain()
                        if events:
                            await tm.append_ledger(task.task_id, events)
                    redelivered.append(task.task_id)
                    return

            async def drain_flip():
                await yield_point()
                draining["on"] = True

            def check():
                if not redelivered:
                    return  # this interleaving never saw the drain
                events = store.get_ledger(task.task_id)
                assert any(ev.get("e") == RETRY
                           and ev.get("r") == "draining"
                           for ev in events), (
                    "draining timeline lost: the task was redelivered "
                    "but its RETRY stamp never reached the store")

            return [handler(), drain_flip()], check

        return make

    def test_fixed_handler_keeps_the_timeline(self):
        report = explore_interleavings(self._scenario(True),
                                       schedules=SCHEDULES, seed=SEED)
        assert report.ok, report.describe()

    def test_reverted_flush_deletion_caught(self):
        report = explore_interleavings(self._scenario(False),
                                       schedules=SCHEDULES, seed=SEED)
        assert not report.ok, (
            "the drain flip never interleaved before the handler's "
            "check — scenario no longer models the race")
        assert "timeline lost" in str(report.failures[0].error)
