"""Multi-tenancy tests (``ai4e_tpu/tenancy/``, docs/tenancy.md): the
registry's key→tenant resolution and FROZEN bounded-cardinality label;
per-tenant token-bucket quotas with the rate-limiter's burst/retry
arithmetic; the broker's deficit-round-robin lanes (ratio fairness,
flood isolation, no banking, live reweights); per-tenant accounting off
the store change feed; the gateway's tenant-quota 429 path; and
``tenancy=False`` leaving every pre-tenancy behavior untouched —
assembly attributes, route table, and ``/metrics`` exposition."""

import asyncio
import time

import pytest
from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.broker.queue import EndpointQueue, InMemoryBroker, Message
from ai4e_tpu.metrics import MetricsRegistry
from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
from ai4e_tpu.taskstore import APITask, InMemoryTaskStore, TaskStatus
from ai4e_tpu.tenancy import (DEFAULT_TENANT, OTHER_LABEL, Tenancy, Tenant,
                              TenantLanes, TenantQuota, TenantRegistry,
                              parse_tenants)


def run(coro):
    return asyncio.run(coro)


async def serve(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


def _msg(seq, tenant="", task_id=None):
    return Message(task_id=task_id or f"t{seq}", endpoint="/v1/q",
                   seq=seq, tenant=tenant)


# ---------------------------------------------------------------------------
# Registry: spec parsing, resolution, frozen bounded label
# ---------------------------------------------------------------------------

class TestRegistry:
    def test_parse_spec_full_and_defaulted_fields(self):
        tenants = parse_tenants("alpha=key-a1|key-a2:4:50:100,beta=key-b:2",
                                default_rps=7.0)
        a, b = tenants
        assert a.tenant_id == "alpha" and a.keys == ("key-a1", "key-a2")
        assert (a.weight, a.rps, a.burst) == (4.0, 50.0, 100.0)
        assert (b.weight, b.rps, b.burst) == (2.0, 7.0, 0.0)

    def test_parse_spec_malformed_fails_loudly(self):
        with pytest.raises(ValueError, match="expected name="):
            parse_tenants("justaname")
        with pytest.raises(ValueError, match="no subscription keys"):
            parse_tenants("a=")
        with pytest.raises(ValueError, match="declared twice"):
            parse_tenants("a=k1,a=k2")
        with pytest.raises(ValueError, match="two tenants"):
            parse_tenants("a=k,b=k")
        with pytest.raises(ValueError, match="not a number"):
            parse_tenants("a=k:heavy")
        with pytest.raises(ValueError, match="weight must be"):
            parse_tenants("a=k:0")

    def test_resolution_known_unknown_none(self):
        reg = TenantRegistry(parse_tenants("a=ka:3,b=kb"))
        assert reg.resolve("ka").tenant_id == "a"
        assert reg.resolve("nope").tenant_id == DEFAULT_TENANT
        assert reg.resolve(None).tenant_id == DEFAULT_TENANT

    def test_default_tenant_carries_configured_policy(self):
        reg = TenantRegistry([], default_weight=2.0, default_rps=5.0)
        t = reg.resolve(None)
        assert t.weight == 2.0 and t.rps == 5.0
        assert reg.weight("") == 2.0  # the shared default lane's weight

    def test_bucket_capacity_burst_rule_matches_rate_limiter(self):
        # burst 0 → max(2*rps, 1), same convention as gateway/ratelimit.py.
        assert Tenant("t", rps=10.0).bucket_capacity() == 20.0
        assert Tenant("t", rps=0.2).bucket_capacity() == 1.0
        assert Tenant("t", rps=10.0, burst=5.0).bucket_capacity() == 5.0

    def test_label_frozen_top_n_plus_other(self):
        reg = TenantRegistry(parse_tenants("a=ka,b=kb,c=kc"), label_top_n=2)
        assert reg.tenant_label("a") == "a"
        assert reg.tenant_label("b") == "b"
        assert reg.tenant_label("c") == OTHER_LABEL
        assert reg.tenant_label("never-seen") == OTHER_LABEL
        assert reg.tenant_label(DEFAULT_TENANT) == OTHER_LABEL

    def test_label_set_does_not_grow_with_live_updates(self):
        # FROZEN at construction: a tenant registered later never steals a
        # label slot — a scrape series must not flip identity mid-run.
        reg = TenantRegistry(parse_tenants("a=ka"), label_top_n=8)
        reg.update(Tenant("late", keys=("kl",)))
        assert reg.resolve("kl").tenant_id == "late"
        assert reg.tenant_label("late") == OTHER_LABEL

    def test_update_replaces_row_and_set_weight_lives(self):
        reg = TenantRegistry(parse_tenants("a=ka:1:10"))
        reg.set_weight("a", 9.0)
        assert reg.weight("a") == 9.0
        assert reg.resolve("ka").rps == 10.0  # other fields kept

    def test_update_refuses_key_theft(self):
        reg = TenantRegistry(parse_tenants("a=ka,b=kb"))
        with pytest.raises(ValueError, match="already belongs"):
            reg.update(Tenant("b", keys=("ka",)))


# ---------------------------------------------------------------------------
# Quota: token buckets with live policy reads
# ---------------------------------------------------------------------------

class TestQuota:
    def _clock(self):
        state = {"t": 100.0}
        return state, (lambda: state["t"])

    def test_burst_then_refusal_then_refill(self):
        reg = TenantRegistry(parse_tenants("a=ka:1:2:3"))  # 2 rps, burst 3
        state, now = self._clock()
        q = TenantQuota(reg, now=now)
        assert [q.admit("a")[0] for _ in range(3)] == [True] * 3
        allowed, retry = q.admit("a")
        assert not allowed
        assert retry == pytest.approx(0.5)  # 1 token / 2 rps
        state["t"] += 0.6
        assert q.admit("a")[0]

    def test_zero_rps_is_unlimited(self):
        reg = TenantRegistry(parse_tenants("a=ka"))
        q = TenantQuota(reg)
        assert all(q.admit("a") == (True, 0.0) for _ in range(100))
        assert q.admit(DEFAULT_TENANT) == (True, 0.0)

    def test_policy_update_takes_effect_without_rebuild(self):
        reg = TenantRegistry(parse_tenants("a=ka:1:1:1"))
        state, now = self._clock()
        q = TenantQuota(reg, now=now)
        assert q.admit("a")[0]
        assert not q.admit("a")[0]
        # Operator raises the contract live; the very next refill obeys it.
        reg.update(Tenant("a", rps=100.0, burst=100.0, keys=("ka",)))
        state["t"] += 1.0
        assert [q.admit("a")[0] for _ in range(50)] == [True] * 50

    def test_idle_buckets_pruned(self):
        reg = TenantRegistry(parse_tenants("a=ka:1:5"))
        state, now = self._clock()
        q = TenantQuota(reg, now=now)
        q.admit("a")
        state["t"] += 120.0
        q.admit("a")  # triggers the prune pass (interval elapsed, full again)
        assert len(q._buckets) <= 1


# ---------------------------------------------------------------------------
# DRR lanes: ratio fairness, isolation, no banking, live reweights
# ---------------------------------------------------------------------------

class TestFairDequeue:
    def _fair(self, spec, **kw):
        return Tenancy.from_spec(spec, **kw).lanes

    def _drain(self, q, n):
        async def main():
            out = []
            for _ in range(n):
                m = await q.receive(timeout=0.2)
                assert m is not None
                out.append(m)
                q.complete(m)
            return out
        return run(main())

    def test_service_ratio_follows_weights(self):
        q = EndpointQueue("/q", fair=self._fair("a=ka:3,b=kb:1"))
        seq = 0
        for tenant in ("a",) * 40 + ("b",) * 40:
            seq += 1
            q.put(_msg(seq, tenant))
        got = self._drain(q, 40)
        counts = {"a": 0, "b": 0}
        for m in got:
            counts[m.tenant] += 1
        assert counts == {"a": 30, "b": 10}  # exactly weight/Σweights

    def test_flooded_lane_cannot_starve_another(self):
        # The noisy-neighbor kernel: 500 queued for the flood tenant, 1
        # for the victim — the victim's message is served within one DRR
        # round, not after the backlog.
        q = EndpointQueue("/q", fair=self._fair("noisy=kn:1,victim=kv:1"))
        for seq in range(1, 501):
            q.put(_msg(seq, "noisy"))
        q.put(_msg(999, "victim"))
        got = self._drain(q, 4)
        assert "victim" in [m.tenant for m in got[:2]]

    def test_fifo_order_within_a_lane(self):
        q = EndpointQueue("/q", fair=self._fair("a=ka"))
        for seq in (1, 2, 3):
            q.put(_msg(seq, "a"))
        assert [m.seq for m in self._drain(q, 3)] == [1, 2, 3]

    def test_deficit_reset_on_empty_no_banking(self):
        # An idle tenant must not bank scheduling credit: drain its lane,
        # and its deficit entry is gone.
        q = EndpointQueue("/q", fair=self._fair("a=ka:5,b=kb:1"))
        q.put(_msg(1, "a"))
        q.put(_msg(2, "b"))
        self._drain(q, 2)
        assert q.lane_depths() == {}
        # Emptied lanes keep no spendable credit (cleanup is lazy, so a
        # just-served lane may linger at < one service cost until the
        # next visit drops it — but never a full serve's worth).
        assert all(credit < 1.0 for credit in q.deficits().values())
        # And once the lane is revisited empty, its state is forgotten:
        q.put(_msg(3, "a"))
        self._drain(q, 1)
        assert "b" not in q.deficits()

    def test_deficits_bounded_and_nonnegative(self):
        lanes = self._fair("a=ka:4,b=kb:1")
        q = EndpointQueue("/q", fair=lanes)
        for seq in range(1, 61):
            q.put(_msg(seq, "a" if seq % 3 else "b"))
        self._drain(q, 30)
        for credit in q.deficits().values():
            assert 0.0 <= credit < 1.0 + 4.0  # cost + max quantum

    def test_live_reweight_shifts_the_ratio(self):
        t = Tenancy.from_spec("a=ka:1,b=kb:1")
        q = EndpointQueue("/q", fair=t.lanes)
        seq = 0
        for tenant in ("a",) * 60 + ("b",) * 60:
            seq += 1
            q.put(_msg(seq, tenant))
        first = self._drain(q, 20)
        assert sum(1 for m in first if m.tenant == "a") == 10  # 1:1
        t.registry.set_weight("a", 3.0)  # live — no queue rebuild
        second = self._drain(q, 20)
        assert sum(1 for m in second if m.tenant == "a") == 15  # 3:1

    def test_tenantless_messages_share_the_default_lane(self):
        q = EndpointQueue("/q", fair=self._fair("a=ka:1"))
        q.put(_msg(1, ""))
        q.put(_msg(2, "a"))
        got = self._drain(q, 2)
        assert {m.seq for m in got} == {1, 2}
        assert q.lane_depths() == {}

    def test_retracted_seq_skipped_inside_lane(self):
        # complete() after lease expiry retracts a seq; the lane's lazy
        # skip must drop it exactly like the FIFO path does.
        q = EndpointQueue("/q", lease_seconds=0.01,
                          fair=self._fair("a=ka"))

        async def main():
            q.put(_msg(1, "a"))
            m1 = await q.receive(timeout=0.2)
            await asyncio.sleep(0.05)       # lease expires
            q._reap_expired_leases()        # reaper requeues seq 1
            q.complete(m1)                  # late complete → retraction
            q.put(_msg(2, "a"))
            m = await q.receive(timeout=0.2)
            assert m.seq == 2               # seq 1 never redelivered
            assert await q.receive(timeout=0.05) is None
        run(main())

    def test_lease_expiry_redelivers_into_the_lane(self):
        q = EndpointQueue("/q", lease_seconds=0.01,
                          fair=self._fair("a=ka"))

        async def main():
            q.put(_msg(1, "a"))
            m1 = await q.receive(timeout=0.2)
            assert m1.delivery_count == 1
            await asyncio.sleep(0.05)
            m2 = await q.receive(timeout=0.5)
            assert m2.seq == 1 and m2.delivery_count == 2
        run(main())

    def test_broker_publish_stamps_tenant_and_lanes_per_queue(self):
        t = Tenancy.from_spec("a=ka:2,b=kb:1")
        broker = InMemoryBroker(metrics=MetricsRegistry(), fair=t.lanes)
        broker.register_queue("/v1/q")
        broker.publish(APITask(task_id="x", endpoint="/v1/q", tenant="a"))
        q = broker.queue("/v1/q")
        assert q.fair is t.lanes
        assert q.lane_depths() == {"a": 1}

        async def main():
            m = await broker.receive("/v1/q", timeout=0.2)
            assert m.tenant == "a"
        run(main())


# ---------------------------------------------------------------------------
# Accounting: outcome feed, burn windows, bounded series
# ---------------------------------------------------------------------------

class TestAccounting:
    def _tenancy(self, spec="a=ka,b=kb", **kw):
        reg = MetricsRegistry()
        return Tenancy.from_spec(spec, metrics=reg, **kw), reg

    def _outcome(self, reg, **labels):
        return reg.counter("ai4e_tenant_outcomes_total").value(**labels)

    def test_store_feed_labels_outcomes_per_tenant(self):
        t, reg = self._tenancy()
        store = InMemoryTaskStore()
        t.attach_store(store)
        ok = store.upsert(APITask(endpoint="/v1/q", tenant="a"))
        store.update_status(ok.task_id, TaskStatus.COMPLETED)
        bad = store.upsert(APITask(endpoint="/v1/q", tenant="b"))
        store.update_status(bad.task_id, TaskStatus.FAILED)
        assert self._outcome(reg, tenant="a", outcome="ok") == 1
        assert self._outcome(reg, tenant="b", outcome="failed") == 1

    def test_late_completion_counts_late_not_ok(self):
        t, reg = self._tenancy()
        store = InMemoryTaskStore()
        t.attach_store(store)
        task = store.upsert(APITask(endpoint="/v1/q", tenant="a",
                                    deadline_at=time.time() - 1.0))
        store.update_status(task.task_id, TaskStatus.COMPLETED)
        assert self._outcome(reg, tenant="a", outcome="late") == 1

    def test_labels_are_bounded_never_raw_ids(self):
        t, reg = self._tenancy("a=ka,b=kb", label_top_n=1)
        store = InMemoryTaskStore()
        t.attach_store(store)
        for tenant in ("a", "b", "who-is-this"):
            task = store.upsert(APITask(endpoint="/v1/q", tenant=tenant))
            store.update_status(task.task_id, TaskStatus.COMPLETED)
        text = reg.render_prometheus()
        assert 'tenant="a"' in text
        assert 'tenant="b"' not in text           # outside frozen top-1
        assert "who-is-this" not in text          # unknown id never a label
        assert self._outcome(reg, tenant=OTHER_LABEL, outcome="ok") == 2

    def test_quota_shed_burns_only_the_shedding_tenant(self):
        t, _reg = self._tenancy(goodput_target=0.9)
        for _ in range(5):
            t.note_quota_shed("a")
        assert t.accounting.burn_rate("a") > 1.0   # all-bad window
        assert t.accounting.burn_rate("b") == 0.0  # victims untouched

    def test_cost_charge_accumulates_per_tenant(self):
        t, reg = self._tenancy()
        t.charge("a", 2.5)
        t.charge("a", 1.5)
        t.charge("b", 0.0)  # zero-cost backends charge nothing
        cost = reg.counter("ai4e_tenant_cost_total")
        assert cost.value(tenant="a") == 4.0
        assert cost.value(tenant="b") == 0.0


# ---------------------------------------------------------------------------
# Gateway edge: tenant resolution + quota 429 path
# ---------------------------------------------------------------------------

class TestGatewayEdge:
    def _platform(self, **cfg):
        defaults = dict(tenancy=True,
                        tenancy_tenants="paid=key-paid:4:100,"
                                        "trial=key-trial:1:2:2")
        defaults.update(cfg)
        return LocalPlatform(PlatformConfig(**defaults),
                             metrics=MetricsRegistry())

    def test_resolved_tenant_rides_the_task_record(self):
        async def main():
            platform = self._platform()
            platform.gateway.set_api_keys({"key-paid", "key-trial"})
            platform.publish_async_api("/v1/api/run",
                                       backend_uri="http://127.0.0.1:9/v1/b")
            client = await serve(platform.gateway.app)
            try:
                resp = await client.post(
                    "/v1/api/run", data=b"{}",
                    headers={"Ocp-Apim-Subscription-Key": "key-paid"})
                assert resp.status == 200
                tid = (await resp.json())["TaskId"]
                assert platform.store.get(tid).tenant == "paid"
            finally:
                await client.close()
        run(main())

    def test_over_quota_tenant_sheds_with_retry_after_and_reason(self):
        async def main():
            platform = self._platform()
            platform.gateway.set_api_keys({"key-paid", "key-trial"})
            platform.publish_async_api("/v1/api/run",
                                       backend_uri="http://127.0.0.1:9/v1/b")
            client = await serve(platform.gateway.app)
            try:
                statuses = []
                for _ in range(6):  # trial: 2 rps, burst 2
                    resp = await client.post(
                        "/v1/api/run", data=b"{}",
                        headers={"Ocp-Apim-Subscription-Key": "key-trial"})
                    statuses.append(resp.status)
                    if resp.status == 429:
                        assert int(resp.headers["Retry-After"]) >= 1
                        assert "tenant-quota" in resp.headers["X-Shed-Reason"]
                        assert "tenant quota" in (await resp.json())["error"]
                assert statuses.count(429) == 4
                # The flooded tenant's shed never touches the other lane:
                resp = await client.post(
                    "/v1/api/run", data=b"{}",
                    headers={"Ocp-Apim-Subscription-Key": "key-paid"})
                assert resp.status == 200
            finally:
                await client.close()
        run(main())

    def test_status_polls_are_not_metered(self):
        async def main():
            platform = self._platform()
            platform.gateway.set_api_keys({"key-paid", "key-trial"})
            platform.publish_async_api("/v1/api/run",
                                       backend_uri="http://127.0.0.1:9/v1/b")
            client = await serve(platform.gateway.app)
            try:
                resp = await client.post(
                    "/v1/api/run", data=b"{}",
                    headers={"Ocp-Apim-Subscription-Key": "key-trial"})
                assert resp.status == 200
                tid = (await resp.json())["TaskId"]
                # Polling costs no quota: far more polls than the bucket
                # holds, all 200.
                for _ in range(10):
                    resp = await client.get(
                        f"/v1/taskmanagement/task/{tid}",
                        headers={"Ocp-Apim-Subscription-Key": "key-trial"})
                    assert resp.status == 200
            finally:
                await client.close()
        run(main())

    def test_auth_off_resolves_the_default_tenant(self):
        async def main():
            platform = self._platform(
                tenancy_tenants=None, tenancy_default_rps=1.0,
                tenancy_default_burst=1.0)
            platform.publish_async_api("/v1/api/run",
                                       backend_uri="http://127.0.0.1:9/v1/b")
            client = await serve(platform.gateway.app)
            try:
                first = await client.post("/v1/api/run", data=b"{}")
                assert first.status == 200
                tid = (await first.json())["TaskId"]
                assert platform.store.get(tid).tenant == DEFAULT_TENANT
                second = await client.post("/v1/api/run", data=b"{}")
                assert second.status == 429  # shared default bucket drained
            finally:
                await client.close()
        run(main())


# ---------------------------------------------------------------------------
# Assembly wiring: off byte-identical, on fully threaded, refusals
# ---------------------------------------------------------------------------

class TestAssemblyWiring:
    def test_off_by_default_byte_identical(self):
        platform = LocalPlatform(PlatformConfig(),
                                 metrics=MetricsRegistry())
        assert platform.tenancy is None
        assert platform.gateway._tenancy is None
        assert platform.dispatchers.tenancy is None
        assert platform.broker._fair is None
        platform.broker.register_queue("/v1/q")
        q = platform.broker.queue("/v1/q")
        assert q.fair is None and q._lanes == {} and q._ring == q._ring.__class__()
        # No tenant series exists with the layer off — the /metrics
        # exposition is unchanged (same discipline as every opt-in layer).
        assert "ai4e_tenant_" not in platform.metrics.render_prometheus()
        # The task wire shape is unchanged too.
        assert "Tenant" not in APITask(endpoint="/v1/q").to_dict()

    def test_on_threads_every_layer(self):
        platform = LocalPlatform(
            PlatformConfig(tenancy=True, tenancy_tenants="a=ka:2:10"),
            metrics=MetricsRegistry())
        assert platform.tenancy is not None
        assert platform.gateway._tenancy is platform.tenancy
        assert platform.dispatchers.tenancy is platform.tenancy
        assert platform.broker._fair is platform.tenancy.lanes
        d = platform.dispatchers.register("/v1/q", "http://h/v1/q")
        assert d.tenancy is platform.tenancy
        q = platform.broker.queue("/v1/q")
        assert q.fair is platform.tenancy.lanes

    def test_sharded_sub_queues_get_lanes_too(self):
        platform = LocalPlatform(
            PlatformConfig(tenancy=True, task_shards=2,
                           tenancy_tenants="a=ka"),
            metrics=MetricsRegistry())
        platform.broker.register_queue("/v1/q")
        platform.store.upsert(APITask(endpoint="/v1/q", tenant="a",
                                      publish=True))
        depths = {name: platform.broker.queue(name).lane_depths()
                  for name in platform.broker.queue_names()}
        assert sum(d.get("a", 0) for d in depths.values()) == 1
        laned = [n for n, d in depths.items() if d.get("a")]
        assert laned and "#s" in laned[0]  # landed on a shard sub-queue

    def test_refusals(self):
        with pytest.raises(ValueError, match="queue transport"):
            LocalPlatform(PlatformConfig(tenancy=True, transport="push"))
        with pytest.raises(ValueError, match="Python store and broker"):
            LocalPlatform(PlatformConfig(tenancy=True, native_broker=True))
        with pytest.raises(ValueError, match="Python store and broker"):
            LocalPlatform(PlatformConfig(tenancy=True, native_store=True))

    def test_malformed_spec_fails_at_assembly(self):
        with pytest.raises(ValueError, match="expected name="):
            LocalPlatform(PlatformConfig(tenancy=True,
                                         tenancy_tenants="oops"))

    def test_config_env_round_trip(self):
        from ai4e_tpu.config import FrameworkConfig
        cfg = FrameworkConfig.from_env({
            "AI4E_TENANCY_ENABLED": "1",
            "AI4E_TENANCY_TENANTS": "a=ka:3:20:40",
            "AI4E_TENANCY_LABEL_TOP_N": "4",
            "AI4E_TENANCY_GOODPUT_TARGET": "0.95",
        })
        pc = cfg.to_platform_config()
        assert pc.tenancy is True
        assert pc.tenancy_tenants == "a=ka:3:20:40"
        assert pc.tenancy_label_top_n == 4
        assert pc.tenancy_goodput_target == 0.95

    def test_dispatcher_charges_cost_through_orchestration(self):
        class _Orch:
            def cost_of(self, uri):
                return 2.0

        class _Tenancy:
            def __init__(self):
                self.charges = []

            def charge(self, tenant, cost):
                self.charges.append((tenant, cost))

        from ai4e_tpu.broker.dispatcher import Dispatcher
        from ai4e_tpu.service import LocalTaskManager
        store = InMemoryTaskStore()
        broker = InMemoryBroker(metrics=MetricsRegistry())
        t = _Tenancy()
        d = Dispatcher(broker, "/v1/q", "http://h/v1/q",
                       LocalTaskManager(store), metrics=MetricsRegistry(),
                       orchestration=_Orch(), tenancy=t)
        assert d.tenancy is t  # threading asserted; the charge call site
        # is exercised end-to-end by tests/test_tenancy_chaos.py
