"""End-to-end async-path tests over the whole platform: gateway → task store →
broker → dispatcher → backend service → status poll — SURVEY.md §3.1's call
stack in one event loop, plus pipelining (§3.4)."""

import asyncio

from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
from ai4e_tpu.service import next_endpoint_from


def run(coro):
    return asyncio.run(coro)


async def serve(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


async def poll_until(client, task_id, predicate, tries=200, delay=0.02):
    body = None
    for _ in range(tries):
        resp = await client.get(f"/v1/taskmanagement/task/{task_id}")
        body = await resp.json()
        if predicate(body):
            return body
        await asyncio.sleep(delay)
    return body


class TestAsyncE2E:
    def test_full_async_lifecycle(self):
        async def main():
            platform = LocalPlatform(PlatformConfig(retry_delay=0.05))
            svc = platform.make_service("detector", prefix="v1/detector")

            @svc.api_async_func("/detect")
            def detect(taskId, body, content_type):
                asyncio.run(_work(taskId, body))

            async def _work(task_id, body):
                await platform.task_manager.update_task_status(task_id, "running")
                await platform.task_manager.complete_task(
                    task_id, f"completed - {len(body)} bytes scored")

            svc_client = await serve(svc.app)
            backend_uri = str(svc_client.make_url("/v1/detector/detect"))
            platform.publish_async_api("/v1/camera-trap/detect", backend_uri)
            gw_client = await serve(platform.gateway.app)
            await platform.start()
            try:
                resp = await gw_client.post("/v1/camera-trap/detect",
                                            data=b"JPEGDATA")
                assert resp.status == 200
                created = await resp.json()
                task_id = created["TaskId"]
                assert created["Status"] == "created"

                final = await poll_until(
                    gw_client, task_id, lambda b: "completed" in b["Status"])
                assert final["Status"] == "completed - 8 bytes scored"
            finally:
                await platform.stop()
                await gw_client.close()
                await svc_client.close()

        run(main())

    def test_backpressure_serializes_saturated_backend(self):
        # A cap-1 backend with N queued tasks: every task completes
        # eventually; dispatcher retries on 503 instead of dropping.
        async def main():
            platform = LocalPlatform(PlatformConfig(retry_delay=0.05))
            svc = platform.make_service("slow", prefix="v1/slow")
            import threading
            gate = threading.Semaphore(1)

            @svc.api_async_func("/work", maximum_concurrent_requests=1)
            def work(taskId, body, content_type):
                with gate:
                    import time as _t
                    _t.sleep(0.05)
                asyncio.run(platform.task_manager.complete_task(
                    taskId, "completed"))

            svc_client = await serve(svc.app)
            backend_uri = str(svc_client.make_url("/v1/slow/work"))
            platform.publish_async_api("/v1/public/work", backend_uri)
            gw_client = await serve(platform.gateway.app)
            await platform.start()
            try:
                ids = []
                for _ in range(5):
                    resp = await gw_client.post("/v1/public/work", data=b"x")
                    ids.append((await resp.json())["TaskId"])
                for tid in ids:
                    final = await poll_until(
                        gw_client, tid,
                        lambda b: "completed" in b["Status"], tries=400)
                    assert "completed" in final["Status"], final
            finally:
                await platform.stop()
                await gw_client.close()
                await svc_client.close()

        run(main())

    def test_pipeline_two_stage(self):
        # §3.4: detector hands the task to the classifier under one TaskId;
        # stage 2 receives the ORIGINAL body (replayed by the store).
        async def main():
            platform = LocalPlatform(PlatformConfig(retry_delay=0.05))
            seen = {}

            det = platform.make_service("det", prefix="v1/det")
            cls = platform.make_service("cls", prefix="v1/cls")

            @det.api_async_func("/detect")
            def detect(taskId, body, content_type):
                async def _s():
                    await platform.task_manager.update_task_status(
                        taskId, "running - detector")
                    nxt = next_endpoint_from(cls_backend, "v1", "cls", "classify")
                    await platform.task_manager.add_pipeline_task(taskId, cls_backend)
                asyncio.run(_s())

            @cls.api_async_func("/classify")
            def classify(taskId, body, content_type):
                seen["stage2_body"] = body
                asyncio.run(platform.task_manager.complete_task(
                    taskId, "completed - classified"))

            det_client = await serve(det.app)
            cls_client = await serve(cls.app)
            det_backend = str(det_client.make_url("/v1/det/detect"))
            cls_backend = str(cls_client.make_url("/v1/cls/classify"))
            platform.publish_async_api("/v1/pipeline/detect", det_backend)
            platform.dispatchers.register("/v1/cls/classify", cls_backend)
            gw_client = await serve(platform.gateway.app)
            await platform.start()
            try:
                resp = await gw_client.post("/v1/pipeline/detect",
                                            data=b"ORIGINAL-IMG")
                task_id = (await resp.json())["TaskId"]
                final = await poll_until(
                    gw_client, task_id, lambda b: "completed" in b["Status"],
                    tries=400)
                assert final["Status"] == "completed - classified"
                assert final["TaskId"] == task_id  # same task across stages
                assert seen["stage2_body"] == b"ORIGINAL-IMG"
            finally:
                await platform.stop()
                await gw_client.close()
                await det_client.close()
                await cls_client.close()

        run(main())

    def test_sync_proxy_route(self):
        async def main():
            platform = LocalPlatform()
            svc = platform.make_service("echo", prefix="v1/echo")

            @svc.api_sync_func("/echo")
            def echo(body, content_type):
                return {"echo": body.decode()}

            svc_client = await serve(svc.app)
            platform.publish_sync_api(
                "/v1/public/echo", str(svc_client.make_url("/v1/echo/echo")))
            gw_client = await serve(platform.gateway.app)
            try:
                resp = await gw_client.post("/v1/public/echo", data=b"hi")
                assert resp.status == 200
                assert (await resp.json()) == {"echo": "hi"}
            finally:
                await gw_client.close()
                await svc_client.close()

        run(main())

    def test_gateway_404_on_unknown_task(self):
        async def main():
            platform = LocalPlatform()
            gw_client = await serve(platform.gateway.app)
            try:
                resp = await gw_client.get("/v1/taskmanagement/task/ghost")
                assert resp.status == 404
            finally:
                await gw_client.close()

        run(main())


class TestCrashRecovery:
    def test_journaled_platform_redispatches_unfinished_tasks(self, tmp_path=None):
        # A task accepted before a crash must be dispatched after restart —
        # the durability the reference gets from Service Bus + Redis.
        import tempfile, os
        journal = os.path.join(tempfile.mkdtemp(), "tasks.jsonl")

        async def before_crash():
            platform = LocalPlatform(PlatformConfig(journal_path=journal))
            platform.gateway.add_async_route(
                "/v1/public/work", "http://127.0.0.1:1/v1/svc/work")
            gw = await serve(platform.gateway.app)
            try:
                resp = await gw.post("/v1/public/work", data=b"PAYLOAD")
                tid = (await resp.json())["TaskId"]
            finally:
                await gw.close()
            platform.store.close()
            return tid  # platform never started: broker message dies with it

        task_id = run(before_crash())

        async def after_restart():
            platform = LocalPlatform(PlatformConfig(
                journal_path=journal, retry_delay=0.05))
            svc = platform.make_service("svc", prefix="v1/svc")

            @svc.api_async_func("/work")
            def work(taskId, body, content_type):
                assert body == b"PAYLOAD"
                asyncio.run(platform.task_manager.complete_task(
                    taskId, "completed - recovered"))

            svc_client = await serve(svc.app)
            platform.publish_async_api(
                "/v1/public/work", str(svc_client.make_url("/v1/svc/work")))
            gw = await serve(platform.gateway.app)
            await platform.start()   # re-seeds journal-restored tasks
            try:
                final = await poll_until(
                    gw, task_id, lambda b: "completed" in b["Status"], tries=400)
                assert final["Status"] == "completed - recovered"
            finally:
                await platform.stop()
                await gw.close()
                await svc_client.close()

        run(after_restart())


class TestOperationTails:
    def test_tail_and_query_reach_backend(self):
        # A POST to {prefix}/op?x=1 must reach the backend's /op route with
        # the query intact, not the bare registered URI.
        async def main():
            platform = LocalPlatform(PlatformConfig(retry_delay=0.05))
            svc = platform.make_service("multi", prefix="v1/multi")
            seen = {}

            @svc.api_async_func("/work/opB")
            def op_b(taskId, body, content_type):
                seen["op"] = "B"
                asyncio.run(platform.task_manager.complete_task(
                    taskId, "completed - opB"))

            @svc.api_async_func("/work")
            def base(taskId, body, content_type):
                seen["op"] = "base"
                asyncio.run(platform.task_manager.complete_task(
                    taskId, "completed - base"))

            svc_client = await serve(svc.app)
            platform.publish_async_api(
                "/v1/public/work", str(svc_client.make_url("/v1/multi/work")))
            gw = await serve(platform.gateway.app)
            await platform.start()
            try:
                resp = await gw.post("/v1/public/work/opB?conf=0.9", data=b"x")
                tid = (await resp.json())["TaskId"]
                final = await poll_until(
                    gw, tid, lambda b: "completed" in b["Status"], tries=400)
                assert final["Status"] == "completed - opB"
                assert seen["op"] == "B"
            finally:
                await platform.stop()
                await gw.close()
                await svc_client.close()

        run(main())


class TestDeadLetterHandler:
    def test_reaped_dead_letter_fails_task(self):
        # retry_delay > lease: reaper dead-letters while dispatcher sleeps;
        # the platform's handler must still fail the task.
        async def main():
            platform = LocalPlatform(PlatformConfig(
                retry_delay=0.3, max_delivery_count=1, lease_seconds=0.05))
            platform.gateway.add_async_route(
                "/v1/public/never", "http://127.0.0.1:1/v1/never")
            platform.dispatchers.register("/v1/never", "http://127.0.0.1:1/v1/never")
            gw = await serve(platform.gateway.app)
            await platform.start()
            try:
                resp = await gw.post("/v1/public/never", data=b"x")
                tid = (await resp.json())["TaskId"]
                final = await poll_until(
                    gw, tid, lambda b: "failed" in b["Status"], tries=400)
                assert "failed" in final["Status"], final
            finally:
                await platform.stop()
                await gw.close()

        run(main())


class TestRedriveRecovery:
    def test_dead_lettered_task_redrives_to_recovered_backend(self):
        """Ops loop the reference ran through Service Bus Explorer: backend
        down → delivery budget exhausts → dead-letter fails the task →
        operator fixes the backend → POST /v1/taskstore/redrive → the ORIG
        body replays through the transport and the task completes."""
        import socket

        from ai4e_tpu.taskstore.http import make_app as make_taskstore_app

        def free_port():
            s = socket.socket()
            s.bind(("127.0.0.1", 0))
            port = s.getsockname()[1]
            s.close()
            return port

        async def main():
            platform = LocalPlatform(PlatformConfig(
                retry_delay=0.05, max_delivery_count=1))
            port = free_port()
            backend_uri = f"http://127.0.0.1:{port}/v1/late/fix"
            platform.publish_async_api("/v1/public/flaky", backend_uri)
            make_taskstore_app(platform.store, app=platform.gateway.app)
            gw = await serve(platform.gateway.app)
            await platform.start()
            svc_client = None
            try:
                # Backend down: connection refused burns the one delivery.
                resp = await gw.post("/v1/public/flaky", data=b"ORIGBODY")
                tid = (await resp.json())["TaskId"]
                failed = await poll_until(
                    gw, tid, lambda b: "failed" in b["Status"], tries=400)
                assert "delivery attempts exhausted" in failed["Status"]

                # Operator fixes the backend (same port the route targets).
                svc = platform.make_service("late", prefix="v1/late")
                seen = {}

                @svc.api_async_func("/fix")
                def fix(taskId, body, content_type):
                    seen["body"] = body
                    asyncio.run(platform.task_manager.complete_task(
                        taskId, "completed - recovered"))

                server = TestServer(svc.app, port=port)
                svc_client = TestClient(server)
                await svc_client.start_server()

                resp = await gw.post("/v1/taskstore/redrive", json={})
                body = await resp.json()
                assert body == {"redriven": 1, "task_ids": [tid]}

                final = await poll_until(
                    gw, tid, lambda b: "completed" in b["Status"], tries=400)
                assert final["Status"] == "completed - recovered"
                assert seen["body"] == b"ORIGBODY"  # the ORIG replay
            finally:
                await platform.stop()
                await gw.close()
                if svc_client is not None:
                    await svc_client.close()

        run(main())
