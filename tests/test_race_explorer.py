"""ai4e-race framework tests (docs/concurrency.md).

The explorer itself must be trustworthy before its verdicts on platform
code mean anything, so this file pins its contract:

- determinism: same ``(schedules, seed)`` → byte-identical traces and the
  same verdict, across runs;
- sensitivity: the canonical lost-update race is found within a small
  budget; the lock-fixed variant is clean over the same budget;
- the virtual clock: ``asyncio.sleep`` costs nothing and orders by
  deadline; a schedule never consults wall time;
- failure modes are verdicts, not hangs: deadlocks and step-budget blowups
  surface as run errors with a replayable trace;
- the vector-clock tracker flags unsynchronized write pairs with both
  stack traces, and the traced lock/event edges suppress the synchronized
  ones;
- ``PrefixSchedule`` replays a failing trace to the same verdict — the
  debugging loop the report's "replay prefix" line promises.

Everything here is stdlib-only: the CI ``race-smoke`` job runs this file
with no JAX installed.
"""

import asyncio

import pytest

from ai4e_tpu.analysis.race import (DeadlockError, ExplorationReport,
                                    PrefixSchedule, RaceError, RaceTracker,
                                    RandomSchedule, ScheduleBudgetExceeded,
                                    TracedEvent, TracedLock,
                                    explore_interleavings, run_schedule,
                                    yield_point)

pytestmark = pytest.mark.race

SEED = 20260803


class Box:
    def __init__(self, n=0):
        self.n = n


def lost_update_fixture():
    """Two read-yield-write incrementers — the canonical schedule race."""
    box = Box()

    async def inc():
        v = box.n
        await yield_point()
        box.n = v + 1

    def check():
        assert box.n == 2, f"lost update: n={box.n}"

    return [inc(), inc()], check


class TestDeterminism:
    def test_same_seed_same_traces_and_verdict(self):
        a = explore_interleavings(lost_update_fixture, schedules=30,
                                  seed=SEED)
        b = explore_interleavings(lost_update_fixture, schedules=30,
                                  seed=SEED)
        assert [r.trace for r in a.runs] == [r.trace for r in b.runs]
        assert [r.ok for r in a.runs] == [r.ok for r in b.runs]
        assert a.ok == b.ok

    def test_different_seed_different_random_schedules(self):
        a = explore_interleavings(lost_update_fixture, schedules=20, seed=1)
        b = explore_interleavings(lost_update_fixture, schedules=20, seed=2)
        rand_a = [r.trace for r in a.runs if r.kind == "random"]
        rand_b = [r.trace for r in b.runs if r.kind == "random"]
        assert rand_a != rand_b

    def test_virtual_clock_orders_by_deadline_not_wall_time(self):
        def make():
            order = []

            async def slow():
                await asyncio.sleep(3600.0)  # one virtual hour, zero real
                order.append("slow")

            async def fast():
                await asyncio.sleep(0.001)
                order.append("fast")

            def check():
                assert order == ["fast", "slow"], order

            return [slow(), fast()], check

        report = explore_interleavings(make, schedules=10, seed=SEED)
        assert report.ok, report.describe()


class TestSensitivity:
    def test_finds_lost_update(self):
        report = explore_interleavings(lost_update_fixture, schedules=20,
                                       seed=SEED)
        assert not report.ok
        # The window is shallow: systematic prefixes alone must hit it.
        assert any(not r.ok and r.kind == "systematic" for r in report.runs)

    def test_lock_fixed_variant_is_clean(self):
        def make():
            box = Box()
            tracker = RaceTracker()
            lock = TracedLock(tracker)

            async def inc():
                async with lock:
                    v = box.n
                    await yield_point()
                    box.n = v + 1

            def check():
                assert box.n == 2
                tracker.assert_race_free()

            return [inc(), inc()], check

        report = explore_interleavings(make, schedules=40, seed=SEED)
        assert report.ok, report.describe()

    def test_fail_fast_stops_at_first_violation(self):
        report = explore_interleavings(lost_update_fixture, schedules=50,
                                       seed=SEED, fail_fast=True)
        assert not report.ok
        assert not report.runs[-1].ok
        assert len(report.runs) < 50

    def test_replay_prefix_reproduces_the_failure(self):
        report = explore_interleavings(lost_update_fixture, schedules=30,
                                       seed=SEED)
        failing = report.failures[0]
        prefix = [c for c, _ in failing.trace]
        # Re-run the full fixture (fresh state + check) under the failing
        # trace as a forced prefix: the violation must reproduce exactly.
        made_coros, made_check = lost_update_fixture()
        results, _trace = run_schedule(lambda: made_coros,
                                       PrefixSchedule(prefix))
        assert not any(isinstance(r, BaseException) for r in results)
        with pytest.raises(AssertionError):
            made_check()


class TestFailureModes:
    def test_deadlock_is_a_verdict(self):
        def make():
            a, b = asyncio.Lock(), asyncio.Lock()

            async def ab():
                async with a:
                    await yield_point()
                    async with b:
                        pass

            async def ba():
                async with b:
                    await yield_point()
                    async with a:
                        pass

            return [ab(), ba()]

        report = explore_interleavings(make, schedules=30, seed=SEED)
        assert not report.ok
        assert any(isinstance(r.error, DeadlockError)
                   for r in report.failures)

    def test_step_budget_is_a_verdict_not_a_hang(self):
        def make():
            async def spin():
                while True:
                    await yield_point()

            return [spin()]

        report = explore_interleavings(make, schedules=2, seed=SEED,
                                       max_steps=200)
        assert not report.ok
        assert all(isinstance(r.error, ScheduleBudgetExceeded)
                   for r in report.runs)

    def test_vthread_exception_is_a_verdict(self):
        def make():
            async def boom():
                await yield_point()
                raise ValueError("explored crash")

            return [boom()]

        report = explore_interleavings(make, schedules=3, seed=SEED)
        assert not report.ok
        assert isinstance(report.failures[0].error, ValueError)

    def test_background_task_exception_is_a_verdict(self):
        # Explored code that create_task's and forgets: the spawned task's
        # crash must fail the run — no root awaits it, so without explicit
        # retrieval it would pass silently.
        def make():
            async def spawn_and_leave():
                asyncio.get_running_loop().create_task(self._bg_boom())
                await yield_point()

            return [spawn_and_leave()]

        report = explore_interleavings(make, schedules=3, seed=SEED)
        assert not report.ok
        assert isinstance(report.failures[0].error, RuntimeError)
        assert "background crash" in str(report.failures[0].error)

    @staticmethod
    async def _bg_boom():
        await yield_point()
        raise RuntimeError("background crash")


class TestHappensBefore:
    def test_unsynchronized_writes_reported_with_both_stacks(self):
        def make():
            tracker = RaceTracker()

            async def writer():
                tracker.write("breaker.state")
                await yield_point()

            def check():
                tracker.assert_race_free()

            return [writer(), writer()], check

        report = explore_interleavings(make, schedules=5, seed=SEED)
        assert not report.ok
        err = report.failures[0].error
        assert isinstance(err, RaceError)
        a, b = err.pairs[0]
        text = str(err)
        assert "breaker.state" in text
        # Both stacks rendered, naming the racing vthreads.
        assert a.vthread != b.vthread
        assert a.stack and b.stack

    def test_reads_never_race_with_reads(self):
        def make():
            tracker = RaceTracker()

            async def reader():
                tracker.read("task:t1")
                await yield_point()
                tracker.read("task:t1")

            def check():
                tracker.assert_race_free()

            return [reader(), reader()], check

        report = explore_interleavings(make, schedules=10, seed=SEED)
        assert report.ok, report.describe()

    def test_lock_edge_orders_accesses(self):
        def make():
            tracker = RaceTracker()
            lock = TracedLock(tracker)

            async def writer():
                async with lock:
                    tracker.write("cache.inflight")

            def check():
                tracker.assert_race_free()

            return [writer(), writer()], check

        report = explore_interleavings(make, schedules=20, seed=SEED)
        assert report.ok, report.describe()

    def test_event_edge_orders_publisher_before_waiter(self):
        def make():
            tracker = RaceTracker()
            event = TracedEvent(tracker)

            async def producer():
                tracker.write("task:t1")
                event.set()

            async def consumer():
                await event.wait()
                tracker.read("task:t1")

            def check():
                tracker.assert_race_free()

            return [producer(), consumer()], check

        report = explore_interleavings(make, schedules=20, seed=SEED)
        assert report.ok, report.describe()


class TestSchedules:
    def test_random_schedule_trace_records_branching(self):
        sched = RandomSchedule(7)
        choices = [sched.pick(3) for _ in range(5)]
        assert all(0 <= c < 3 for c in choices)
        assert sched.trace == [(c, 3) for c in choices]

    def test_prefix_schedule_clamps_shrunken_branching(self):
        sched = PrefixSchedule([5, 1])
        assert sched.pick(2) == 1   # 5 clamped to n-1
        assert sched.pick(3) == 1
        assert sched.pick(4) == 0   # past the prefix: default 0

    def test_report_describe_names_seed_and_replay_prefix(self):
        report = explore_interleavings(lost_update_fixture, schedules=20,
                                       seed=SEED)
        text = report.describe()
        assert str(SEED) in text
        assert "replay prefix" in text

    def test_empty_exploration_report_is_ok(self):
        assert ExplorationReport([], seed=0).ok
