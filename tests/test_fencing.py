"""Split-brain fencing tests (VERDICT r4 #3 + ADVICE r4).

The reference got the single-writer property from managed Redis — one
writer, Azure's problem (``RedisConnection.cs:12-38``). Here it is code:
promotion mints a journaled fencing epoch, every store response carries it
(``X-Store-Epoch``), clients echo the highest epoch they have seen, and a
primary that learns of a newer epoch self-demotes and refuses writes. The
headline test is the partition e2e: the old primary is PARTITIONED (alive,
not killed), the standby promotes, a write attempted against the old
primary is REJECTED and lands on the true primary instead, and the old
node rejoins as a follower automatically when the partition heals.
"""

import asyncio

import aiohttp
import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
from ai4e_tpu.service.task_manager import HttpTaskManager
from ai4e_tpu.taskstore import (
    APITask,
    FollowerTaskStore,
    NotPrimaryError,
    StaleEpochError,
)
from ai4e_tpu.taskstore.http import make_app
from ai4e_tpu.taskstore.replication import (
    FailoverWatchdog,
    FencingProber,
    JournalReplicator,
)


def run(coro):
    return asyncio.run(coro)


async def serve(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


async def wait_for(predicate, timeout=10.0, interval=0.02):
    deadline = asyncio.get_event_loop().time() + timeout
    while asyncio.get_event_loop().time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval)
    return predicate()


def make_partition_proxy(target_url: str, journal_budget: int | None = None):
    """A togglable 'network' in front of ``target_url``: while
    ``state['up']`` is False every request gets a 503 — what a partitioned
    peer looks like to the watchdog's probe (non-200), the replicator
    (stream error), and the fencing prober (no role answer).

    ``journal_budget``: forward only that many journal DATA polls
    (limit != 1; the watchdog's probes use limit=1) and then flip the
    partition on — a deterministic 'primary died mid-initial-sync'."""
    state = {"up": True, "journal_left": journal_budget}
    target = target_url.rstrip("/")
    session_holder = {}

    async def forward(request: web.Request) -> web.Response:
        if not state["up"]:
            return web.Response(status=503, text="partitioned")
        if (state["journal_left"] is not None
                and "/journal" in request.path
                and request.query.get("limit") != "1"):
            if state["journal_left"] <= 0:
                state["up"] = False
                return web.Response(status=503, text="partitioned")
            state["journal_left"] -= 1
        session = session_holder.get("s")
        if session is None or session.closed:
            session = aiohttp.ClientSession()
            session_holder["s"] = session
        async with session.request(
                request.method, target + request.path_qs,
                data=await request.read(),
                headers={k: v for k, v in request.headers.items()
                         if k.startswith("X-")}) as resp:
            body = await resp.read()
            headers = {k: v for k, v in resp.headers.items()
                       if k.startswith("X-")}
            return web.Response(status=resp.status, body=body,
                                headers=headers,
                                content_type=resp.content_type)

    app = web.Application()
    app.router.add_route("*", "/{tail:.*}", forward)

    async def close():
        s = session_holder.get("s")
        if s is not None:
            await s.close()

    return app, state, close


class TestEpochLifecycle:
    def test_promotion_mints_and_journals_the_epoch(self, tmp_path):
        path = str(tmp_path / "f.jsonl")
        store = FollowerTaskStore(path)
        store.promote()
        assert store.epoch == 1
        store.close()
        # The mint survives restart: a re-promotion can never reuse it.
        store2 = FollowerTaskStore(path)
        assert store2.epoch == 1
        store2.promote()
        assert store2.epoch == 2
        store2.close()

    def test_born_primary_accepts_writes_without_minting(self, tmp_path):
        store = FollowerTaskStore(str(tmp_path / "p.jsonl"),
                                  start_as_primary=True)
        assert store.role == "primary"
        assert store.epoch == 0  # boot is not a failover
        t = store.upsert(APITask(endpoint="http://e/v1/x", body=b"b"))
        assert store.get(t.task_id).task_id == t.task_id
        store.close()

    def test_demote_fences_writes_and_survives_restart(self, tmp_path):
        path = str(tmp_path / "p.jsonl")
        store = FollowerTaskStore(path, start_as_primary=True)
        store.upsert(APITask(endpoint="http://e/v1/x", body=b"b"))
        store.demote(epoch=3)
        assert store.role == "follower"
        assert store.epoch == 3
        with pytest.raises(NotPrimaryError):
            store.upsert(APITask(endpoint="http://e/v1/x", body=b"c"))
        store.close()
        # A rebooted deposed primary replays the fence: its next promotion
        # mints PAST the epoch that deposed it.
        store2 = FollowerTaskStore(path, start_as_primary=True)
        assert store2.epoch == 3
        store2.close()

    def test_demote_with_stale_epoch_is_refused(self, tmp_path):
        store = FollowerTaskStore(str(tmp_path / "p.jsonl"),
                                  start_as_primary=True)
        store.demote(epoch=5)
        store.promote()  # mints 6
        assert store.epoch == 6
        with pytest.raises(StaleEpochError):
            store.demote(epoch=6)  # equal is not newer
        assert store.role == "primary"
        store.close()

    def test_note_epoch_self_demotes_only_on_newer(self, tmp_path):
        store = FollowerTaskStore(str(tmp_path / "p.jsonl"),
                                  start_as_primary=True)
        store.note_epoch(0)
        assert store.role == "primary"
        store.note_epoch(2)
        assert store.role == "follower"
        assert store.epoch == 2
        store.close()

    def test_epoch_survives_compaction(self, tmp_path):
        path = str(tmp_path / "f.jsonl")
        store = FollowerTaskStore(path)
        store.promote()
        for i in range(4):
            t = store.upsert(APITask(endpoint="http://e/v1/x",
                                     body=b"b%d" % i))
            store.update_status(t.task_id, "completed")
        store.compact()
        store.close()
        store2 = FollowerTaskStore(path, start_as_primary=True)
        assert store2.epoch == 1
        store2.close()


class TestResetRoleFence:
    def test_reset_refuses_after_promotion(self, tmp_path):
        # ADVICE r4 high: a replicator that kept running past a promotion
        # must not be able to wipe the newly-promoted primary via the
        # generation-resync path.
        store = FollowerTaskStore(str(tmp_path / "f.jsonl"))
        store.promote()
        t = store.upsert(APITask(endpoint="http://e/v1/x", body=b"b"))
        with pytest.raises(RuntimeError, match="reset after promote"):
            store.reset()
        assert store.get(t.task_id).task_id == t.task_id
        store.close()

    def test_http_promote_runs_full_lifecycle(self, tmp_path):
        # ADVICE r4 high, second half: POST /promote with a platform
        # lifecycle stops the replicator + watchdog BEFORE the flip and
        # starts the transport — the exact watchdog sequence.
        async def main():
            pri = LocalPlatform(PlatformConfig(
                journal_path=str(tmp_path / "pri.jsonl"), retry_delay=0.05))
            pri_client = await serve(make_app(pri.store, lifecycle=pri))
            await pri.start()
            t = pri.store.upsert(APITask(endpoint="http://e/v1/x", body=b"b"))

            stb = LocalPlatform(PlatformConfig(
                journal_path=str(tmp_path / "stb.jsonl"),
                replicate_from=str(pri_client.make_url("")),
                failover_interval=0.05, failover_down_after=2,
                retry_delay=0.05))
            stb_client = await serve(make_app(stb.store, lifecycle=stb))
            await stb.start()
            try:
                assert await wait_for(
                    lambda: t.task_id in {x.task_id
                                          for x in stb.store.unfinished_tasks()})
                resp = await stb_client.post("/v1/taskstore/promote")
                assert resp.status == 200
                data = await resp.json()
                assert data["role"] == "primary"
                assert data["epoch"] == 1
                # Replication machinery is gone; transport is running; the
                # replicated task was re-seeded for dispatch.
                assert stb.replicator is None and stb.watchdog is None
                assert stb._transport_running
                # The store journal is live again: writes flow.
                stb.store.update_status(t.task_id, "completed")
            finally:
                await stb.stop()
                await pri.stop()
                await stb_client.close()
                await pri_client.close()

        run(main())


class TestSyncedMeansCaughtUp:
    def test_watchdog_never_promotes_mid_initial_sync(self, tmp_path):
        # ADVICE r4 medium: with a chunk limit far below the journal size,
        # the first poll transfers an arbitrary snapshot PREFIX. If the
        # primary dies right then, promotion must NOT arm — a follower
        # holding half the tasks would be crowned. Partition the primary
        # after the first chunk and assert the watchdog holds its fire.
        async def main():
            primary = FollowerTaskStore(str(tmp_path / "pri.jsonl"),
                                        start_as_primary=True)
            for i in range(20):
                primary.upsert(APITask(endpoint="http://e/v1/x",
                                       body=b"payload-%03d" % i))
            pri_client = await serve(make_app(primary))
            # The proxy forwards exactly ONE journal data poll, then
            # partitions — deterministically "the primary died after the
            # first 256-byte chunk of a 20-task snapshot".
            proxy_app, net, close_proxy = make_partition_proxy(
                str(pri_client.make_url("")), journal_budget=1)
            proxy_client = await serve(proxy_app)

            follower = FollowerTaskStore(str(tmp_path / "stb.jsonl"))
            repl = JournalReplicator(follower,
                                     str(proxy_client.make_url("")),
                                     poll_wait=0.1, chunk_limit=256)
            dog = FailoverWatchdog(repl, interval=0.05, down_after=2)
            repl.start()
            dog.start()
            try:
                assert await wait_for(lambda: repl.offset > 0)
                assert not repl.synced.is_set(), (
                    "a 256-byte chunk of a 20-task journal must not count "
                    "as synced")
                await asyncio.sleep(0.5)  # many watchdog intervals
                assert not dog.promoted.is_set()
                assert follower.role == "follower"
                # Heal: replication catches up, and only now is the
                # follower a legal promotion target.
                net["journal_left"] = None
                net["up"] = True
                assert await wait_for(lambda: repl.synced.is_set())
                assert await wait_for(
                    lambda: len(follower.unfinished_tasks()) == 20)
                net["up"] = False
                assert await wait_for(lambda: dog.promoted.is_set())
                assert follower.role == "primary"
                assert len(follower.unfinished_tasks()) == 20
            finally:
                await dog.stop()
                await repl.aclose()
                await close_proxy()
                await proxy_client.close()
                await pri_client.close()
                primary.close()
                follower.close()

        run(main())


class TestClientRotation:
    def test_plain_503_does_not_rotate_to_follower(self, tmp_path):
        # ADVICE r4 low: only an X-Not-Primary 503 means "rotate"; an
        # overloaded/draining primary's plain 503 must surface to the
        # caller, not silently re-home reads to a lagging follower.
        async def main():
            overloaded = web.Application()

            async def plain_503(_):
                return web.json_response({"error": "draining"}, status=503)

            overloaded.router.add_route("*", "/{tail:.*}", plain_503)
            busy_client = await serve(overloaded)

            follower = FollowerTaskStore(str(tmp_path / "f.jsonl"))
            fol_client = await serve(make_app(follower))

            mgr = HttpTaskManager([str(busy_client.make_url("")),
                                   str(fol_client.make_url(""))])
            try:
                resp, _ = await mgr._request("GET", "/v1/taskstore/depths")
                assert resp.status == 503
                assert mgr.base_url == str(busy_client.make_url("")).rstrip("/")
            finally:
                await mgr.close()
                await fol_client.close()
                await busy_client.close()
                follower.close()

        run(main())


class TestPartitionedPrimaryIsFenced:
    def test_partitioned_primary_rejects_write_and_rejoins(self, tmp_path):
        """The headline split-brain e2e (VERDICT r4 #3 'done' criteria):

        1. HA pair running; standby mirrors the primary.
        2. The primary is PARTITIONED from the standby — alive, serving,
           its HTTP surface still open to clients.
        3. The standby's watchdog promotes it (epoch 1) and its fencing
           prober starts knocking on the old primary's door.
        4. A client that has seen the new primary writes to the OLD
           primary: the write is REJECTED (epoch header demotes it,
           503-not-primary), and the client's rotation lands the write on
           the true primary — rejected, not lost.
        5. The partition heals: the prober's demote call (with the new
           primary's URL) makes the old node rejoin as a follower
           automatically and mirror the new primary's state.
        """
        async def main():
            # -- 1. HA pair ------------------------------------------------
            pri = LocalPlatform(PlatformConfig(
                journal_path=str(tmp_path / "pri.jsonl"), retry_delay=0.05))
            pri_client = await serve(make_app(pri.store, lifecycle=pri))
            pri_url = str(pri_client.make_url("")).rstrip("/")
            # advertise_url is the HA-pair marker: it arms passive fencing
            # on this primary (a solo primary ignores epoch headers).
            pri.config.advertise_url = pri_url
            await pri.start()

            proxy_app, net, close_proxy = make_partition_proxy(pri_url)
            proxy_client = await serve(proxy_app)

            stb = LocalPlatform(PlatformConfig(
                journal_path=str(tmp_path / "stb.jsonl"),
                replicate_from=str(proxy_client.make_url("")),
                failover_interval=0.05, failover_down_after=2,
                retry_delay=0.05))
            stb_client = await serve(make_app(stb.store, lifecycle=stb))
            stb_url = str(stb_client.make_url("")).rstrip("/")
            stb.config.advertise_url = stb_url
            await stb.start()

            mgr = HttpTaskManager([stb_url, pri_url], failover_delay=0.05)
            try:
                t_before = pri.store.upsert(APITask(
                    endpoint="http://e/v1/landcover/classify",
                    body=b"tile-before"))
                assert await wait_for(
                    lambda: t_before.task_id in {
                        x.task_id for x in stb.store.unfinished_tasks()})

                # -- 2+3. partition; standby promotes ----------------------
                net["up"] = False
                assert await wait_for(
                    lambda: stb.store.role == "primary", timeout=15.0)
                assert stb.store.epoch == 1
                # The watchdog promotion releases the replicator ref (a
                # beat after the role flip — _on_promoted runs async) —
                # a later fail-back demote must see `replicator is None`
                # or it would silently skip the auto-rejoin.
                assert await wait_for(lambda: stb.replicator is None)
                # The old primary is alive and still believes it is primary
                # — the dangerous window.
                assert pri.store.role == "primary"
                assert pri.store.epoch == 0

                # -- 4. fenced write ---------------------------------------
                # The client reads from the new primary (learns epoch 1)…
                status = await mgr.get_task_status(t_before.task_id)
                assert status is not None
                assert mgr.store_epoch == 1
                # …then client-side routing flaps back to the old primary.
                mgr.base_url = pri_url
                created = await mgr.add_task(
                    "http://e/v1/landcover/classify",
                    b"tile-during-split")
                new_id = created["TaskId"]
                # The epoch header demoted the old primary on contact: the
                # write was rejected there and rotation landed it on the
                # true primary.
                assert pri.store.role == "follower"
                assert pri.store.epoch == 1
                assert stb.store.get(new_id).task_id == new_id
                with pytest.raises(KeyError):
                    # not in the deposed node's (stale) lineage
                    pri.store.get(new_id)
                # Direct writes to the deposed node now refuse loudly.
                with pytest.raises(NotPrimaryError):
                    pri.store.upsert(APITask(endpoint="http://e/v1/x",
                                             body=b"doomed"))

                # -- 5. heal; auto-rejoin ----------------------------------
                net["up"] = True
                assert await wait_for(
                    lambda: pri.replicator is not None, timeout=15.0)
                assert await wait_for(
                    lambda: (new_id in {x.task_id
                                        for x in pri.store.unfinished_tasks()}
                             ), timeout=15.0)
                assert pri.store.role == "follower"
                # Full mirror of the new primary, fence intact.
                assert (pri.store.get(new_id).to_dict()
                        == stb.store.get(new_id).to_dict())
                assert pri.store.epoch == 1
            finally:
                await mgr.close()
                await stb.stop()
                await pri.stop()
                await close_proxy()
                await proxy_client.close()
                await stb_client.close()
                await pri_client.close()

        run(main())


class TestPushTransportFailback:
    def test_push_transport_rebuilds_after_demote_and_repromote(
            self, tmp_path):
        # PushTopic.aclose() is terminal — a demoted push-transport node
        # must rebuild topic + webhook on re-promotion, or fail-back would
        # crash the promotion with "push topic is closed".
        async def main():
            p = LocalPlatform(PlatformConfig(
                transport="push", retry_delay=0.05,
                journal_path=str(tmp_path / "p.jsonl")))
            await p.start()
            try:
                await p.demote_now(epoch=1)
                assert p.store.role == "follower"
                assert p.topic is None and not p._transport_running
                await p.promote_now()
                assert p.store.role == "primary"
                assert p.store.epoch == 2
                assert p.topic is not None and p._transport_running
                # The store's publish hook points at the NEW topic: an
                # upsert publishes without raising.
                t = p.store.upsert(APITask(endpoint="http://e/v1/x",
                                           body=b"b"))
                assert p.store.get(t.task_id).canonical_status == "created"
            finally:
                await p.stop()

        run(main())


class TestSoloPrimaryImmunity:
    def test_forged_epoch_header_cannot_fence_a_solo_primary(self, tmp_path):
        # A primary with no configured HA peer has no standby to take
        # over: a forged/stale X-Store-Epoch header must NOT demote it
        # (that would be a total write outage from one bogus request).
        async def main():
            solo = LocalPlatform(PlatformConfig(
                journal_path=str(tmp_path / "solo.jsonl"), retry_delay=0.05))
            client = await serve(make_app(solo.store, lifecycle=solo))
            await solo.start()  # no advertise_url → passive fencing off
            try:
                resp = await client.post(
                    "/v1/taskstore/upsert",
                    json={"Endpoint": "http://e/v1/x", "Body": "b"},
                    headers={"X-Store-Epoch": "999"})
                assert resp.status == 200, await resp.text()
                assert solo.store.role == "primary"
                assert solo.store.epoch == 0
            finally:
                await solo.stop()
                await client.close()

        run(main())


class TestHaObservability:
    def test_role_epoch_and_replication_lag_gauges(self, tmp_path):
        # The HA machinery is alertable: role/epoch ride the depth
        # logger's 30s tick, replication offset/lag ride the replicator's
        # poll loop. Split-brain shows as two role=1 or epoch skew.
        async def main():
            from ai4e_tpu.metrics import MetricsRegistry
            from ai4e_tpu.observability import DepthLogger

            primary = FollowerTaskStore(str(tmp_path / "pri.jsonl"),
                                        start_as_primary=True)
            primary.upsert(APITask(endpoint="http://e/v1/x", body=b"b"))
            pri_client = await serve(make_app(primary))
            follower = FollowerTaskStore(str(tmp_path / "stb.jsonl"))
            metrics = MetricsRegistry()
            repl = JournalReplicator(follower,
                                     str(pri_client.make_url("")),
                                     poll_wait=0.1, metrics=metrics)
            repl.start()
            try:
                assert await wait_for(lambda: repl.synced.is_set())
                assert metrics.gauge(
                    "ai4e_replication_offset_bytes").value() > 0
                assert metrics.gauge(
                    "ai4e_replication_lag_bytes").value() == 0.0
                logger = DepthLogger(follower, metrics=metrics)
                logger.sample_queue_depth()
                assert metrics.gauge("ai4e_store_role").value() == 0.0
                follower2 = DepthLogger(primary, metrics=metrics)
                follower2.sample_queue_depth()
                assert metrics.gauge("ai4e_store_role").value() == 1.0
                primary.demote(epoch=7)
                follower2.sample_queue_depth()
                assert metrics.gauge("ai4e_store_role").value() == 0.0
                assert metrics.gauge("ai4e_store_epoch").value() == 7.0
            finally:
                await repl.aclose()
                await pri_client.close()
                primary.close()
                follower.close()

        run(main())


class TestFencingProber:
    def test_prober_demotes_stale_primary_without_client_traffic(
            self, tmp_path):
        # Passive fencing needs a client to carry the epoch; the prober
        # closes the window deterministically even on an idle system.
        async def main():
            stale = FollowerTaskStore(str(tmp_path / "stale.jsonl"),
                                      start_as_primary=True)
            stale_client = await serve(make_app(stale))

            new = FollowerTaskStore(str(tmp_path / "new.jsonl"))
            new.promote()  # epoch 1
            prober = FencingProber(new, str(stale_client.make_url("")),
                                   interval=0.05)
            prober.start()
            try:
                assert await wait_for(lambda: prober.fenced.is_set())
                assert stale.role == "follower"
                assert stale.epoch == 1
            finally:
                await prober.aclose()
                await stale_client.close()
                stale.close()
                new.close()

        run(main())
