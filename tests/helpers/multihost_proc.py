"""Subprocess body for the multi-host serving test: N jax.distributed
processes over CPU, primary broadcasts batches, followers mirror
(``parallel/multihost.py``). Run: multihost_proc.py <proc_id> <nprocs> <port>.
"""

import os
import sys

proc_id, nprocs, port = int(sys.argv[1]), int(sys.argv[2]), sys.argv[3]
os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                           + " --xla_force_host_platform_device_count=2").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(f"127.0.0.1:{port}", num_processes=nprocs,
                           process_id=proc_id)
assert jax.process_count() == nprocs, jax.process_count()

import numpy as np  # noqa: E402

from ai4e_tpu.parallel import MeshSpec, make_mesh  # noqa: E402
from ai4e_tpu.parallel.multihost import MultihostRuntime, is_primary  # noqa: E402
from ai4e_tpu.runtime import ModelRuntime, build_servable  # noqa: E402
from ai4e_tpu.runtime.families import build_echo  # noqa: E402

# Global dp mesh over every device of every process. Two servables so the
# bridge is exercised with both wire dtypes: f32 (echo) and the seqformer
# family's f16 default (the descriptor carries the dtype code; followers
# must reassemble half-precision shards byte-exactly).
mesh = make_mesh(MeshSpec(dp=jax.device_count()))
runtime = ModelRuntime(mesh=mesh)
runtime.register(build_echo(size=4, buckets=(jax.device_count(),)))
runtime.register(build_servable(
    "seqformer", name="lc16", seq_len=16, input_dim=8, dim=16, depth=1,
    heads=2, num_classes=4, attention="full",
    buckets=(jax.device_count(),)))
mh = MultihostRuntime(runtime)

import ai4e_tpu.parallel.multihost as mh_mod  # noqa: E402

if proc_id == 1:
    # Sabotage follower 1's FOURTH shard fetch (batches 1-3 are the happy
    # path below): the follower must degrade to a zeros shard, stay in
    # lockstep, and report its rows poisoned on the health gather
    # (VERDICT r2 #5).
    real_fetch = mh_mod._fetch
    calls = {"n": 0}

    def flaky_fetch(url, token, timeout_s=60.0):
        calls["n"] += 1
        if calls["n"] == 4:
            raise TimeoutError("injected fetch failure")
        return real_fetch(url, token, timeout_s)

    mh_mod._fetch = flaky_fetch

if is_primary():
    n = jax.device_count()
    batch = np.arange(n * 4, dtype=np.float32).reshape(n, 4)
    out = np.asarray(mh.run_batch("echo", batch))
    np.testing.assert_allclose(out, batch, rtol=1e-6)
    out2 = np.asarray(mh.run_batch("echo", batch * 3))
    np.testing.assert_allclose(out2, batch * 3, rtol=1e-6)
    # Sharded ingestion (VERDICT r1 weak #5): the primary must ship each
    # follower ONLY the rows its devices own — batch/N bytes, not a full
    # O(batch) replica. With dp=n over `nprocs` equal hosts that is
    # exactly (nprocs-1)/nprocs of the batch in total.
    expected = batch.nbytes * (nprocs - 1) // nprocs
    assert mh.last_egress_bytes == expected, (
        mh.last_egress_bytes, expected)
    assert mh.last_egress_bytes < batch.nbytes
    assert 0.0 < mh.last_ingest_s < 5.0, mh.last_ingest_s
    # f16 wire through the bridge: half-precision shards reassemble and
    # score; egress stays rows-owned-only at 2 bytes/element.
    seqs = np.random.default_rng(0).standard_normal(
        (n, 16, 8)).astype(np.float16)
    logits = np.asarray(mh.run_batch("lc16", seqs))
    assert logits.shape == (n, 4), logits.shape
    assert np.isfinite(logits).all()
    expected = seqs.nbytes * (nprocs - 1) // nprocs
    assert mh.last_egress_bytes == expected, (
        mh.last_egress_bytes, expected)
    # Batch 4: follower 1's fetch is sabotaged — the health gather must
    # flag exactly its rows as poisoned while the slice stays alive.
    out4, poisoned = mh.run_batch_report("echo", batch)
    expect_rows = {i for a, b in mh._plan("echo", batch.shape)[1]
                   for i in range(a, b)}
    assert poisoned == frozenset(expect_rows), (poisoned, expect_rows)
    # Unaffected rows still scored correctly.
    good = sorted(set(range(n)) - expect_rows)
    np.testing.assert_allclose(np.asarray(out4)[good], batch[good], rtol=1e-6)
    # Batch 5: the follower healed — clean report, correct output everywhere.
    out5, poisoned5 = mh.run_batch_report("echo", batch * 2)
    assert poisoned5 == frozenset(), poisoned5
    np.testing.assert_allclose(np.asarray(out5), batch * 2, rtol=1e-6)
    mh.shutdown_followers()
    print("PRIMARY_OK", flush=True)
else:
    mh.follower_loop()
    assert 0.0 < mh.last_ingest_s < 5.0, mh.last_ingest_s
    print("FOLLOWER_OK", flush=True)
