"""Traffic-tuned ladder + double-buffered transfer tests (PR 13,
docs/device_path.md):

- ``ShapeHistogram`` decay/bounds, ``derive_ladder`` unit + PROPERTY
  tests (monotone, covers the observed max, never worse pad-waste than
  the static ladder on the same histogram, program budget respected);
- persistence round-trip + the invalidation rule (params_version bump
  keeps the ladder, model code change discards it);
- ``LadderManager`` derive→prepare→swap→persist loop over a stub
  runtime (order: every bucket warmed BEFORE the swap), dwell + sample
  floors, restore-before-warmup;
- batcher identity: with derivation off the registered metric set and
  the ``ai4e_batch_size`` exposition buckets are byte-identical to the
  pre-ladder platform (same discipline as observability=False); with it
  on, exposition buckets come from the servables' own ladders;
- the double-buffered execute path on the real runtime: identical
  results, measured phase windows, overlap accounting;
- restart-warm acceptance: a second runtime restoring the persisted
  ladder warms it and its first phased serving call stamps ``execute``,
  never ``compile``.
"""

import asyncio
import random
from types import SimpleNamespace

import numpy as np
import pytest

from ai4e_tpu.metrics.registry import MetricsRegistry
from ai4e_tpu.runtime.ladder import (
    DEFAULT_BUCKETS,
    EXPOSITION_BUCKETS,
    LadderManager,
    ShapeHistogram,
    derive_ladder,
    expected_pad_waste,
    exposition_buckets,
    load_ladders,
    save_ladders,
    servable_fingerprint,
)

SEED = 20260803


def run(coro):
    return asyncio.run(coro)


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t


def _stub_servable(buckets=(1, 64), name="m", version="1.0"):
    return SimpleNamespace(name=name, version=version,
                           batch_buckets=tuple(buckets),
                           input_shape=(4,), input_dtype=np.float32,
                           params_version=1, max_bucket=max(buckets))


class _StubRuntime:
    """Duck-typed ModelRuntime for manager tests — records the
    prepare/apply order and enforces the swap-safety invariant the real
    ``apply_ladder`` enforces (no un-executed bucket ever swaps in)."""

    data_axis_size = 1

    def __init__(self, buckets=(1, 64)):
        self.models = {"m": _stub_servable(buckets)}
        self.prepared: list[tuple] = []
        self.applied: list[tuple] = []
        self._warm = set(buckets)

    def prepare_buckets(self, name, buckets):
        aligned = tuple(sorted({int(b) for b in buckets}))
        self.prepared.append(aligned)
        self._warm |= set(aligned)
        return aligned

    def apply_ladder(self, name, buckets):
        aligned = tuple(sorted(buckets))
        missing = [b for b in aligned if b not in self._warm]
        assert not missing, f"swap with un-warmed buckets {missing}"
        self.applied.append(aligned)
        self.models[name].batch_buckets = aligned
        return aligned


class TestShapeHistogram:
    def test_observe_and_snapshot(self):
        clock = _FakeClock()
        hist = ShapeHistogram(window_s=10.0, clock=clock)
        for _ in range(3):
            hist.observe(7)
        hist.observe(20)
        snap = hist.snapshot()
        assert snap[7] == pytest.approx(3.0)
        assert snap[20] == pytest.approx(1.0)
        assert hist.observations == 4

    def test_half_life_decay(self):
        clock = _FakeClock()
        hist = ShapeHistogram(window_s=10.0, clock=clock)
        hist.observe(8, weight=4.0)
        clock.t += 10.0  # one half-life
        assert hist.snapshot()[8] == pytest.approx(2.0)
        clock.t += 20.0  # two more
        assert hist.snapshot()[8] == pytest.approx(0.5)

    def test_bounded_evicts_lightest(self):
        clock = _FakeClock()
        hist = ShapeHistogram(window_s=1e9, max_sizes=4, clock=clock)
        for s in (1, 2, 3, 4):
            hist.observe(s, weight=10.0)
        hist.observe(5, weight=0.5)   # over the bound: lightest (5) evicted
        hist.observe(6, weight=20.0)  # heavier entry evicts the next lightest
        snap = hist.snapshot()
        assert len(snap) == 4
        assert 6 in snap

    def test_nonpositive_size_ignored(self):
        hist = ShapeHistogram()
        hist.observe(0)
        hist.observe(-3)
        assert hist.snapshot() == {}
        assert hist.observations == 0


class TestDeriveLadder:
    def test_empty_histogram_returns_baseline(self):
        assert derive_ladder({}, baseline=(1, 8, 32)) == (1, 8, 32)

    def test_exact_sizes_get_exact_buckets(self):
        hist = {3: 10.0, 17: 5.0}
        out = derive_ladder(hist, baseline=(1, 64), max_programs=8)
        assert expected_pad_waste(out, hist) == 0.0
        assert 3 in out and 17 in out

    def test_budget_of_one_covers_the_max(self):
        hist = {3: 10.0, 17: 5.0}
        out = derive_ladder(hist, baseline=(1, 64), max_programs=1)
        assert out == (17,)

    def test_product_objective_prefers_fewer_zero_waste_programs(self):
        # One observed size: one bucket gives waste 0 × 1 program —
        # strictly better than any larger zero-waste ladder.
        out = derive_ladder({24: 100.0}, baseline=(1, 2, 4, 8, 16, 32),
                            max_programs=8)
        assert out == (24,)

    def test_alignment_rounds_up_and_dedupes(self):
        hist = {3: 1.0, 5: 1.0, 9: 1.0}
        out = derive_ladder(hist, baseline=DEFAULT_BUCKETS,
                            max_programs=8, align=8)
        assert all(b % 8 == 0 for b in out)
        assert max(out) >= 9

    def test_property_derived_never_worse_than_static(self):
        rng = random.Random(SEED)
        static = EXPOSITION_BUCKETS  # the retired (1, 2, 4, ..., 256)
        for trial in range(250):
            hist = {rng.randint(1, 256): rng.uniform(0.1, 100.0)
                    for _ in range(rng.randint(1, 14))}
            derived = derive_ladder(hist, baseline=static, max_programs=16)
            # Monotone (strictly ascending).
            assert list(derived) == sorted(set(derived)), (trial, hist)
            # Largest bucket covers the observed max.
            assert max(derived) >= max(hist), (trial, hist)
            # Program budget respected.
            assert 1 <= len(derived) <= 16, (trial, hist)
            # Never more expected pad-waste than the static ladder.
            assert (expected_pad_waste(derived, hist)
                    <= expected_pad_waste(static, hist) + 1e-9), (
                trial, hist, derived)

    def test_property_holds_under_alignment(self):
        rng = random.Random(SEED + 1)
        static = EXPOSITION_BUCKETS
        for trial in range(100):
            hist = {rng.randint(1, 256): rng.uniform(0.1, 10.0)
                    for _ in range(rng.randint(1, 10))}
            derived = derive_ladder(hist, baseline=static,
                                    max_programs=16, align=8)
            assert all(b % 8 == 0 for b in derived), (trial, derived)
            assert max(derived) >= max(hist), (trial, hist)
            aligned_static = tuple(sorted(
                {((b + 7) // 8) * 8 for b in static}))
            assert (expected_pad_waste(derived, hist)
                    <= expected_pad_waste(aligned_static, hist) + 1e-9), (
                trial, hist, derived)

    def test_skewed_histogram_beats_static_strictly(self):
        # The bench's skew shape: cuts cluster at 20 on a (1, 64) ladder.
        hist = {20: 100.0, 21: 40.0, 1: 5.0}
        static = (1, 64)
        derived = derive_ladder(hist, baseline=static, max_programs=8)
        assert expected_pad_waste(derived, hist) < expected_pad_waste(
            static, hist)

    def test_rejects_bad_budget(self):
        with pytest.raises(ValueError):
            derive_ladder({1: 1.0}, baseline=(1,), max_programs=0)


class TestPersistence:
    def test_round_trip_and_corrupt_file(self, tmp_path):
        path = str(tmp_path / "ladders.json")
        entries = {"m": {"fingerprint": "f", "buckets": [4, 8],
                         "baseline": [1, 64], "generation": 2}}
        save_ladders(path, entries)
        assert load_ladders(path) == entries
        with open(path, "w") as fh:
            fh.write("{not json")
        assert load_ladders(path) == {}
        assert load_ladders(str(tmp_path / "missing.json")) == {}

    def test_fingerprint_ignores_params_version(self):
        s = _stub_servable()
        before = servable_fingerprint(s)
        s.params_version += 1  # hot weight reload
        assert servable_fingerprint(s) == before

    def test_fingerprint_tracks_code_identity(self):
        s = _stub_servable()
        before = servable_fingerprint(s)
        s.version = "2.0"  # model code change
        assert servable_fingerprint(s) != before


class TestLadderManager:
    def _manager(self, runtime, tmp_path=None, **kw):
        clock = kw.pop("clock", _FakeClock())
        path = str(tmp_path / "ladders.json") if tmp_path else None
        mgr = LadderManager(runtime, period_s=kw.pop("period_s", 5.0),
                            dwell_s=kw.pop("dwell_s", 0.0),
                            min_observations=kw.pop("min_observations", 4),
                            persist_path=path, metrics=MetricsRegistry(),
                            clock=clock, **kw)
        return mgr, clock

    def test_derive_swaps_after_prepare_and_persists(self, tmp_path):
        rt = _StubRuntime(buckets=(1, 64))
        mgr, _clock = self._manager(rt, tmp_path)
        for _ in range(10):
            mgr.observe_cut("m", 20)
        assert mgr.derive_now("m") == "swapped"
        # prepare ran BEFORE apply, and apply saw only warmed buckets.
        assert rt.prepared and rt.applied
        assert 20 in rt.models["m"].batch_buckets
        assert mgr.generation("m") == 1
        entry = load_ladders(str(tmp_path / "ladders.json"))["m"]
        assert entry["generation"] == 1
        assert 20 in entry["buckets"]
        assert entry["fingerprint"] == servable_fingerprint(rt.models["m"])

    def test_unchanged_and_sample_floor(self, tmp_path):
        rt = _StubRuntime(buckets=(1, 64))
        mgr, _clock = self._manager(rt, tmp_path)
        assert mgr.derive_now("m") == "skipped"  # nothing observed
        for _ in range(10):
            mgr.observe_cut("m", 64)  # traffic that matches the ladder
        # (1, 64) on an all-64 histogram: 64 covers with 0 waste and the
        # product objective still can't beat... a single (64,) bucket
        # CAN: generation may swap to the smaller ladder. Drive with the
        # baseline shape instead: sizes 1 and 64.
        for _ in range(10):
            mgr.observe_cut("m", 1)
        out = mgr.derive_now("m")
        assert out in ("unchanged", "swapped")
        if out == "swapped":
            assert mgr.derive_now("m") == "unchanged"  # fixpoint

    def test_dwell_bounds_swap_churn(self, tmp_path):
        rt = _StubRuntime(buckets=(1, 64))
        clock = _FakeClock()
        # period_s huge: this test drives derive_now explicitly and must
        # not race the observe_cut-kicked background deriver.
        mgr, _ = self._manager(rt, tmp_path, dwell_s=100.0, clock=clock,
                               period_s=1e9)
        for _ in range(10):
            mgr.observe_cut("m", 20)
        assert mgr.derive_now("m") == "swapped"
        for _ in range(10):
            mgr.observe_cut("m", 33)
        assert mgr.derive_now("m") == "skipped"  # inside the dwell
        clock.t += 101.0
        for _ in range(10):
            mgr.observe_cut("m", 33)
        assert mgr.derive_now("m") == "swapped"

    def test_observe_cut_schedules_background_derive(self, tmp_path):
        rt = _StubRuntime(buckets=(1, 64))
        clock = _FakeClock()
        mgr, _ = self._manager(rt, tmp_path, period_s=5.0, clock=clock)
        for _ in range(20):
            mgr.observe_cut("m", 20)
        assert not rt.applied  # inside the first period: no derive yet
        clock.t += 6.0
        mgr.observe_cut("m", 20)  # period elapsed → background thread
        for _ in range(200):
            if rt.applied:
                break
            import time
            time.sleep(0.01)
        assert rt.applied, "background derive never swapped"
        assert 20 in rt.models["m"].batch_buckets

    def test_restore_applies_matching_entry(self, tmp_path):
        rt = _StubRuntime(buckets=(1, 64))
        path = str(tmp_path / "ladders.json")
        save_ladders(path, {"m": {
            "fingerprint": servable_fingerprint(rt.models["m"]),
            "baseline": [1, 64], "buckets": [4, 20, 64],
            "generation": 3}})
        mgr = LadderManager(rt, persist_path=path,
                            metrics=MetricsRegistry())
        restored = mgr.restore()
        assert restored == {"m": (4, 20, 64)}
        assert rt.models["m"].batch_buckets == (4, 20, 64)
        assert mgr.generation("m") == 3

    def test_restore_discards_stale_fingerprint(self, tmp_path):
        rt = _StubRuntime(buckets=(1, 64))
        path = str(tmp_path / "ladders.json")
        save_ladders(path, {"m": {
            "fingerprint": "someone-else", "baseline": [1, 64],
            "buckets": [4, 20, 64], "generation": 3}})
        mgr = LadderManager(rt, persist_path=path,
                            metrics=MetricsRegistry())
        assert mgr.restore() == {}
        assert rt.models["m"].batch_buckets == (1, 64)

    def test_restore_discards_misaligned_buckets(self, tmp_path):
        rt = _StubRuntime(buckets=(8, 64))
        rt.data_axis_size = 8  # the mesh grew since the ladder persisted
        path = str(tmp_path / "ladders.json")
        save_ladders(path, {"m": {
            "fingerprint": servable_fingerprint(rt.models["m"]),
            "baseline": [8, 64], "buckets": [4, 20], "generation": 1}})
        mgr = LadderManager(rt, persist_path=path,
                            metrics=MetricsRegistry())
        assert mgr.restore() == {}
        assert rt.models["m"].batch_buckets == (8, 64)

    def test_failed_derive_keeps_serving_ladder(self, tmp_path):
        rt = _StubRuntime(buckets=(1, 64))

        def boom(name, buckets):
            raise RuntimeError("compile exploded")

        rt.prepare_buckets = boom
        mgr, _ = self._manager(rt, tmp_path)
        for _ in range(10):
            mgr.observe_cut("m", 20)
        mgr._derive_in_background("m")  # the thread body, synchronously
        assert rt.models["m"].batch_buckets == (1, 64)
        assert mgr.metrics.counter(
            "ai4e_ladder_derives_total", "").value(
            model="m", outcome="failed") == 1
        # The busy flag must clear or no later derive ever runs.
        assert "m" not in mgr._busy


# The exact metric-name set the pre-ladder batcher registered — the
# byte-identity contract for derivation-off (acceptance criterion; same
# discipline as the observability=False assembly assertions).
HEAD_BATCHER_METRICS = {
    "ai4e_batch_size", "ai4e_batch_exec_seconds",
    "ai4e_batch_queue_wait_seconds", "ai4e_batcher_pending",
    "ai4e_batcher_inflight_batches", "ai4e_batch_h2d_bytes_total",
    "ai4e_batch_d2h_bytes_total", "ai4e_admission_expired_total",
}


class TestBatcherIdentityAndExposition:
    def _batcher(self, **kw):
        from ai4e_tpu.runtime.batcher import MicroBatcher
        runtime = kw.pop("runtime", None)
        if runtime is None:
            runtime = SimpleNamespace(models={})
        reg = MetricsRegistry()
        return MicroBatcher(runtime, metrics=reg, **kw), reg

    def test_default_batcher_metric_set_identical_to_head(self):
        _b, reg = self._batcher()
        assert set(reg._metrics) == HEAD_BATCHER_METRICS
        # And the exposition buckets are the static ladder, verbatim.
        hist = reg.histogram("ai4e_batch_size", "")
        assert hist.buckets == (*EXPOSITION_BUCKETS, float("inf"))

    def test_default_exposition_rendering_has_no_ladder_series(self):
        _b, reg = self._batcher()
        text = reg.render_prometheus()
        assert "ai4e_ladder_" not in text
        assert "ai4e_batch_pad_" not in text

    def test_derivation_on_builds_exposition_from_servable_ladders(self):
        rt = _StubRuntime(buckets=(1, 20, 64))
        rt.models["m2"] = _stub_servable(buckets=(4, 96), name="m2")
        mgr = LadderManager(rt, metrics=MetricsRegistry())
        b, reg = self._batcher(runtime=rt, ladder_manager=mgr)
        hist = reg.histogram("ai4e_batch_size", "")
        assert hist.buckets == (1, 4, 20, 64, 96, float("inf"))
        # Pad metrics ride the ladder/phase instruments.
        assert "ai4e_batch_pad_ratio" in reg._metrics
        assert "ai4e_batch_pad_bytes_total" in reg._metrics

    def test_exposition_union_helper(self):
        assert exposition_buckets([]) == EXPOSITION_BUCKETS
        assert exposition_buckets(
            [_stub_servable((1, 8)), _stub_servable((4, 8))]
        ) == (1, 4, 8)

    def test_measure_phases_alone_registers_pad_metrics(self):
        _b, reg = self._batcher(measure_phases=True)
        assert "ai4e_batch_pad_ratio" in reg._metrics

    def test_per_model_flush_gate(self):
        # The cross-model coupling fix, both directions: a full
        # SMALL-bucket model is cut-ready immediately even while a
        # large-bucket model idles, AND a hot full model does NOT
        # cancel a trickle model's own accumulation window.
        import time as _t
        from ai4e_tpu.runtime.batcher import _Pending

        def entry(age=0.0):
            p = _Pending.__new__(_Pending)
            p.enqueued = _t.perf_counter() - age
            return p

        rt = SimpleNamespace(models={
            "small": _stub_servable((1, 4), name="small"),
            "big": _stub_servable((1, 256), name="big")})
        b, _reg = self._batcher(runtime=rt, max_wait_ms=50.0)
        now = _t.perf_counter()
        b._pending = {"small": [entry()] * 4, "big": [entry()]}
        assert b._cut_ready("small", now)        # its own bucket is full
        assert not b._cut_ready("big", now)      # its window keeps running
        assert b._nearest_cut_deadline(now) == 0.0
        b._pending = {"small": [entry()] * 3, "big": [entry()]}
        assert not b._cut_ready("small", now)
        nearest = b._nearest_cut_deadline(now)
        assert nearest is not None and 0 < nearest <= 0.06
        # An expired per-model window is ready regardless of fill.
        b._pending = {"big": [entry(age=0.06)]}
        assert b._cut_ready("big", _t.perf_counter())


def _echo_servable(buckets, name="echo", size=4):
    import jax.numpy as jnp
    from ai4e_tpu.runtime import ServableModel
    return ServableModel(
        name=name,
        apply_fn=lambda params, batch: jnp.asarray(batch) * params["k"],
        params={"k": jnp.asarray(3.0)},
        input_shape=(size,),
        preprocess=lambda body, ct: np.frombuffer(body, np.float32),
        postprocess=lambda out: {"sum": float(np.asarray(out).sum())},
        batch_buckets=buckets,
    )


def _single_device_runtime(**kw):
    import jax
    from ai4e_tpu.parallel import MeshSpec, make_mesh
    from ai4e_tpu.runtime import ModelRuntime
    mesh = make_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
    return ModelRuntime(mesh=mesh, **kw)


class TestRealRuntimeLadder:
    def test_prepare_then_apply_swaps_and_old_buckets_stay_warm(self):
        runtime = _single_device_runtime()
        runtime.register(_echo_servable((1, 8)))
        runtime.warmup(parallel=False)
        prepared = runtime.prepare_buckets("echo", (4, 8))
        assert prepared == (4, 8)
        runtime.apply_ladder("echo", prepared)
        assert runtime.models["echo"].batch_buckets == (4, 8)
        # Old AND new buckets execute without a compile stamp.
        for bucket in (1, 4, 8):
            _out, _p, phases = runtime.run_batch_phases(
                "echo", np.ones((bucket, 4), np.float32))
            assert "execute" in phases and "compile" not in phases

    def test_apply_without_prepare_refused(self):
        runtime = _single_device_runtime()
        runtime.register(_echo_servable((1, 8)))
        runtime.warmup(parallel=False)
        with pytest.raises(RuntimeError, match="no\\s+executed program"):
            runtime.apply_ladder("echo", (1, 4, 8))

    def test_restart_restores_persisted_ladder_and_serves_execute(
            self, tmp_path):
        path = str(tmp_path / "ladders.json")
        # "First life": derive + persist a traffic-tuned ladder.
        rt1 = _single_device_runtime()
        rt1.register(_echo_servable((1, 64)))
        rt1.warmup(parallel=False)
        mgr1 = LadderManager(rt1, persist_path=path, min_observations=4,
                             dwell_s=0.0, metrics=MetricsRegistry())
        for _ in range(16):
            mgr1.observe_cut("echo", 20)
        assert mgr1.derive_now("echo") == "swapped"
        tuned = rt1.models["echo"].batch_buckets
        assert 20 in tuned
        # "Restart": fresh runtime, factory ladder, restore BEFORE warmup.
        rt2 = _single_device_runtime()
        rt2.register(_echo_servable((1, 64)))
        mgr2 = LadderManager(rt2, persist_path=path,
                             metrics=MetricsRegistry())
        assert mgr2.restore() == {"echo": tuned}
        rt2.warmup(parallel=False)
        # First serving call on the tuned bucket stamps execute — the
        # restart serves hot (acceptance criterion).
        _out, _p, phases = rt2.run_batch_phases(
            "echo", np.ones((20, 4), np.float32))
        assert "execute" in phases and "compile" not in phases


class TestDoubleBufferedBatcher:
    def _submit_many(self, batcher, n, size=4):
        async def main():
            await batcher.start()
            try:
                outs = await asyncio.gather(*(
                    batcher.submit("echo", np.full((size,), i,
                                                   np.float32))
                    for i in range(n)))
            finally:
                await batcher.stop()
            return outs
        return run(main())

    def test_results_identical_to_fused_path(self):
        from ai4e_tpu.runtime import MicroBatcher
        results = {}
        for double in (False, True):
            runtime = _single_device_runtime()
            runtime.register(_echo_servable((1, 2, 4, 8)))
            runtime.warmup(parallel=False)
            batcher = MicroBatcher(runtime, max_wait_ms=1.0,
                                   metrics=MetricsRegistry(),
                                   double_buffer=double)
            assert batcher._double is double
            results[double] = self._submit_many(batcher, 12)
        assert results[True] == results[False]

    def test_phase_windows_and_pad_accounting(self):
        from ai4e_tpu.runtime import MicroBatcher
        runtime = _single_device_runtime()
        runtime.register(_echo_servable((1, 2, 4, 8)))
        runtime.warmup(parallel=False)
        reg = MetricsRegistry()
        batcher = MicroBatcher(runtime, max_wait_ms=1.0, metrics=reg,
                               double_buffer=True, measure_phases=True)
        self._submit_many(batcher, 16)
        phase_hist = reg.histogram("ai4e_device_phase_seconds", "")
        counts = {}
        for _k, _n, labels, data in phase_hist.collect():
            counts[labels["phase"]] = counts.get(labels["phase"], 0) \
                + int(data["count"])
        assert counts.get("h2d", 0) > 0
        assert counts.get("execute", 0) > 0
        assert counts.get("d2h", 0) > 0
        # Warmed worker: the serving path never stamps compile.
        assert counts.get("compile", 0) == 0
        # Overlap ratio is defined (>= 0); on shared CPU the actual
        # overlap is not asserted — the bench artifact carries that.
        assert reg.gauge("ai4e_batch_overlap_ratio", "").value() >= 0.0
        # Pad accounting saw the padded cuts.
        assert reg.gauge("ai4e_batch_pad_ratio", "").value(
            model="echo") >= 0.0

    def test_double_buffer_respects_multihost_fallback(self):
        # A runtime without the split surface keeps the fused path.
        from ai4e_tpu.runtime.batcher import MicroBatcher
        rt = SimpleNamespace(models={})
        batcher = MicroBatcher(rt, metrics=MetricsRegistry(),
                               double_buffer=True)
        assert batcher._double is False

    def test_staging_ring_alternates_and_reuses(self):
        from ai4e_tpu.runtime import MicroBatcher
        runtime = _single_device_runtime()
        servable = _echo_servable((1, 2, 4, 8))
        runtime.register(servable)
        runtime.warmup(parallel=False)
        batcher = MicroBatcher(runtime, metrics=MetricsRegistry(),
                               double_buffer=True, pipeline_depth=2)
        b1 = batcher._staging_buffer("echo", 8, servable)
        b2 = batcher._staging_buffer("echo", 8, servable)
        b3 = batcher._staging_buffer("echo", 8, servable)
        assert b1 is not b2
        assert b3 is b1  # ring of pipeline_depth


class TestBatcherLadderIntegration:
    def test_cuts_feed_manager_and_swap_changes_buckets(self):
        from ai4e_tpu.runtime import MicroBatcher
        runtime = _single_device_runtime()
        runtime.register(_echo_servable((1, 64)))
        runtime.warmup(parallel=False)
        mgr = LadderManager(runtime, min_observations=4, dwell_s=0.0,
                            period_s=1e9,  # no background kicks in-test
                            metrics=MetricsRegistry())
        batcher = MicroBatcher(runtime, max_wait_ms=20.0,
                               metrics=MetricsRegistry(),
                               ladder_manager=mgr)

        async def burst(n):
            await asyncio.gather(*(
                batcher.submit("echo", np.full((4,), i, np.float32))
                for i in range(n)))

        async def main():
            await batcher.start()
            try:
                for _ in range(6):
                    await burst(20)
            finally:
                await batcher.stop()

        run(main())
        assert mgr._hists["echo"].observations > 0
        assert mgr.derive_now("echo") == "swapped"
        tuned = runtime.models["echo"].batch_buckets
        assert max(tuned) <= 64
        hist = mgr._hists["echo"].snapshot()
        assert expected_pad_waste(tuned, hist) <= expected_pad_waste(
            (1, 64), hist)


class TestReviewRegressions:
    """Fixes from the PR 13 review pass, each pinned."""

    def test_ladder_grows_back_after_demand_rises(self, tmp_path):
        # The ratchet-down bug: observing POST-clamp cut sizes meant a
        # shrunken ladder capped every later observation at its own max
        # and could never grow back. The batcher now feeds pre-clamp
        # demand and the manager clamps to the FACTORY max only.
        rt = _StubRuntime(buckets=(1, 64))
        mgr = LadderManager(rt, period_s=1e9, dwell_s=0.0,
                            min_observations=4,
                            persist_path=str(tmp_path / "l.json"),
                            metrics=MetricsRegistry(), clock=_FakeClock())
        for _ in range(10):
            mgr.observe_cut("m", 20)
        assert mgr.derive_now("m") == "swapped"
        assert max(rt.models["m"].batch_buckets) == 20  # shrunk
        # Demand rises past the derived max (the batcher reports the
        # pre-clamp queue length, so 64 IS observable again).
        for _ in range(40):
            mgr.observe_cut("m", 64)
        assert mgr.derive_now("m") == "swapped"
        assert max(rt.models["m"].batch_buckets) == 64  # grew back

    def test_observed_demand_clamps_to_factory_max(self):
        rt = _StubRuntime(buckets=(1, 64))
        mgr = LadderManager(rt, period_s=1e9, metrics=MetricsRegistry(),
                            clock=_FakeClock())
        mgr.observe_cut("m", 500)  # a deep backlog, not a servable batch
        assert max(mgr._hists["m"].snapshot()) == 64

    def test_batcher_reports_preclamp_demand(self):
        from ai4e_tpu.runtime.batcher import MicroBatcher, _Pending
        rt = _StubRuntime(buckets=(1, 8))
        seen = []
        mgr = SimpleNamespace(observe_cut=lambda name, n: seen.append(n))

        async def main():
            batcher = MicroBatcher(rt, metrics=MetricsRegistry(),
                                   ladder_manager=mgr)
            loop = asyncio.get_running_loop()
            batcher._pending["m"] = [
                _Pending(np.zeros(4, np.float32), loop.create_future())
                for _ in range(20)]
            batch, bucket = batcher._take_batch("m")
            assert len(batch) == 8  # clamped to the ladder's max bucket
            assert bucket == 8      # chosen from the SAME ladder snapshot
        run(main())
        assert seen == [20]  # …but the DEMAND was observed

    def test_restore_discards_changed_factory_ladder(self, tmp_path):
        # The documented invalidation rule: an operator raising the
        # factory ladder must not be shadowed by a ladder tuned under
        # the old config (fingerprint alone cannot carry this — at
        # persist time batch_buckets already holds the derived ladder).
        rt = _StubRuntime(buckets=(1, 128))  # factory raised since persist
        path = str(tmp_path / "ladders.json")
        save_ladders(path, {"m": {
            "fingerprint": servable_fingerprint(rt.models["m"]),
            "baseline": [1, 64], "buckets": [4, 20], "generation": 2}})
        mgr = LadderManager(rt, persist_path=path,
                            metrics=MetricsRegistry())
        assert mgr.restore() == {}
        assert rt.models["m"].batch_buckets == (1, 128)

    def test_pad_gauge_tracks_serving_ladder_on_skip(self, tmp_path):
        rt = _StubRuntime(buckets=(1, 64))
        clock = _FakeClock()
        reg = MetricsRegistry()
        mgr = LadderManager(rt, period_s=1e9, dwell_s=1000.0,
                            min_observations=4,
                            persist_path=str(tmp_path / "l.json"),
                            metrics=reg, clock=clock)
        for _ in range(10):
            mgr.observe_cut("m", 20)
        assert mgr.derive_now("m") == "swapped"
        for _ in range(10):
            mgr.observe_cut("m", 33)
        assert mgr.derive_now("m") == "skipped"  # dwell holds
        gauge = reg.gauge("ai4e_ladder_expected_pad_ratio", "")
        hist = mgr._hists["m"].snapshot()
        serving = rt.models["m"].batch_buckets
        expect = expected_pad_waste(serving, hist) / sum(
            s * w for s, w in hist.items())
        # The gauge reports the SERVING ladder's ratio, not the
        # candidate that never swapped in.
        assert gauge.value(model="m") == pytest.approx(expect)

    def test_staging_ring_evicted_on_ladder_swap(self):
        from ai4e_tpu.runtime import MicroBatcher
        runtime = _single_device_runtime()
        servable = _echo_servable((1, 8, 64))
        runtime.register(servable)
        runtime.warmup(parallel=False)
        batcher = MicroBatcher(runtime, metrics=MetricsRegistry(),
                               double_buffer=True, pipeline_depth=2)
        batcher._staging_buffer("echo", 64, servable)
        batcher._staging_buffer("echo", 8, servable)
        assert ("echo", 64) in batcher._staging
        # A swap retires bucket 64; the next NEW ring allocation drops
        # the stale ring instead of leaking its host buffers forever.
        prepared = runtime.prepare_buckets("echo", (1, 16))
        runtime.apply_ladder("echo", prepared)
        batcher._staging_buffer("echo", 16, servable)
        assert ("echo", 64) not in batcher._staging
        assert ("echo", 16) in batcher._staging

    def test_swap_between_cut_and_execute_pads_to_cut_time_bucket(self):
        # The second review pass's cut-vs-swap race: the bucket is
        # chosen at CUT time from one ladder snapshot, so a deriver
        # swap that shrinks the top bucket before _execute runs cannot
        # make bucket_for(n) clamp below n (IndexError mid-padding,
        # stranded futures). The pre-swap bucket's program stays
        # compiled (append-only warm set), so the batch executes fine.
        from ai4e_tpu.runtime import MicroBatcher

        async def main():
            runtime = _single_device_runtime()
            servable = _echo_servable((1, 64))
            runtime.register(servable)
            runtime.warmup(parallel=False)
            batcher = MicroBatcher(runtime, metrics=MetricsRegistry())
            loop = asyncio.get_running_loop()
            from ai4e_tpu.runtime.batcher import _Pending
            batcher._pending["echo"] = [
                _Pending(np.full((4,), i, np.float32),
                         loop.create_future())
                for i in range(40)]
            batch, bucket = batcher._take_batch("echo")
            assert (len(batch), bucket) == (40, 64)
            # The deriver swaps the ladder down BETWEEN cut and execute.
            prepared = runtime.prepare_buckets("echo", (1, 4, 8))
            runtime.apply_ladder("echo", prepared)
            await batcher._execute(loop, "echo", batch, bucket)
            results = [p.future.result() for p in batch]  # all resolved
            assert len(results) == 40
        run(main())

    def test_concurrent_persists_keep_both_models(self, tmp_path):
        # _persist is a load-modify-write of the shared ladder file;
        # without the lock two models' deriver threads could each read
        # a stale snapshot and the last writer dropped the other's
        # entry (a restart then warmed that model's factory ladder).
        import threading
        rt = _StubRuntime(buckets=(1, 64))
        rt.models["m2"] = _stub_servable(buckets=(1, 32), name="m2")
        path = str(tmp_path / "ladders.json")
        mgr = LadderManager(rt, persist_path=path,
                            metrics=MetricsRegistry(),
                            clock=_FakeClock())
        mgr._adopt("m")
        mgr._adopt("m2")
        mgr._generation["m"] = mgr._generation["m2"] = 1

        def hammer(name, bucket):
            for _ in range(25):
                mgr._persist(name, (bucket,))

        threads = [threading.Thread(target=hammer, args=("m", 20)),
                   threading.Thread(target=hammer, args=("m2", 16))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        entries = load_ladders(path)
        assert set(entries) == {"m", "m2"}

    def test_staging_ring_evicted_on_shrink_only_swap(self):
        # Third review pass: a swap that only SHRINKS the ladder never
        # allocates a new ring, so allocation-time-only eviction kept
        # the retired larger ring (pipeline_depth full-size host
        # buffers) for the process lifetime — the sweep now runs on
        # every staging-buffer fetch.
        from ai4e_tpu.runtime import MicroBatcher
        runtime = _single_device_runtime()
        servable = _echo_servable((1, 16, 64))
        runtime.register(servable)
        runtime.warmup(parallel=False)
        batcher = MicroBatcher(runtime, metrics=MetricsRegistry(),
                               double_buffer=True, pipeline_depth=2)
        batcher._staging_buffer("echo", 64, servable)
        batcher._staging_buffer("echo", 16, servable)
        prepared = runtime.prepare_buckets("echo", (1, 16))  # shrink only
        runtime.apply_ladder("echo", prepared)
        batcher._staging_buffer("echo", 16, servable)  # existing ring
        assert ("echo", 64) not in batcher._staging
        assert ("echo", 16) in batcher._staging
