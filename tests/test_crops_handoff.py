"""Crop-based pipeline handoff (runtime/handoffs.crops_handoff): the
detector ships its CROPS to the classifier's batch endpoint — the payload
shape real camera-trap ensembles use, beyond the reference's replay-the-
original-image composition (CacheConnectorUpsert.cs:144-176)."""

import asyncio
import io
import json

import numpy as np
from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.runtime.handoffs import crops_handoff


def detections(*boxes, score=0.9):
    return {"detections": [
        {"box": list(b), "score": score, "class_id": 0} for b in boxes]}


class TestCropsHandoff:
    def test_crops_match_box_contents(self):
        img = np.zeros((64, 64, 3), np.uint8)
        img[10:30, 20:40] = (200, 50, 25)  # the "animal"
        handoff = crops_handoff("/v1/next", crop_size=8)
        endpoint, body = handoff(detections((10, 20, 30, 40)), img)
        assert endpoint == "/v1/next"
        stack = np.load(io.BytesIO(body))
        assert stack.shape == (1, 8, 8, 3)
        # The crop is the colored region, not background.
        assert stack[0, :, :, 0].min() > 150
        assert stack[0, :, :, 2].max() < 60

    def test_boxes_clamped_and_degenerate_boxes_survive(self):
        img = np.full((32, 32, 3), 128, np.uint8)
        handoff = crops_handoff("/v1/next", crop_size=4)
        out = handoff(detections((-10, -5, 40, 50), (5.2, 5.8, 5.4, 5.9)),
                      img)
        assert out is not None
        stack = np.load(io.BytesIO(out[1]))
        assert stack.shape == (2, 4, 4, 3)

    def test_gating_and_limits(self):
        img = np.zeros((16, 16, 3), np.uint8)
        handoff = crops_handoff("/v1/next", crop_size=4, max_crops=2,
                                min_score=0.5)
        assert handoff({"detections": []}, img) is None
        assert handoff(detections((0, 0, 8, 8), score=0.1), img) is None
        out = handoff(detections((0, 0, 8, 8), (1, 1, 9, 9), (2, 2, 10, 10)),
                      img)
        stack = np.load(io.BytesIO(out[1]))
        assert len(stack) == 2  # max_crops cap

    def test_box_fully_outside_image_clamps_to_border_sliver(self):
        # A box entirely past the right/bottom edge must clamp to a >=1px
        # region INSIDE the image (y0/x0 clamp to dim-1, y1/x1 to >= +1),
        # never index out of bounds or produce an empty crop.
        img = np.full((32, 32, 3), 7, np.uint8)
        handoff = crops_handoff("/v1/next", crop_size=4)
        out = handoff(detections((40, 40, 50, 50)), img)
        assert out is not None
        stack = np.load(io.BytesIO(out[1]))
        assert stack.shape == (1, 4, 4, 3)
        assert (stack == 7).all()  # resized from a real in-image sliver

    def test_min_score_boundary_is_inclusive(self):
        img = np.zeros((16, 16, 3), np.uint8)
        handoff = crops_handoff("/v1/next", crop_size=4, min_score=0.5)
        # Exactly at the threshold: kept (>= semantics).
        out = handoff(detections((0, 0, 8, 8), score=0.5), img)
        assert out is not None
        assert len(np.load(io.BytesIO(out[1]))) == 1
        # Strictly below: filtered; nothing left -> None (the stage then
        # completes the task itself instead of handing off).
        assert handoff(detections((0, 0, 8, 8), score=0.49999), img) is None

    def test_max_crops_keeps_the_first_n_in_order(self):
        # Detectors emit score-ordered detections; truncation must keep
        # the FIRST max_crops (the top-scoring ones), in order.
        img = np.zeros((32, 32, 3), np.uint8)
        img[0:8, 0:8] = 10    # detection 1's region
        img[0:8, 8:16] = 20   # detection 2's region
        img[0:8, 16:24] = 30  # detection 3's region
        handoff = crops_handoff("/v1/next", crop_size=4, max_crops=2)
        out = handoff(detections((0, 0, 8, 8), (0, 8, 8, 16),
                                 (0, 16, 8, 24)), img)
        stack = np.load(io.BytesIO(out[1]))
        assert stack.shape[0] == 2
        assert int(stack[0].mean()) == 10 and int(stack[1].mean()) == 20

    def test_missing_and_empty_result_complete_the_task(self):
        # ``None`` from the handoff is the stage-completes-the-task signal
        # (runtime/worker.py) — a result with no "detections" key, an
        # empty list, or a None result must all take that path.
        img = np.zeros((8, 8, 3), np.uint8)
        handoff = crops_handoff("/v1/next", crop_size=4)
        assert handoff({}, img) is None
        assert handoff({"detections": None}, img) is None
        assert handoff(None, img) is None

    def test_float_example_scaled(self):
        img = np.full((16, 16, 3), 0.5, np.float32)
        handoff = crops_handoff("/v1/next", crop_size=4)
        _, body = handoff(detections((0, 0, 8, 8)), img)
        stack = np.load(io.BytesIO(body))
        assert stack.dtype == np.uint8
        assert 120 <= stack.mean() <= 135  # 0.5 -> ~128, not truncated to 0


class TestCropPipelineE2E:
    """Spec-driven detector→classifier-with-crops composite through the cli
    builder, parametrized over the wire: stage 1 detects (threshold 0 on
    random init → always fires), hands a crop stack to stage 2's batch
    endpoint, which completes the task with per-crop classifications. On
    the compressed wires the handoff receives the decoded RGB image back
    (example_decoder) and the classifier's batch stage converts the crop
    stack at ingestion (stack_adapter) — composite pipelines are
    wire-agnostic end to end."""

    import pytest as _pytest

    @_pytest.mark.parametrize("wire", [None, "yuv420", "dct"])
    def test_detector_crops_feed_classifier_batch_stage(self, wire):
        from ai4e_tpu.cli import build_worker
        from ai4e_tpu.config import FrameworkConfig
        from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig

        wire_kw = {"wire": wire} if wire else {}

        async def main():
            platform = LocalPlatform(PlatformConfig(retry_delay=0.05))
            worker, batcher, _tm = build_worker(FrameworkConfig(), {
                "service_name": "crops", "prefix": "v1/crops",
                "models": [
                    {"family": "detector", "name": "det", "image_size": 64,
                     "widths": [8, 8, 8], "score_threshold": 0.0,
                     "max_detections": 4, "buckets": [1],
                     "async_path": "/detect-async",
                     "pipeline_to": {
                         "endpoint": "/v1/crops/cls-batch-async",
                         "payload": "crops", "crop_size": 16,
                         "max_crops": 3}, **wire_kw},
                    {"family": "resnet", "name": "cls", "image_size": 16,
                     "stage_sizes": [1], "width": 8, "num_classes": 4,
                     "buckets": [4],
                     "batch": {"async_path": "/cls-batch-async",
                               "max_items": 8}, **wire_kw},
                ]})
            worker.service.task_manager = platform.task_manager
            worker.store = platform.store
            await batcher.start()
            svc = TestClient(TestServer(worker.service.app))
            await svc.start_server()
            base = str(svc.make_url("")).rstrip("/")
            platform.publish_async_api("/v1/public/detect",
                                       base + "/v1/crops/detect-async")
            platform.dispatchers.register("/v1/crops/cls-batch-async",
                                          base + "/v1/crops/cls-batch-async")
            gw = TestClient(TestServer(platform.gateway.app))
            await gw.start_server()
            await platform.start()
            try:
                img = np.random.default_rng(0).integers(
                    0, 256, (64, 64, 3), dtype=np.uint8)
                buf = io.BytesIO()
                np.save(buf, img)
                resp = await gw.post("/v1/public/detect", data=buf.getvalue())
                tid = (await resp.json())["TaskId"]
                r = await gw.get(f"/v1/taskmanagement/task/{tid}",
                                 params={"wait": "30"})
                final = await r.json()
                assert "completed" in final["Status"], final

                # Stage-1's detections are retrievable; the final result is
                # the classifier's per-crop batch output.
                staged = platform.store.get_result(tid, stage="det")
                assert staged is not None
                dets = json.loads(staged[0])["detections"]
                assert len(dets) >= 1
                body, _ctype = platform.store.get_result(tid)
                doc = json.loads(body)
                assert doc["count"] == min(len(dets), 3)
                assert doc["failed"] == 0, doc
                for item in doc["items"]:
                    assert "class_id" in item["result"]
            finally:
                await platform.stop()
                await batcher.stop()
                await gw.close()
                await svc.close()

        asyncio.run(main())
