"""Mesh serving plane e2e (PR 17, docs/mesh_serving.md): a worker built by
``cli.build_worker`` with ``AI4E_RUNTIME_MESH_SPEC`` serves through a
NamedSharding mesh endpoint on the CPU host-device substrate (conftest
forces 8 host devices), and the contract holds at every layer:

- **correctness**: meshed results are byte-identical to the unmeshed
  oracle, and mesh=off leaves the worker byte-identical (unwrapped);
- **introspection**: the validated layout + live health ride
  ``GET {prefix}/models``;
- **failure semantics**: a poisoned row (``AI4E_FAULT_MESH_POISON_NTHS``)
  completes the batch's other rows and redelivers ONLY its own task —
  RETRY visible in the hop ledger, exactly one client-visible completion
  per task (the chaos half of tests/test_race_regressions.py's
  interleaving proof);
- **orchestration**: distinct mesh shapes are distinct cost tiers — the
  placement walk routes a deadline-bearing request to the cheapest tier
  whose completion estimate clears the budget.
"""

import asyncio
import io
import time

import numpy as np
import pytest

from ai4e_tpu.config import FrameworkConfig
from ai4e_tpu.runtime.mesh import MeshLayout, MeshSpecError, parse_mesh_spec

DEVICES = 8  # conftest: --xla_force_host_platform_device_count=8


def _build(mesh_spec="", hop_ledger=False):
    from ai4e_tpu.cli import build_worker
    config = FrameworkConfig()
    config.runtime.mesh_spec = mesh_spec
    config.observability.hop_ledger = hop_ledger
    return build_worker(config, {
        "service_name": "w", "prefix": "v1/echo",
        "models": [{"family": "echo", "name": "echo", "size": 4,
                    "buckets": [DEVICES], "async_path": "/echo-async"}]})


# ---------------------------------------------------------------------------
# Spec grammar (stdlib-only — the same module the rig and race harness use)
# ---------------------------------------------------------------------------

class TestMeshSpecGrammar:
    def test_parse_and_describe_round_trip(self):
        layout = MeshLayout.parse("dp=2,tp=2,sp=2")
        assert (layout.dp, layout.tp, layout.sp) == (2, 2, 2)
        assert layout.size == 8
        d = layout.describe()
        assert MeshLayout.parse(d["spec"]) == layout
        assert d["data_axis_multiple"] == 2

    def test_tier_labels_elide_unit_axes(self):
        assert MeshLayout.parse("dp=8").tier_label == "mesh-dp8"
        assert MeshLayout.parse("tp=4").tier_label == "mesh-tp4"
        assert MeshLayout.parse("dp=2,tp=2").tier_label == "mesh-dp2tp2"
        assert MeshLayout().tier_label == "mesh-dp1"

    def test_off_spellings_mean_mesh_off(self):
        assert parse_mesh_spec(None) is None
        assert parse_mesh_spec("") is None
        assert parse_mesh_spec("  off ") is None
        assert parse_mesh_spec("dp=4") == MeshLayout(dp=4)

    @pytest.mark.parametrize("bad", ["dp", "dp=0", "dp=x", "ep=2",
                                     "dp=2,dp=4", ","])
    def test_bad_specs_are_named_errors(self, bad):
        with pytest.raises(MeshSpecError):
            MeshLayout.parse(bad)

    def test_validate_names_the_device_gap_and_the_cpu_substrate_fix(self):
        with pytest.raises(MeshSpecError,
                           match="xla_force_host_platform_device_count"):
            MeshLayout.parse("dp=3").validate(DEVICES)

    def test_validate_requires_even_process_split(self):
        with pytest.raises(MeshSpecError, match="split evenly"):
            MeshLayout.parse("dp=8").validate(8, process_count=3)


# ---------------------------------------------------------------------------
# The mesh endpoint on the real device path
# ---------------------------------------------------------------------------

class TestMeshEndpointE2E:
    def test_meshed_results_byte_identical_to_unmeshed_oracle(self):
        meshed, _b1, _t1 = _build("dp=8")
        plain, _b2, _t2 = _build("")
        # mesh=off is the unwrapped runtime — byte-identical worker.
        assert hasattr(meshed.runtime, "layout")
        assert not hasattr(plain.runtime, "layout")
        assert meshed.runtime.layout.tier_label == "mesh-dp8"
        assert meshed.runtime.supports_split_phases() == \
            plain.runtime.supports_split_phases()

        rng = np.random.default_rng(20260803)
        batch = rng.standard_normal((DEVICES, 4)).astype(np.float32)
        out_mesh, poisoned = meshed.runtime.run_batch_report("echo", batch)
        out_plain = plain.runtime.run_batch("echo", batch)
        assert poisoned == frozenset()
        assert np.asarray(out_mesh).tobytes() == \
            np.asarray(out_plain).tobytes()

    def test_distinct_shapes_are_distinct_tiers(self):
        worker, _b, _t = _build("dp=4,tp=2")
        desc = worker.runtime.describe()
        assert desc["tier"] == "mesh-dp4tp2"
        assert desc["devices"] == DEVICES
        assert desc["data_axis_multiple"] == 4
        assert desc["healthy"] is True

    def test_models_endpoint_exposes_the_layout(self):
        from aiohttp.test_utils import TestClient, TestServer

        async def main():
            worker, _b, _t = _build("dp=8")
            client = TestClient(TestServer(worker.service.app))
            await client.start_server()
            try:
                resp = await client.get("/v1/echo/models")
                body = await resp.json()
            finally:
                await client.close()
            entry = body["models"][0]
            assert entry["mesh"]["spec"] == "dp=8"
            assert entry["mesh"]["tier"] == "mesh-dp8"
            assert entry["mesh"]["healthy"] is True

        asyncio.run(main())

    def test_mesh_spec_and_axis_knobs_are_mutually_exclusive(self):
        config = FrameworkConfig()
        config.runtime.mesh_spec = "dp=8"
        config.runtime.tp = 2
        from ai4e_tpu.cli import build_worker
        with pytest.raises(ValueError, match="mutually exclusive"):
            build_worker(config, {"service_name": "w", "prefix": "v1/e",
                                  "models": []})

    def test_mesh_spec_must_cover_the_visible_devices(self):
        with pytest.raises(MeshSpecError,
                           match="xla_force_host_platform_device_count"):
            _build("dp=3")


class TestPartitionRules:
    def test_unmatched_params_fail_with_every_path_named(self):
        from jax.sharding import PartitionSpec as P

        from ai4e_tpu.runtime.mesh.placement import match_partition_rules
        params = {"dense": {"kernel": np.zeros((4, 4)),
                            "bias": np.zeros((4,))},
                  "gamma": np.zeros((4,))}
        with pytest.raises(ValueError) as err:
            match_partition_rules([(r".*kernel", P(None, "tp"))], params)
        # Every unmapped param named at once, not one per retry.
        assert "dense/bias" in str(err.value)
        assert "gamma" in str(err.value)

    def test_catch_all_completes_the_mapping(self):
        from jax.sharding import PartitionSpec as P

        from ai4e_tpu.runtime.mesh.placement import match_partition_rules
        params = {"dense": {"kernel": np.zeros((4, 4)),
                            "bias": np.zeros((4,))}}
        specs = match_partition_rules(
            [(r".*kernel", P(None, "tp")), (r".*", P())], params)
        assert specs["dense/kernel"] == P(None, "tp")
        assert specs["dense/bias"] == P()


# ---------------------------------------------------------------------------
# Mesh shapes as orchestration cost tiers
# ---------------------------------------------------------------------------

MESH_DP8 = "http://pool-a:9/v1/echo-mesh-dp8/run-async"
MESH_DP4TP2 = "http://pool-b:9/v1/echo-mesh-dp4tp2/run-async"
TIERS = [(MESH_DP8, 1.0), (MESH_DP4TP2, 1.0)]


class TestMeshCostTiers:
    """The placement walk prices mesh shapes by tier label — the label a
    mesh worker's route carries (``spec.tier_label``) is the substring
    the cost map keys on, so no orchestration code knows about meshes."""

    @staticmethod
    def _orch():
        from ai4e_tpu.metrics.registry import MetricsRegistry
        from ai4e_tpu.orchestration.core import (OrchestrationPolicy,
                                                 Orchestrator)
        from ai4e_tpu.resilience.health import (BackendHealth,
                                                ResiliencePolicy)
        health = BackendHealth(ResiliencePolicy(failure_threshold=2),
                               metrics=MetricsRegistry())
        # The dp=8 pool is the cheap tier (small model, commodity slice);
        # the dp=4,tp=2 pool is the expensive one (big-model slice).
        policy = OrchestrationPolicy(
            costs={"mesh-dp8": 1.0, "mesh-dp4tp2": 4.0})
        orch = Orchestrator(health, policy=policy,
                            metrics=MetricsRegistry())
        for _ in range(8):
            orch.observe(MESH_DP8, 0.8)       # cheap but slow
            orch.observe(MESH_DP4TP2, 0.01)   # expensive but fast
        return orch

    def test_tier_labels_price_the_walk(self):
        orch = self._orch()
        assert orch.cost_of(MESH_DP8) == 1.0
        assert orch.cost_of(MESH_DP4TP2) == 4.0

    def test_no_deadline_takes_the_cheapest_mesh_tier(self):
        assert self._orch().place(TIERS) == MESH_DP8

    def test_generous_deadline_stays_on_the_cheap_tier(self):
        orch = self._orch()
        assert orch.place(TIERS, deadline_at=time.time() + 5.0) == MESH_DP8

    def test_tight_deadline_routes_to_the_tier_that_clears(self):
        orch = self._orch()
        # 100 ms budget: the dp=8 tier's 800 ms estimate can never clear
        # it; the walk escalates to the expensive mesh shape that does.
        chosen = orch.place(TIERS, deadline_at=time.time() + 0.1)
        assert chosen == MESH_DP4TP2


# ---------------------------------------------------------------------------
# Poisoned-row chaos: per-task redelivery on the full async path
# ---------------------------------------------------------------------------

@pytest.mark.chaos
class TestPoisonedRowRedeliveryE2E:
    def test_poisoned_row_redelivers_only_its_task(self, monkeypatch):
        """Batch 1 gets one injected poisoned row. Every accepted task
        still completes exactly once (the poisoned one via broker
        redelivery, stamped RETRY/poisoned-row in its hop ledger); the
        batch's other rows complete in place; no whole-batch failure."""
        monkeypatch.setenv("AI4E_FAULT_MESH_POISON_NTHS", "1")
        from aiohttp.test_utils import TestClient, TestServer

        from ai4e_tpu.observability.ledger import RETRY
        from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
        from ai4e_tpu.taskstore import TaskStatus

        async def serve_app(app):
            client = TestClient(TestServer(app))
            await client.start_server()
            return client

        async def main():
            platform = LocalPlatform(PlatformConfig(retry_delay=0.05))
            worker, batcher, _tm = _build("dp=8", hop_ledger=True)
            worker.service.task_manager = platform.task_manager
            worker.store = platform.store

            # Exactly-once client-visible completions, off the store's
            # change feed (the chaos/invariants.py discipline).
            prev: dict[str, str] = {}
            completions: dict[str, int] = {}

            def _count(task):
                cur = task.canonical_status
                if (cur == TaskStatus.COMPLETED
                        and prev.get(task.task_id) != TaskStatus.COMPLETED):
                    completions[task.task_id] = (
                        completions.get(task.task_id, 0) + 1)
                prev[task.task_id] = cur

            platform.store.add_listener(_count)

            await batcher.start()
            svc = await serve_app(worker.service.app)
            base = str(svc.make_url("")).rstrip("/")
            platform.publish_async_api("/v1/pub/echo",
                                       base + "/v1/echo/echo-async")
            gw = await serve_app(platform.gateway.app)
            await platform.start()
            try:
                tids = []
                for i in range(3):
                    buf = io.BytesIO()
                    np.save(buf, np.full(4, float(i + 1), np.float32))
                    resp = await gw.post("/v1/pub/echo",
                                         data=buf.getvalue())
                    assert resp.status == 200, resp.status
                    tids.append((await resp.json())["TaskId"])

                deadline = asyncio.get_running_loop().time() + 30.0
                while asyncio.get_running_loop().time() < deadline:
                    stats = {t: platform.store.get(t).canonical_status
                             for t in tids}
                    if all(s == TaskStatus.COMPLETED
                           for s in stats.values()):
                        break
                    assert TaskStatus.FAILED not in stats.values(), (
                        f"poisoned row escalated to a task failure: "
                        f"{stats}")
                    await asyncio.sleep(0.02)
                else:
                    raise AssertionError(f"tasks never drained: {stats}")

                # Never a duplicate client-visible completion.
                assert all(completions.get(t) == 1 for t in tids), (
                    completions)
                # Exactly one task was redelivered, and its timeline says
                # why (the per-task retry the ledger makes auditable).
                retried = [t for t in tids
                           if any(e.get("e") == RETRY
                                  and e.get("r") == "poisoned-row"
                                  for e in platform.store.get_ledger(t))]
                assert len(retried) == 1, (
                    f"expected exactly one poisoned-row redelivery, "
                    f"got {retried}")
                # The mesh endpoint stayed healthy: one poisoned batch is
                # below the consecutive-degrade threshold.
                assert worker.runtime.health.healthy
            finally:
                await platform.stop()
                await batcher.stop()
                await gw.close()
                await svc.close()

        asyncio.run(main())
