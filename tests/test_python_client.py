"""Caller-side Python SDK (clients/python/ai4e_client.py) against a live
platform: submit → long-poll wait → result, sync call, failure and auth
surfaces — the caller workflow the reference documents as raw HTTP
(``README.md:24``), packaged."""

import asyncio
import importlib.util
import io
import os
import threading

import numpy as np
import pytest
from aiohttp import web

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
_spec = importlib.util.spec_from_file_location(
    "ai4e_client", os.path.join(REPO, "clients", "python", "ai4e_client.py"))
ai4e_client = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(ai4e_client)

from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig  # noqa: E402
from ai4e_tpu.runtime import (  # noqa: E402
    InferenceWorker,
    MicroBatcher,
    ModelRuntime,
    build_servable,
)


def npy_bytes(arr) -> bytes:
    buf = io.BytesIO()
    np.save(buf, arr)
    return buf.getvalue()


class _PlatformThread:
    """Full platform (gateway+store+broker+worker, echo API) on a background
    event loop, so the blocking stdlib client can be driven from the test
    thread exactly as a real caller would."""

    def __init__(self, api_keys: str | None = None):
        self.api_keys = api_keys
        self.port = None
        self._stop = None
        self._ready = threading.Event()
        self._thread = threading.Thread(target=self._run, daemon=True)

    def __enter__(self):
        self._thread.start()
        assert self._ready.wait(30), "platform failed to start"
        return self

    def __exit__(self, *exc):
        self._loop.call_soon_threadsafe(self._stop.set)
        self._thread.join(timeout=30)

    def _run(self):
        asyncio.run(self._main())

    async def _main(self):
        self._loop = asyncio.get_running_loop()
        self._stop = asyncio.Event()
        platform = LocalPlatform(PlatformConfig(retry_delay=0.05))
        if self.api_keys is not None:
            platform.gateway.set_api_keys({self.api_keys})
        # Production control planes mount the task-store HTTP surface on the
        # gateway port (cli.py build_control_plane) — mirror that so
        # client.result() hits /v1/taskstore/result like a real deployment.
        from ai4e_tpu.taskstore.http import make_app as make_taskstore_app
        make_taskstore_app(platform.store, app=platform.gateway.app)
        runtime = ModelRuntime()
        servable = build_servable("echo", name="echo", size=4, buckets=(4,))

        def failing_preprocess(body, content_type):
            arr = np.load(io.BytesIO(body))
            if arr.shape != (4,):
                raise ValueError(f"expected (4,), got {arr.shape}")
            return arr.astype(np.float32)

        servable.preprocess = failing_preprocess
        runtime.register(servable)
        runtime.warmup()
        batcher = MicroBatcher(runtime, max_wait_ms=2)
        worker = InferenceWorker("echo-svc", runtime, batcher,
                                 task_manager=platform.task_manager,
                                 prefix="v1/echo", store=platform.store)
        worker.serve_model(servable, sync_path="/echo",
                           async_path="/echo-async")
        await batcher.start()

        be = web.AppRunner(worker.service.app)
        await be.setup()
        be_site = web.TCPSite(be, "127.0.0.1", 0)
        await be_site.start()
        be_port = be.addresses[0][1]
        platform.publish_async_api(
            "/v1/echo/echo-async", f"http://127.0.0.1:{be_port}/v1/echo/echo-async")
        platform.publish_sync_api(
            "/v1/echo/echo", f"http://127.0.0.1:{be_port}/v1/echo/echo")
        gw = web.AppRunner(platform.gateway.app)
        await gw.setup()
        gw_site = web.TCPSite(gw, "127.0.0.1", 0)
        await gw_site.start()
        self.port = gw.addresses[0][1]
        await platform.start()
        self._ready.set()
        await self._stop.wait()
        await platform.stop()
        await batcher.stop()
        await gw.cleanup()
        await be.cleanup()


class TestPythonClient:
    def test_async_submit_wait_result_and_sync_call(self):
        with _PlatformThread() as pt:
            client = ai4e_client.AI4EClient(f"http://127.0.0.1:{pt.port}")
            payload = npy_bytes(np.asarray([1, 2, 3, 4], np.float32))

            task_id = client.submit("/v1/echo/echo-async", payload)
            record = client.wait(task_id, timeout=60, poll_wait=5)
            assert "completed" in record["Status"]
            assert record["TaskId"] == task_id
            assert client.result(record) == {"echo": [1.0, 2.0, 3.0, 4.0]}
            # run() = submit+wait+result in one call
            assert client.run("/v1/echo/echo-async", payload,
                              timeout=60) == {"echo": [1.0, 2.0, 3.0, 4.0]}
            # sync API through the gateway proxy
            assert client.call_sync("/v1/echo/echo", payload) == {
                "echo": [1.0, 2.0, 3.0, 4.0]}

    def test_failed_task_raises_with_record(self):
        with _PlatformThread() as pt:
            client = ai4e_client.AI4EClient(f"http://127.0.0.1:{pt.port}")
            bad = npy_bytes(np.zeros(7, np.float32))  # wrong shape
            task_id = client.submit("/v1/echo/echo-async", bad)
            with pytest.raises(ai4e_client.TaskFailed) as exc:
                client.wait(task_id, timeout=60, poll_wait=5)
            assert "failed" in exc.value.record["Status"]

    def test_subscription_key_required_and_accepted(self):
        import urllib.error

        with _PlatformThread(api_keys="sekrit") as pt:
            payload = npy_bytes(np.asarray([1, 2, 3, 4], np.float32))
            anon = ai4e_client.AI4EClient(f"http://127.0.0.1:{pt.port}")
            with pytest.raises(urllib.error.HTTPError) as exc:
                anon.submit("/v1/echo/echo-async", payload)
            assert exc.value.code == 401
            keyed = ai4e_client.AI4EClient(f"http://127.0.0.1:{pt.port}",
                                           api_key="sekrit")
            record = keyed.wait(keyed.submit("/v1/echo/echo-async", payload),
                                timeout=60, poll_wait=5)
            assert "completed" in record["Status"]


def stub_server(script):
    """Context manager: HTTP server answering POSTs from ``script`` —
    a list of (status, headers, body) consumed in order (the last entry
    repeats) — yielding (base_url, call_times). Shared by the
    backpressure-retry and gateway-rotation tests."""
    import contextlib
    import http.server
    import time as _time

    calls = []

    class Handler(http.server.BaseHTTPRequestHandler):
        def do_POST(self):
            self.rfile.read(int(self.headers.get("Content-Length", 0)))
            calls.append(_time.monotonic())
            status, headers, body = script[min(len(calls) - 1,
                                               len(script) - 1)]
            self.send_response(status)
            for k, v in headers.items():
                self.send_header(k, v)
            if body:
                self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            if body:
                self.wfile.write(body)

        def log_message(self, *a):
            pass

    @contextlib.contextmanager
    def running():
        srv = http.server.ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        threading.Thread(target=srv.serve_forever, daemon=True).start()
        try:
            yield f"http://127.0.0.1:{srv.server_address[1]}", calls
        finally:
            srv.shutdown()
            srv.server_close()

    return running()


class TestBackpressureRetry:
    _stub_server = staticmethod(stub_server)

    def test_429_retried_honoring_retry_after(self):
        """SDK transparently retries throttled requests: two 429s with
        Retry-After, then success — caller sees only the result."""
        import json as _json

        ok = (200, {"Content-Type": "application/json"},
              _json.dumps({"TaskId": "t-1"}).encode())
        throttle = (429, {"Retry-After": "1"}, b"")
        with self._stub_server([throttle, throttle, ok]) as (url, calls):
            client = ai4e_client.AI4EClient(url)
            assert client.submit("/v1/api/run", b"x") == "t-1"
            assert len(calls) == 3
            # Retry-After honored: >=1s between attempts.
            assert calls[1] - calls[0] >= 0.9
            assert calls[2] - calls[1] >= 0.9

    def test_retries_exhausted_surfaces_429(self):
        import urllib.error

        with self._stub_server([(429, {"Retry-After": "1"}, b"")]) as (url, _):
            client = ai4e_client.AI4EClient(url, retries=1)
            with pytest.raises(urllib.error.HTTPError) as err:
                client.submit("/v1/api/run", b"x")
            assert err.value.code == 429

    def test_retry_sleeps_respect_the_time_budget(self):
        """A long Retry-After must not stretch a short-budget call: the
        429 surfaces once the next sleep would cross the deadline."""
        import time as _time
        import urllib.error

        with self._stub_server([(429, {"Retry-After": "60"}, b"")]) as (url, _):
            client = ai4e_client.AI4EClient(url, timeout=2.0, retries=4)
            t0 = _time.monotonic()
            with pytest.raises(urllib.error.HTTPError) as err:
                client.submit("/v1/api/run", b"x")
            assert err.value.code == 429
            assert _time.monotonic() - t0 < 2.0  # no 60s sleep happened


OK = (200, {"Content-Type": "application/json"}, b'{"TaskId": "t1"}')
NOT_PRIMARY = (503, {"X-Not-Primary": "1", "Retry-After": "1"},
               b'{"error": "standby"}')


class TestGatewayRotation:
    """HA-pair client rotation — the store clients' replica-failover
    semantics (ADVICE r4), on the caller SDK: rotate ONLY on connection
    failure or an X-Not-Primary 503; plain backpressure never fans the
    request out to the peer."""

    def test_dead_primary_rotates_and_sticks(self):
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead = f"http://127.0.0.1:{s.getsockname()[1]}"
        s.close()  # nothing listening
        with stub_server([OK]) as (live, calls):
            client = ai4e_client.AI4EClient([dead, live], retries=1)
            assert client.submit("/v1/x/run-async", b"p") == "t1"
            assert client.gateway == live  # sticky after rotation
            assert client.submit("/v1/x/run-async", b"p") == "t1"
            assert len(calls) == 2

    def test_not_primary_503_rotates_within_one_cycle(self):
        with stub_server([NOT_PRIMARY]) as (standby, standby_calls), \
                stub_server([OK]) as (primary, primary_calls):
            client = ai4e_client.AI4EClient([standby, primary], retries=1)
            t0 = __import__("time").monotonic()
            assert client.submit("/v1/x/run-async", b"p") == "t1"
            # Rotation happened inside one pass — no Retry-After sleep.
            assert __import__("time").monotonic() - t0 < 2.0
            assert len(standby_calls) == 1 and len(primary_calls) == 1
            assert client.gateway == primary

    def test_plain_backpressure_does_not_fan_out(self):
        # A healthy-but-throttling active gateway (429 + Retry-After) must
        # NOT cause the request to also hit the peer, and ITS Retry-After
        # governs the sleep — per-replica load discipline under throttle.
        throttle = (429, {"Retry-After": "1"}, b"slow down")
        with stub_server([throttle, OK]) as (active, active_calls), \
                stub_server([OK]) as (peer, peer_calls):
            client = ai4e_client.AI4EClient([active, peer], retries=2)
            assert client.submit("/v1/x/run-async", b"p") == "t1"
            assert len(active_calls) == 2  # throttled once, then served
            assert len(peer_calls) == 0    # never fanned out
            assert active_calls[1] - active_calls[0] >= 0.9  # Retry-After

    def test_single_gateway_connection_error_raises_immediately(self):
        import socket
        import urllib.error

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead = f"http://127.0.0.1:{s.getsockname()[1]}"
        s.close()
        client = ai4e_client.AI4EClient(dead, retries=3)
        with pytest.raises(urllib.error.URLError):
            client.submit("/v1/x/run-async", b"p")

    def test_non_backpressure_error_not_retried_across_replicas(self):
        import urllib.error

        bad = (404, {"Content-Type": "application/json"},
               b'{"error": "no route"}')
        with stub_server([bad]) as (a, a_calls), \
                stub_server([OK]) as (b, b_calls):
            client = ai4e_client.AI4EClient([a, b], retries=3)
            with pytest.raises(urllib.error.HTTPError):
                client.submit("/v1/x/run-async", b"p")
            assert len(a_calls) == 1 and len(b_calls) == 0  # caller's bug

    def test_failover_window_retries_then_recovers(self):
        # Both replicas refuse during a promotion window (dead primary +
        # not-yet-promoted standby), then the standby serves: the client
        # rides its short Retry-After through the window.
        import socket

        s = socket.socket()
        s.bind(("127.0.0.1", 0))
        dead = f"http://127.0.0.1:{s.getsockname()[1]}"
        s.close()
        with stub_server([NOT_PRIMARY, OK]) as (standby, calls):
            client = ai4e_client.AI4EClient([dead, standby], retries=3,
                                            retry_backoff=0.1)
            assert client.submit("/v1/x/run-async", b"p") == "t1"
            assert len(calls) == 2  # one refusal, then promoted + served
