"""Dark-fleet chaos acceptance for deadline-aware orchestration
(``ai4e_tpu/orchestration/``, docs/orchestration.md):

- **the acceptance scenario** — a mixed fleet (3 fast TPU-class backends
  at cost 3, one slow CPU fallback at cost 1) behind one async route on
  a 2-shard store, seeded background fault noise, and 1 of the 3
  TPU-class backends BLACKED OUT for the middle third of the run (30% of
  that tier's capacity dark). The bar: interactive goodput
  (within-deadline completions) holds within 15% of a fault-free
  baseline run of the identical seeded workload, background traffic
  rides the cheap tier (reroute) or sheds, and the InvariantChecker is
  clean — 0 lost, 0 duplicate completions — globally AND per shard;

- **the combined scenario** — ``kill_shard_primary`` lands DURING a
  dark-backend brownout (ladder at ``shed_background``): the shard
  failover's fencing epoch bumps, orchestration keeps placing around the
  dark backend, background stays refused with brownout provenance,
  interactive completes, and once darkness lifts the ladder steps back
  down — shard failover and the degradation ladder compose.

Both replay on the fixed ``AI4E_CHAOS_SEED`` CI pins (chaos-smoke job);
verified locally across seeds 1, 2, 3, 7 and 42.
"""

import asyncio
import os
import time

import pytest
from aiohttp import web
from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.chaos import (FaultInjector, InvariantChecker,
                            RestartableBackend, wrap_platform_http,
                            wrap_publish_duplicates)
from ai4e_tpu.chaos.harness import kill_shard_primary
from ai4e_tpu.metrics import MetricsRegistry
from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
from ai4e_tpu.taskstore import TaskStatus

SEED = int(os.environ.get("AI4E_CHAOS_SEED", "20260803"))

INTERACTIVE_DEADLINE_MS = 2000.0
BACKGROUND_DEADLINE_MS = 30000.0
# Slow tier: strictly slower than the interactive budget, so the
# estimator can NEVER clear it for interactive work (the tier split is
# deterministic: interactive → TPU-class, background → cheap CPU).
CPU_LATENCY_S = 2.5


def run(coro):
    return asyncio.run(coro)


async def serve(app):
    client = TestClient(TestServer(app))
    await client.start_server()
    return client


def _platform(tmp_path=None, replicas=0, **extra):
    return LocalPlatform(PlatformConfig(
        orchestration=True, admission=True, resilience=True,
        task_shards=2,
        journal_path=(str(tmp_path / "shards") if tmp_path else None),
        task_shard_replicas=replicas,
        retry_delay=0.01,
        lease_seconds=2.0,
        resilience_retry_base_s=0.001,
        resilience_failure_threshold=3,
        resilience_recovery_seconds=0.2,
        **extra), metrics=MetricsRegistry())


#: The fast tier serves through a mesh endpoint (PR 17): its tier label
#: rides the delivery route as a URI substring, which is exactly what the
#: orchestrator's cost map keys on (docs/mesh_serving.md#cost-tiers).
MESH_ROUTE = "/v1/be/mesh-dp2tp2/x"


def _completing_app(platform, latency_s: float = 0.0,
                    route: str = "/v1/be/x") -> web.Application:
    """A worker that adopts (``running``) then completes tasks, both via
    conditional writes — the service-shell discipline an at-least-once
    transport requires. Adoption matters here: a slow tier's in-service
    tasks must leave the ``created`` set, or they'd read as edge backlog
    and trip the admission feasibility shed on queue state that is
    actually in-flight work."""
    async def handler(request):
        tid = request.headers["taskId"]
        body = await request.read()
        platform.store.update_status_if(tid, "created", "running",
                                        TaskStatus.RUNNING)
        if latency_s:
            await asyncio.sleep(latency_s)
        platform.store.update_status_if(
            tid, "running", f"completed - scored {len(body)}",
            TaskStatus.COMPLETED)
        return web.Response(text="ok")

    app = web.Application()
    app.router.add_post(route, handler)
    return app


async def _mixed_fleet(platform):
    """3 fast mesh-tier backends (replicas of one dp=2,tp=2 serving mesh,
    so one cost tier) + 1 slow CPU-class fallback. Loopback hosts carry
    no tier names, so the mesh tier's tag rides its route path — the
    same place a real mesh worker's tier label lives."""
    tpus = []
    for _ in range(3):
        be = await RestartableBackend(
            _completing_app(platform, route=MESH_ROUTE)).start()
        tpus.append(be)
    cpu = await RestartableBackend(
        _completing_app(platform, latency_s=CPU_LATENCY_S)).start()
    uris = [f"{be.url}{MESH_ROUTE}" for be in tpus] + [f"{cpu.url}/v1/be/x"]
    return tpus, cpu, uris


async def _warm_drain(gw, checker, n=30, timeout=30.0):
    """Establish the admission drain-rate estimator before the measured
    workload (no-deadline default-class tasks — the bench's ramp
    philosophy): a cold estimator makes the edge's deadline-feasibility
    shed refuse deadline traffic on a backlog/rate guess built from
    nothing. Identical in every run, so comparisons stay apples-to-apples."""
    ids = []
    for _ in range(n):
        resp = await gw.post("/v1/pub/x", data=b"warm")
        assert resp.status == 200, resp.status
        tid = (await resp.json())["TaskId"]
        checker.note_accepted(tid)
        ids.append(tid)
    deadline = asyncio.get_running_loop().time() + timeout
    while asyncio.get_running_loop().time() < deadline:
        if all(t in checker.terminal for t in ids):
            return
        await asyncio.sleep(0.05)
    raise AssertionError("drain warm-up never completed")


class _GoodputMeter:
    """Per-priority within-deadline completion counts, measured off the
    store's change feed exactly like admission's goodput scorer."""

    def __init__(self, store):
        self.in_deadline = {0: 0, 2: 0}
        self.late = {0: 0, 2: 0}
        store.add_listener(self._on_change)

    def _on_change(self, task):
        if task.canonical_status != TaskStatus.COMPLETED:
            return
        pri = getattr(task, "priority", 1)
        if pri not in self.in_deadline:
            return
        deadline_at = getattr(task, "deadline_at", 0.0)
        if deadline_at and time.time() <= deadline_at:
            self.in_deadline[pri] += 1
        else:
            self.late[pri] += 1


async def _drive_dark_fleet(dark: bool, tmp_path=None) -> dict:
    """One seeded run of the mixed-fleet workload; ``dark`` blacks out
    tpu[0] for the middle third. Returns the scorecard."""
    platform = _platform()
    tpus, cpu, uris = await _mixed_fleet(platform)
    # One substring prices the whole mesh tier (all three replicas);
    # the CPU fallback is priced by its port.
    platform.orchestration.policy.costs = {
        "mesh-dp2tp2": 3.0, f":{cpu.port}": 1.0}
    platform.publish_async_api("/v1/pub/x", [(u, 1.0) for u in uris])

    checker = InvariantChecker(
        shard_of=platform.store.shard_for).attach(platform.store)
    meter = _GoodputMeter(platform.store)

    injector = FaultInjector(seed=SEED)
    injector.add_rule(error_rate=0.08, error_status=500)
    injector.add_rule(backend="/v1/be/", duplicate_rate=0.05)
    wrap_platform_http(platform, injector)
    wrap_publish_duplicates(platform, injector)

    # Pre-teach the estimator the tiers' shapes so the first interactive
    # burst doesn't explore the slow tier cold (the sketches keep
    # re-learning from live RTTs for the rest of the run).
    for u in uris[:3]:
        for _ in range(8):
            platform.orchestration.observe(u, 0.02)
    for _ in range(8):
        platform.orchestration.observe(uris[3], CPU_LATENCY_S)

    gw = await serve(platform.gateway.app)
    await platform.start()
    accepted = {0: 0, 2: 0}
    try:
        await _warm_drain(gw, checker)

        async def accept(n_interactive, n_background):
            for i in range(max(n_interactive, n_background)):
                batch = []
                if i < n_interactive:
                    batch.append(("interactive", INTERACTIVE_DEADLINE_MS, 0))
                if i < n_background:
                    batch.append(("background", BACKGROUND_DEADLINE_MS, 2))
                for name, budget, pri in batch:
                    # The platform's client contract: a 429 carries
                    # Retry-After — back off and re-issue. Interactive
                    # retries until admitted (a SUSTAINED refusal of the
                    # top class would time the test out and fail it);
                    # background takes the shed (that's the brownout
                    # design) after one retry.
                    for attempt in range(60):
                        resp = await gw.post(
                            "/v1/pub/x", data=b"payload",
                            headers={"X-Priority": name,
                                     "X-Deadline-Ms": str(int(budget))})
                        if resp.status == 200:
                            checker.note_accepted(
                                (await resp.json())["TaskId"])
                            accepted[pri] += 1
                            break
                        assert resp.status == 429, (name, resp.status)
                        if pri == 2 and attempt >= 1:
                            break  # background shed — allowed
                        await asyncio.sleep(0.1)
                    else:
                        raise AssertionError(
                            f"{name} refused for the whole retry budget")
                await asyncio.sleep(0.04)

        # First third: everything up.
        await accept(14, 6)
        # Middle third: 1 of 3 TPU-class backends dark (30% of the tier).
        rule = injector.blackout(f":{tpus[0].port}") if dark else None
        await accept(14, 6)
        # Final third: darkness lifts.
        if rule is not None:
            injector.lift(rule)
        await accept(14, 6)

        # Drain: every accepted task terminal.
        deadline = asyncio.get_running_loop().time() + 40.0
        while asyncio.get_running_loop().time() < deadline:
            if all(t in checker.terminal for t in checker.accepted):
                break
            await asyncio.sleep(0.05)

        checker.assert_ok()
        for shard in range(2):
            checker.assert_shard_ok(shard)

        placements = platform.metrics.counter(
            "ai4e_orchestration_placements_total", "")
        cpu_host = f"127.0.0.1:{cpu.port}"
        return {
            "accepted": dict(accepted),
            "in_deadline": dict(meter.in_deadline),
            "late": dict(meter.late),
            "by_shard": checker.by_shard(),
            "injected": injector.counts(),
            "cpu_placements": sum(
                v for _, _, labels, v in placements.collect()
                if labels.get("backend") == cpu_host),
            "brownout_refusals": sum(
                v for *_, v in platform.metrics.counter(
                    "ai4e_orchestration_brownout_refusals_total",
                    "").collect()),
            "dark_breaker_opened": platform.metrics.counter(
                "ai4e_resilience_transitions_total", "").value(
                backend=f"127.0.0.1:{tpus[0].port}", state="open"),
        }
    finally:
        await platform.stop()
        await gw.close()
        for be in tpus:
            await be.kill()
        await cpu.kill()


@pytest.mark.chaos
class TestDarkFleetAcceptance:
    def test_interactive_goodput_holds_while_background_reroutes(self):
        async def main():
            baseline = await _drive_dark_fleet(dark=False)
            dark = await _drive_dark_fleet(dark=True)

            # Same seeded workload accepted in both runs (background may
            # shed under brownout, interactive must not).
            assert dark["accepted"][0] == baseline["accepted"][0] == 42

            # THE acceptance bar: interactive goodput within 15% of the
            # fault-free baseline despite 30% of the fast tier dark for
            # the middle third.
            assert baseline["in_deadline"][0] > 0
            ratio = dark["in_deadline"][0] / baseline["in_deadline"][0]
            assert ratio >= 0.85, (
                f"interactive goodput collapsed under darkness: "
                f"{dark['in_deadline'][0]} vs baseline "
                f"{baseline['in_deadline'][0]} ({ratio:.2f})")

            # Background traffic rode the cheap tier (best-effort
            # reroute) or shed — it must not have starved interactive.
            assert (dark["cpu_placements"] > 0
                    or dark["brownout_refusals"] > 0)

            # The darkness was real: deliveries actually hit the
            # blacked-out backend (injected connection refusals) —
            # often enough to trip its breaker, but with the canary-
            # preserving weighted pick the per-backend hit count is
            # seed/timing-dependent, so the refusals are the invariant
            # and the breaker opening is corroboration, not a must.
            assert (dark["injected"].get("connect_error", 0) > 0
                    or dark["dark_breaker_opened"] >= 1)
            assert dark["injected"].get("error", 0) > 0

            # Per-shard verdicts came from both shards (the ring spread
            # the keyspace).
            assert set(dark["by_shard"]) == {0, 1}
            for shard, stats in dark["by_shard"].items():
                assert stats["terminal"] == stats["accepted"], (shard, stats)
                assert stats["duplicates"] == 0, (shard, stats)

        run(main())


@pytest.mark.chaos
class TestShardFailoverDuringBrownout:
    def test_kill_shard_primary_composes_with_the_ladder(self, tmp_path):
        async def main():
            platform = _platform(tmp_path=tmp_path, replicas=1,
                                 orchestration_ladder_hold_s=0.3)
            tpus, cpu, uris = await _mixed_fleet(platform)
            platform.orchestration.policy.costs = {
                "mesh-dp2tp2": 3.0, f":{cpu.port}": 1.0}
            platform.publish_async_api("/v1/pub/x",
                                       [(u, 1.0) for u in uris])
            checker = InvariantChecker(
                shard_of=platform.store.shard_for).attach(platform.store)

            injector = FaultInjector(seed=SEED)
            injector.add_rule(error_rate=0.08, error_status=500)
            wrap_platform_http(platform, injector)

            gw = await serve(platform.gateway.app)
            await platform.start()
            try:
                await _warm_drain(gw, checker)

                async def accept(n, priority, budget_ms,
                                 expect_admitted=True):
                    admitted = 0
                    for _ in range(n):
                        resp = await gw.post(
                            "/v1/pub/x", data=b"p",
                            headers={"X-Priority": priority,
                                     "X-Deadline-Ms": str(int(budget_ms))})
                        if resp.status == 200:
                            checker.note_accepted(
                                (await resp.json())["TaskId"])
                            admitted += 1
                        elif expect_admitted:
                            raise AssertionError(
                                (priority, resp.status,
                                 resp.headers.get("X-Shed-Reason")))
                        else:
                            assert "brownout" in resp.headers.get(
                                "X-Shed-Reason", "")
                        await asyncio.sleep(0.01)
                    return admitted

                await accept(8, "interactive", INTERACTIVE_DEADLINE_MS)
                await accept(4, "background", BACKGROUND_DEADLINE_MS)

                # Dark backend + forced brownout: drive the ladder to
                # shed_background on real miss evidence at its real
                # clock (hold_s is config-scaled in the ladder; feed a
                # dense miss burst the way a miss storm would).
                rule = injector.blackout(f":{tpus[0].port}")
                ladder = platform.orchestration.ladder
                t0 = time.monotonic()
                while (ladder.level < 2
                       and time.monotonic() - t0 < 30.0):
                    ladder.note(miss=True)
                    await asyncio.sleep(0.005)
                assert ladder.level >= 2, "ladder never browned out"

                # SIGKILL one shard primary MID-brownout.
                epoch_before = platform.store.groups[0].epoch
                kill_shard_primary(platform, 0)

                # Background is refused with brownout provenance while
                # interactive keeps flowing through the failover AND
                # around the dark backend.
                admitted_bg = await accept(4, "background",
                                           BACKGROUND_DEADLINE_MS,
                                           expect_admitted=False)
                assert admitted_bg == 0
                await accept(8, "interactive", INTERACTIVE_DEADLINE_MS)

                # The killed shard promoted: epoch strictly above the
                # corpse's, the OTHER shard untouched.
                assert platform.store.groups[0].epoch > epoch_before

                # Lift the darkness; good outcomes step the ladder down.
                injector.lift(rule)
                t0 = time.monotonic()
                while ladder.level > 0 and time.monotonic() - t0 < 30.0:
                    ladder.note(miss=False)
                    await asyncio.sleep(0.005)
                assert ladder.level == 0, (
                    "ladder wedged at brownout after recovery")

                # Drain the brownout-era backlog first (keeps the drain
                # estimator honest for the readmission probe below).
                deadline = asyncio.get_running_loop().time() + 40.0
                while asyncio.get_running_loop().time() < deadline:
                    if all(t in checker.terminal
                           for t in checker.accepted):
                        break
                    await asyncio.sleep(0.05)
                # Background is admitted again end-to-end.
                await accept(4, "background", BACKGROUND_DEADLINE_MS)
                deadline = asyncio.get_running_loop().time() + 40.0
                while asyncio.get_running_loop().time() < deadline:
                    if all(t in checker.terminal
                           for t in checker.accepted):
                        break
                    await asyncio.sleep(0.05)
                checker.assert_ok()
                for shard in range(2):
                    checker.assert_shard_ok(shard)
                # Every interactive acceptance completed (none lost to
                # the failover window or the dark backend).
                summary = checker.summary()
                assert summary["terminal"] == summary["accepted"]
            finally:
                await platform.stop()
                await gw.close()
                for be in tpus:
                    await be.kill()
                await cpu.kill()

        run(main())
