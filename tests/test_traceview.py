"""The trace viewer (`python -m ai4e_tpu trace`) — the App Insights
end-to-end transaction view rendered offline from the JSONL span log.

Spans are generated through the REAL Tracer + JsonlExporter (not
hand-written dicts), so a change to the span wire format that breaks the
viewer breaks here first.
"""

import contextlib
import subprocess
import sys
import time
from pathlib import Path

from ai4e_tpu.observability.tracing import JsonlExporter, Tracer
from ai4e_tpu.observability.traceview import (load_spans, render_list,
                                              render_trace, select_traces)

REPO = str(Path(__file__).resolve().parent.parent)


def _emit_pipeline_trace(path, task_id="task-123"):
    """gateway → dispatch → infer (error) nested under one trace, plus an
    unrelated second trace — the shape a pipelined request produces."""
    tracer = Tracer("gateway", exporter=JsonlExporter(str(path)))
    with tracer.span("create_task", task_id=task_id):
        time.sleep(0.002)
        dispatch_tracer = Tracer("control-plane",
                                 exporter=tracer.exporter)
        with dispatch_tracer.span("dispatch", task_id=task_id):
            worker = Tracer("worker", exporter=tracer.exporter)
            with contextlib.suppress(RuntimeError):
                with worker.span("infer", task_id=task_id, model="unet"):
                    raise RuntimeError("device poisoned")
    other = Tracer("gateway", exporter=tracer.exporter)
    with other.span("healthcheck"):
        pass
    tracer.exporter.close()


class TestTraceView:
    def test_select_by_task_returns_whole_trace(self, tmp_path):
        log = tmp_path / "spans.jsonl"
        _emit_pipeline_trace(log)
        spans = load_spans(str(log))
        assert len(spans) == 4
        picked = select_traces(spans, task_id="task-123")
        assert len(picked) == 3  # the healthcheck trace is excluded
        assert len({s["trace_id"] for s in picked}) == 1

    def test_render_tree_shape_and_error(self, tmp_path):
        log = tmp_path / "spans.jsonl"
        _emit_pipeline_trace(log)
        text = render_trace(select_traces(load_spans(str(log)),
                                          task_id="task-123"))
        lines = text.splitlines()
        assert lines[0].startswith("trace ")
        assert "3 spans" in lines[0]
        assert "task task-123" in lines[0]
        assert "1 ERROR" in lines[0]
        # Nesting: create_task roots, dispatch under it, infer under that.
        assert "└─ create_task [gateway]" in lines[1]
        assert "└─ dispatch [control-plane]" in lines[2]
        assert lines[2].startswith("   ")
        assert "└─ infer [worker]" in lines[3]
        assert "ERROR: RuntimeError: device poisoned" in lines[3]
        assert "model=unet" in lines[3]

    def test_orphan_span_roots_and_garbage_lines_skipped(self, tmp_path):
        log = tmp_path / "spans.jsonl"
        _emit_pipeline_trace(log)
        with open(log, "a") as fh:
            fh.write("{truncated mid-wri\n")
            fh.write('{"trace_id": "t-orphan", "span_id": "s1", '
                     '"parent_id": "missing", "name": "late", '
                     '"service": "w", "start": 1.0, "duration": 0.5}\n')
        spans = load_spans(str(log))
        text = render_trace(select_traces(spans, trace_id="t-orphan"))
        assert "└─ late [w]" in text  # orphan renders as a root

    def test_list_summarizes_most_recent_first(self, tmp_path):
        log = tmp_path / "spans.jsonl"
        _emit_pipeline_trace(log)
        listing = render_list(load_spans(str(log)))
        lines = listing.splitlines()
        assert len(lines) == 2
        # healthcheck started last → listed first.
        assert "healthcheck" in lines[0]
        assert "create_task" in lines[1] and "task task-123" in lines[1]

    def test_cli_verb_renders_without_jax(self, tmp_path):
        log = tmp_path / "spans.jsonl"
        _emit_pipeline_trace(log)
        out = subprocess.run(
            [sys.executable, "-m", "ai4e_tpu", "trace",
             "--export", str(log), "--task-id", "task-123"],
            capture_output=True, text=True, timeout=60,
            cwd=REPO)
        assert out.returncode == 0, out.stderr
        assert "└─ infer [worker]" in out.stdout
        assert "ERROR" in out.stdout
