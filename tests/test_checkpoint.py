"""Checkpoint/resume: params round trip, rolling manager, trainer resume."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ai4e_tpu.checkpoint import (
    CheckpointManager,
    load_params,
    resume_trainer,
    save_params,
    save_trainer,
)


def tiny_params():
    return {
        "dense": {"kernel": jnp.arange(12, dtype=jnp.float32).reshape(3, 4),
                  "bias": jnp.ones((4,), jnp.float32)},
        "scale": jnp.asarray(2.5, jnp.float32),
    }


def trees_equal(a, b):
    return all(jax.tree.leaves(
        jax.tree.map(lambda x, y: bool(np.allclose(x, y)), a, b)))


class TestParamsRoundTrip:
    def test_save_load(self, tmp_path):
        params = tiny_params()
        path = str(tmp_path / "ckpt")
        save_params(path, params)
        restored = load_params(path, like=params)
        assert trees_equal(params, restored)

    def test_load_without_template(self, tmp_path):
        params = tiny_params()
        path = str(tmp_path / "ckpt")
        save_params(path, params)
        restored = load_params(path)
        assert np.allclose(restored["dense"]["kernel"],
                           np.asarray(params["dense"]["kernel"]))

    def test_save_overwrites(self, tmp_path):
        path = str(tmp_path / "ckpt")
        save_params(path, {"w": jnp.zeros(3)})
        save_params(path, {"w": jnp.ones(3)})
        restored = load_params(path)
        assert np.allclose(restored["w"], 1.0)


class TestManager:
    def test_rolling_retention_and_latest(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), max_to_keep=2)
        params = tiny_params()
        for step in (1, 2, 3):
            assert mgr.save(step, params)
        mgr.wait()
        assert mgr.latest_step() == 3
        restored = mgr.restore(params)
        assert restored["step"] == 3
        assert trees_equal(restored["params"], params)
        mgr.close()

    def test_save_interval_policy(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path), save_interval_steps=5)
        params = tiny_params()
        assert mgr.save(0, params)
        assert not mgr.save(1, params)   # within interval → skipped
        assert mgr.save(5, params)
        mgr.close()

    def test_extra_metadata_round_trip(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        params = tiny_params()
        assert mgr.save(4, params, extra={"lr": 0.1, "epoch": 2})
        mgr.wait()
        restored = mgr.restore(params)
        assert restored["extra"] == {"lr": 0.1, "epoch": 2}
        mgr.close()

    def test_restore_empty_raises(self, tmp_path):
        mgr = CheckpointManager(str(tmp_path))
        with pytest.raises(FileNotFoundError):
            mgr.restore(tiny_params())
        mgr.close()


class TestTrainerResume:
    def test_resume_restores_params_opt_state_step(self, tmp_path):
        from ai4e_tpu.models import create_vit
        from ai4e_tpu.parallel import MeshSpec, make_mesh
        from ai4e_tpu.train import Trainer, cross_entropy_loss

        mesh = make_mesh(MeshSpec(dp=1), devices=jax.devices("cpu")[:1])
        model, params = create_vit(image_size=16, patch=8, dim=32, depth=1,
                                   heads=2, num_classes=4)
        images = np.random.default_rng(0).uniform(
            size=(2, 16, 16, 3)).astype(np.float32)
        labels = np.asarray([0, 1], np.int32)

        with mesh:
            trainer = Trainer(model.apply, params, mesh,
                              loss_fn=cross_entropy_loss)
            trainer.train_step(images, labels)
            mgr = CheckpointManager(str(tmp_path))
            assert save_trainer(mgr, trainer, step=7)
            mgr.wait()

            # train_step donates the old param buffers, so the fresh trainer
            # needs its own init tree (same shapes; restore overwrites values)
            _, params2 = create_vit(image_size=16, patch=8, dim=32, depth=1,
                                    heads=2, num_classes=4)
            fresh = Trainer(model.apply, params2, mesh,
                            loss_fn=cross_entropy_loss)
            step = resume_trainer(mgr, fresh)
            assert step == 7
            assert trees_equal(fresh.params, trainer.params)
            # resumed trainer can keep stepping
            loss = fresh.train_step(images, labels)
            assert np.isfinite(loss)
            mgr.close()

    def test_resume_with_no_checkpoint_returns_zero(self, tmp_path):
        from ai4e_tpu.models import create_vit
        from ai4e_tpu.parallel import MeshSpec, make_mesh
        from ai4e_tpu.train import Trainer

        mesh = make_mesh(MeshSpec(dp=1), devices=jax.devices("cpu")[:1])
        model, params = create_vit(image_size=16, patch=8, dim=32, depth=1,
                                   heads=2, num_classes=4)
        with mesh:
            trainer = Trainer(model.apply, params, mesh)
            mgr = CheckpointManager(str(tmp_path))
            assert resume_trainer(mgr, trainer) == 0
            mgr.close()
