"""Chrome-trace/Perfetto timeline export (observability/timeline.py):
builder invariants (valid trace-event JSON, lane packing, phase slices,
chaos instants, vitals counters) and the `timeline` CLI over a rig
artifact directory. JAX-free."""

from __future__ import annotations

import json

import pytest

from ai4e_tpu.observability.timeline import (build_chrome_trace,
                                             build_from_rig_dir)

T0 = 1000.0


def _ledger(offset: float, complete: bool = True) -> list[dict]:
    evs = [
        {"e": "admitted", "h": "gateway", "t": T0 + offset,
         "r": "/v1/echo/run-async"},
        {"e": "published", "h": "gateway", "t": T0 + offset + 0.001},
        {"e": "popped", "h": "dispatcher", "t": T0 + offset + 0.01},
        {"e": "delivered", "h": "dispatcher", "t": T0 + offset + 0.02,
         "r": "127.0.0.1:8081"},
        {"e": "execute", "h": "worker", "t": T0 + offset + 0.02,
         "ms": 5.0},
    ]
    if complete:
        evs.append({"e": "completed", "h": "store",
                    "t": T0 + offset + 0.03, "r": "completed"})
    return evs


class TestBuilder:
    def test_document_shape_and_json_serializable(self):
        doc = build_chrome_trace(
            {"t1": _ledger(0.0), "t2": _ledger(0.005)},
            chaos=[{"verb": "kill_gateway", "t": T0 + 0.015,
                    "gateway": 1, "ok": True}],
            vitals={"gateway0": [{"t": T0, "lag_s": 0.002,
                                  "rss_bytes": 1048576, "fds": 9,
                                  "cpu_s": 1.0, "gc_pause_s": 0.0}]},
            loadgen_samples={"loadgen0": [{"t": T0 + 0.01,
                                           "accepted": 2,
                                           "terminal": 1}]})
        # Loadable: valid JSON, ints for pid/tid, ts >= 0 everywhere.
        json.dumps(doc)
        assert doc["displayTimeUnit"] == "ms"
        for ev in doc["traceEvents"]:
            assert isinstance(ev["pid"], int)
            assert isinstance(ev["tid"], int)
            if ev["ph"] != "M":
                assert ev["ts"] >= 0
        assert doc["otherData"]["tasks"] == 2
        assert doc["otherData"]["hops"] == ["dispatcher", "gateway",
                                            "store", "worker"]

    def test_task_slices_and_lane_packing(self):
        # Two OVERLAPPING tasks must land in different lanes; a third
        # starting after both end reuses lane 1.
        doc = build_chrome_trace({"a": _ledger(0.0), "b": _ledger(0.01),
                                  "c": _ledger(10.0)})
        slices = {ev["args"]["task_id"]: ev for ev in doc["traceEvents"]
                  if ev["ph"] == "X" and "task_id" in ev.get("args", {})
                  and ev["name"] in ("completed", "in-flight")}
        assert slices["a"]["tid"] != slices["b"]["tid"]
        assert slices["c"]["tid"] == slices["a"]["tid"]
        # The slice spans first event -> last (completed), in µs.
        assert slices["a"]["dur"] == pytest.approx(0.03 * 1e6, rel=1e-3)

    def test_phase_events_become_duration_slices(self):
        doc = build_chrome_trace({"a": _ledger(0.0)})
        phases = [ev for ev in doc["traceEvents"]
                  if ev["ph"] == "X" and ev["name"] == "execute"]
        assert len(phases) == 1
        assert phases[0]["dur"] == 5000.0  # 5 ms in µs

    def test_chaos_verbs_are_global_instants(self):
        doc = build_chrome_trace(
            {"a": _ledger(0.0)},
            chaos=[{"verb": "move_slot", "t": T0 + 1.0, "slot": 3,
                    "src": 0, "dest": 1, "ok": True},
                   {"verb": "never_fired"}])  # no t -> skipped
        instants = [ev for ev in doc["traceEvents"]
                    if ev["ph"] == "i" and ev.get("s") == "g"]
        assert len(instants) == 1
        assert instants[0]["name"] == "move_slot"
        assert instants[0]["args"]["slot"] == 3

    def test_vitals_and_loadgen_counter_tracks(self):
        doc = build_chrome_trace(
            {}, vitals={"worker0.0": [
                {"t": T0, "lag_s": 0.3, "rss_bytes": 2 * 1048576},
                {"t": T0 + 1, "rss_bytes": -1.0}]},  # dead read skipped
            loadgen_samples={"loadgen1": [{"t": T0, "accepted": 5,
                                           "terminal": 2}]})
        counters = [ev for ev in doc["traceEvents"] if ev["ph"] == "C"]
        lag = [c for c in counters if c["name"] == "loop_lag_ms"]
        assert lag and lag[0]["args"]["lag"] == 300.0
        rss = [c for c in counters if c["name"] == "rss_mb"]
        assert len(rss) == 1  # the -1 sample contributed nothing
        tasks = [c for c in counters if c["name"] == "tasks"]
        assert tasks[0]["args"] == {"accepted": 5, "terminal": 2}
        names = {ev["args"]["name"] for ev in doc["traceEvents"]
                 if ev["ph"] == "M"}
        assert {"proc:worker0.0", "proc:loadgen1"} <= names

    def test_empty_inputs_produce_a_loadable_document(self):
        doc = build_chrome_trace({})
        json.dumps(doc)
        assert doc["otherData"]["tasks"] == 0


class TestCliRoundTrip:
    def _rig_dir(self, tmp_path) -> str:
        (tmp_path / "rig.json").write_text(json.dumps({
            "chaos": [{"verb": "kill_gateway", "t": T0 + 0.5,
                       "ok": True}],
            "verdict": {"windows": [
                {"loadgen": 0, "window": {},
                 "samples": [{"t": T0, "accepted": 1, "terminal": 0}]}]},
        }))
        (tmp_path / "ledgers.json").write_text(json.dumps(
            {"Ledgers": {"t1": _ledger(0.0)}}))
        (tmp_path / "vitals.json").write_text(json.dumps(
            {"gateway0": [{"t": T0, "lag_s": 0.001,
                           "rss_bytes": 1048576}]}))
        return str(tmp_path)

    def test_build_from_rig_dir(self, tmp_path):
        doc = build_from_rig_dir(self._rig_dir(tmp_path))
        assert doc["otherData"]["tasks"] == 1
        assert any(ev["name"] == "kill_gateway"
                   for ev in doc["traceEvents"])
        assert any(ev["ph"] == "C" and ev["name"] == "tasks"
                   for ev in doc["traceEvents"])

    def test_timeline_cli(self, tmp_path, capsys):
        from ai4e_tpu.cli import main as cli_main
        rig_dir = self._rig_dir(tmp_path)
        cli_main(["timeline", "--rig-dir", rig_dir])
        out = capsys.readouterr().out
        assert "timeline.json" in out and "perfetto" in out.lower()
        doc = json.loads((tmp_path / "timeline.json").read_text())
        assert doc["otherData"]["tasks"] == 1

    def test_missing_pieces_still_export(self, tmp_path):
        # Only rig.json (a chaos-only run, observability swept nothing):
        # the export must still produce a loadable file.
        (tmp_path / "rig.json").write_text(json.dumps(
            {"chaos": [], "verdict": {"windows": []}}))
        doc = build_from_rig_dir(str(tmp_path))
        json.dumps(doc)
        assert doc["otherData"]["tasks"] == 0
