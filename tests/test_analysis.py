"""ai4e-lint tests (docs/analysis.md).

Three layers:

- per-rule fixtures: at least one true positive, one near-miss negative,
  and one suppression case for each of AIL001-AIL006;
- framework semantics: noqa parsing, baseline matching/justification
  enforcement, fingerprint stability under line moves, CLI exit codes;
- the whole-repo smoke test: ``ai4e_tpu/`` must be clean modulo the
  checked-in baseline — the same gate CI runs;

plus behavioral regression tests for the real defects the analyzer
surfaced and this PR fixed (terminal-status clobbers on the push/expired/
cache paths, the dropped dead-letter task handles, span metrics leaking
into DEFAULT_REGISTRY, the rejected AI4E_FEED_* namespace).
"""

import asyncio
import os
import textwrap

import pytest

from ai4e_tpu.analysis import Analyzer, Baseline, BaselineError
from ai4e_tpu.analysis.rules import ALL_RULES
from ai4e_tpu.analysis.rules.blocking import BlockingCallInAsync
from ai4e_tpu.analysis.rules.config_drift import ConfigDrift
from ai4e_tpu.analysis.rules.fire_and_forget import FireAndForgetTask
from ai4e_tpu.analysis.rules.registry_leak import MetricsRegistryLeak
from ai4e_tpu.analysis.rules.status_clobber import TerminalStatusClobber
from ai4e_tpu.analysis.rules.swallowed import SwallowedException

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def run_rule(tmp_path, rule, source, filename="mod.py"):
    """Run one rule over a snippet; returns active findings."""
    f = tmp_path / filename
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return Analyzer([rule], root=str(tmp_path)).run([str(f)]).findings


def run_analysis(coro):
    return asyncio.run(coro)


# -- AIL001 blocking-call-in-async -------------------------------------------


class TestBlockingCallInAsync:
    def test_true_positive_time_sleep(self, tmp_path):
        findings = run_rule(tmp_path, BlockingCallInAsync(), """
            import time
            async def handler():
                time.sleep(1)
        """)
        assert [f.rule for f in findings] == ["AIL001"]
        assert "time.sleep" in findings[0].message

    def test_true_positive_requests_and_alias(self, tmp_path):
        findings = run_rule(tmp_path, BlockingCallInAsync(), """
            import requests
            import time as t
            async def handler():
                requests.get("http://x")
                t.sleep(0.1)
        """)
        assert len(findings) == 2

    def test_near_miss_negatives(self, tmp_path):
        # asyncio.sleep, sync def, nested sync helper (executor-bound), and
        # time.sleep passed as a CALLABLE to to_thread are all fine.
        findings = run_rule(tmp_path, BlockingCallInAsync(), """
            import asyncio
            import time
            async def ok():
                await asyncio.sleep(1)
                await asyncio.to_thread(time.sleep, 1)
                def helper():
                    time.sleep(1)   # runs in an executor, not on the loop
                await asyncio.to_thread(helper)
            def sync_path():
                time.sleep(1)
        """)
        assert findings == []

    def test_suppression(self, tmp_path):
        findings = run_rule(tmp_path, BlockingCallInAsync(), """
            import time
            async def handler():
                time.sleep(0.001)  # ai4e: noqa[AIL001] — sub-ms, measured
        """)
        assert findings == []


# -- AIL002 metrics-registry-leak --------------------------------------------


class TestMetricsRegistryLeak:
    def test_true_positive_direct_call(self, tmp_path):
        findings = run_rule(tmp_path, MetricsRegistryLeak(), """
            from ai4e_tpu.metrics import DEFAULT_REGISTRY
            class Pool:
                def __init__(self, metrics=None):
                    self.metrics = metrics
                def work(self):
                    DEFAULT_REGISTRY.counter("x").inc()
        """)
        assert [f.rule for f in findings] == ["AIL002"]
        assert "DEFAULT_REGISTRY" in findings[0].message

    def test_true_positive_conditional_rebinding(self, tmp_path):
        # The exact shape the replication/tracing leaks hid in.
        findings = run_rule(tmp_path, MetricsRegistryLeak(), """
            class Replicator:
                def __init__(self, metrics=None):
                    if metrics is None:
                        from ai4e_tpu.metrics import DEFAULT_REGISTRY
                        metrics = DEFAULT_REGISTRY
                    self._gauge = metrics.gauge("lag")
        """)
        assert [f.rule for f in findings] == ["AIL002"]

    def test_near_miss_blessed_idiom(self, tmp_path):
        findings = run_rule(tmp_path, MetricsRegistryLeak(), """
            from ai4e_tpu.metrics import DEFAULT_REGISTRY
            class Pool:
                def __init__(self, metrics=None):
                    self.metrics = metrics or DEFAULT_REGISTRY
                    self._c = (metrics or DEFAULT_REGISTRY).counter("x")
                def work(self):
                    self.metrics.counter("y").inc()
            class NoInjection:
                def work(self):
                    DEFAULT_REGISTRY.counter("z").inc()  # no metrics param
        """)
        assert findings == []

    def test_suppression(self, tmp_path):
        findings = run_rule(tmp_path, MetricsRegistryLeak(), """
            from ai4e_tpu.metrics import DEFAULT_REGISTRY
            class Pool:
                def __init__(self, metrics=None):
                    DEFAULT_REGISTRY.counter("x").inc()  # ai4e: noqa[AIL002] — process-wide by design
        """)
        assert findings == []


# -- AIL003 terminal-status-clobber ------------------------------------------


class TestTerminalStatusClobber:
    def test_true_positive_unguarded_write(self, tmp_path):
        findings = run_rule(tmp_path, TerminalStatusClobber(), """
            async def deliver(tm, task_id):
                await tm.update_task_status(task_id, "Awaiting")
        """)
        assert [f.rule for f in findings] == ["AIL003"]

    def test_near_miss_guarded_variants(self, tmp_path):
        findings = run_rule(tmp_path, TerminalStatusClobber(), """
            from ai4e_tpu.taskstore import TaskStatus

            async def guarded(tm, task_id, record):
                if TaskStatus.canonical(record) not in TaskStatus.TERMINAL:
                    await tm.update_task_status(task_id, "Awaiting")

            async def via_helper(self, store, task_id):
                if await self._suppress_duplicate(task_id):
                    return
                await self.task_manager.fail_task(task_id, "failed")

            async def conditional(store, task_id):
                store.update_status_if(task_id, "running", "completed")
        """)
        assert findings == []

    def test_shell_guarded_decorator(self, tmp_path):
        # api_async_func handlers (and callbacks nested in them) are
        # guarded by the service shell's adoption-time terminal check.
        findings = run_rule(tmp_path, TerminalStatusClobber(), """
            def register(svc, tm):
                @svc.api_async_func("/x")
                async def handler(taskId, body):
                    await tm.update_task_status(taskId, "running")
                    async def on_progress(done):
                        await tm.update_task_status(taskId, f"running {done}")
                    return on_progress
        """)
        assert findings == []

    def test_taskstore_layer_exempt(self, tmp_path):
        findings = run_rule(tmp_path, TerminalStatusClobber(), """
            def sweep(store, task_id):
                store.update_status(task_id, "failed - lease expired")
        """, filename="taskstore/reaper.py")
        assert findings == []

    def test_suppression(self, tmp_path):
        findings = run_rule(tmp_path, TerminalStatusClobber(), """
            async def deliver(tm, task_id):
                await tm.update_task_status(task_id, "Awaiting")  # ai4e: noqa[AIL003] — task created this call, cannot be terminal
        """)
        assert findings == []


# -- AIL004 fire-and-forget-task ---------------------------------------------


class TestFireAndForgetTask:
    def test_true_positive(self, tmp_path):
        findings = run_rule(tmp_path, FireAndForgetTask(), """
            import asyncio
            def spawn(loop, coro):
                loop.create_task(coro)
                asyncio.ensure_future(coro)
        """)
        assert [f.rule for f in findings] == ["AIL004", "AIL004"]

    def test_near_miss_stored_awaited_chained(self, tmp_path):
        findings = run_rule(tmp_path, FireAndForgetTask(), """
            import asyncio
            async def spawn(loop, coro, holder):
                t = loop.create_task(coro)
                holder.add(t)
                t.add_done_callback(holder.discard)
                await asyncio.ensure_future(coro)
                loop.create_task(coro).add_done_callback(print)
                holder.track(loop.create_task(coro))
        """)
        assert findings == []

    def test_suppression(self, tmp_path):
        findings = run_rule(tmp_path, FireAndForgetTask(), """
            def spawn(loop, coro):
                loop.create_task(coro)  # ai4e: noqa[AIL004] — test scaffolding, loop torn down next line
        """)
        assert findings == []


# -- AIL005 swallowed-exception ----------------------------------------------


class TestSwallowedException:
    def test_true_positive_silent_pass(self, tmp_path):
        findings = run_rule(tmp_path, SwallowedException(), """
            def f():
                try:
                    work()
                except Exception:
                    pass
                try:
                    work()
                except:
                    return None
        """)
        assert [f.rule for f in findings] == ["AIL005", "AIL005"]

    def test_near_miss_logged_counted_raised(self, tmp_path):
        findings = run_rule(tmp_path, SwallowedException(), """
            def f(log, errors):
                try:
                    work()
                except Exception:
                    log.exception("work failed")
                try:
                    work()
                except Exception:
                    errors.inc(kind="work")
                try:
                    work()
                except Exception as exc:
                    raise RuntimeError("wrapped") from exc
                try:
                    work()
                except ValueError:
                    pass   # narrow except is out of scope for AIL005
        """)
        assert findings == []

    def test_event_set_is_not_metric_evidence(self, tmp_path):
        """A bare .set() is asyncio/threading Event signalling, not
        telemetry — it must not satisfy the rule; Gauge.set(value) does."""
        findings = run_rule(tmp_path, SwallowedException(), """
            def f(self, gauge):
                try:
                    work()
                except Exception:
                    self._stopped.set()
                try:
                    work()
                except Exception:
                    gauge.set(1.0)
        """)
        assert len(findings) == 1 and findings[0].line == 5

    def test_suppression(self, tmp_path):
        findings = run_rule(tmp_path, SwallowedException(), """
            def f():
                try:
                    work()
                except Exception:  # ai4e: noqa[AIL005] — destructor-time best effort
                    pass
        """)
        assert findings == []


# -- AIL006 config-drift ------------------------------------------------------


class TestConfigDrift:
    def _project(self, tmp_path, doc_text):
        (tmp_path / "docs").mkdir()
        (tmp_path / "docs" / "config.md").write_text(doc_text)
        (tmp_path / "config.py").write_text(textwrap.dedent("""
            import os
            def _env_section(prefix):
                def deco(cls):
                    return cls
                return deco
            @_env_section("AI4E_DEMO_")
            class DemoSection:
                port: int = 1
                host: str = "x"
            TOKEN = os.environ.get("AI4E_DEMO_EXTRA_TOKEN", "")
        """))
        return Analyzer([ConfigDrift()], root=str(tmp_path)).run(
            [str(tmp_path / "config.py")]).findings

    def test_true_positive_undocumented_and_stale(self, tmp_path):
        findings = self._project(
            tmp_path, "Only `AI4E_DEMO_PORT` and `AI4E_DEMO_GONE` here.\n")
        msgs = {f.message.split(" ", 1)[0]: f for f in findings}
        # host + direct read undocumented; AI4E_DEMO_GONE stale in docs.
        assert "AI4E_DEMO_HOST" in msgs
        assert "AI4E_DEMO_EXTRA_TOKEN" in msgs
        stale = [f for f in findings if "AI4E_DEMO_GONE" in f.message]
        assert stale and stale[0].path == "docs/config.md"

    def test_near_miss_fully_documented(self, tmp_path):
        findings = self._project(
            tmp_path,
            "`AI4E_DEMO_PORT`, `AI4E_DEMO_HOST`, `AI4E_DEMO_EXTRA_TOKEN`;\n"
            "out-of-band: `AI4E_FAULT_SOMETHING`, `AI4E_CHAOS_SEED`.\n")
        assert findings == []

    def test_prefix_mention_covers_family(self, tmp_path):
        findings = self._project(
            tmp_path,
            "All `AI4E_DEMO` knobs (AI4E_DEMO_*) are demo-only.\n")
        assert findings == []

    def test_unstarred_mention_does_not_cover_extensions(self, tmp_path):
        """Documenting AI4E_DEMO_PORT must not silently 'document' a later
        AI4E_DEMO_PORT_FOO — family coverage needs an explicit star."""
        (tmp_path / "docs").mkdir()
        (tmp_path / "docs" / "config.md").write_text(
            "`AI4E_DEMO_PO` is documented (no star).\n")
        (tmp_path / "config.py").write_text(textwrap.dedent("""
            def _env_section(prefix):
                def deco(cls):
                    return cls
                return deco
            @_env_section("AI4E_DEMO_")
            class DemoSection:
                port: int = 1
        """))
        findings = Analyzer([ConfigDrift()], root=str(tmp_path)).run(
            [str(tmp_path / "config.py")]).findings
        assert any("AI4E_DEMO_PORT" in f.message for f in findings)


# -- framework: noqa, baseline, fingerprints, CLI -----------------------------


class TestFramework:
    def test_fingerprint_stable_across_line_moves(self, tmp_path):
        src1 = "import time\nasync def h():\n    time.sleep(1)\n"
        src2 = ("import time\n\n# a comment pushing everything down\n\n"
                "async def h():\n    time.sleep(1)\n")
        f1 = run_rule(tmp_path, BlockingCallInAsync(), src1, "a/m.py")
        f2 = run_rule(tmp_path, BlockingCallInAsync(), src2, "a/m.py")
        assert f1[0].line != f2[0].line
        assert f1[0].fingerprint == f2[0].fingerprint

    def test_baseline_grandfathers_and_reports_stale(self, tmp_path):
        src = "import time\nasync def h():\n    time.sleep(1)\n"
        (tmp_path / "m.py").write_text(src)
        raw = Analyzer([BlockingCallInAsync()], root=str(tmp_path)).run(
            [str(tmp_path / "m.py")]).findings
        entries = [{"rule": "AIL001", "path": "m.py",
                    "fingerprint": raw[0].fingerprint,
                    "justification": "legacy warmup sleep; tracked in #42"},
                   {"rule": "AIL001", "path": "gone.py",
                    "fingerprint": "feedfeedfeedfeed",
                    "justification": "file was deleted"}]
        result = Analyzer(
            [BlockingCallInAsync()], root=str(tmp_path),
            baseline=Baseline(entries)).run([str(tmp_path / "m.py")])
        assert result.findings == [] and len(result.baselined) == 1
        assert [e["path"] for e in result.stale_baseline] == ["gone.py"]

    def test_identical_findings_get_distinct_fingerprints(self, tmp_path):
        """Two byte-identical flagged lines in one symbol must not share a
        fingerprint — else one baseline entry would grandfather NEW
        identical findings nobody justified."""
        src = ("import time\n"
               "async def h():\n"
               "    time.sleep(1)\n"
               "    time.sleep(1)\n")
        (tmp_path / "m.py").write_text(src)
        raw = Analyzer([BlockingCallInAsync()], root=str(tmp_path)).run(
            [str(tmp_path / "m.py")]).findings
        assert len(raw) == 2
        assert raw[0].fingerprint != raw[1].fingerprint
        # Baselining only the first leaves the second ACTIVE.
        entries = [{"rule": "AIL001", "path": "m.py",
                    "fingerprint": raw[0].fingerprint,
                    "justification": "first sleep is grandfathered"}]
        result = Analyzer([BlockingCallInAsync()], root=str(tmp_path),
                          baseline=Baseline(entries)).run(
            [str(tmp_path / "m.py")])
        assert len(result.findings) == 1 and len(result.baselined) == 1

    def test_baseline_without_justification_refused(self, tmp_path):
        p = tmp_path / "baseline.json"
        p.write_text('{"version": 1, "findings": [{"rule": "AIL001", '
                     '"fingerprint": "abc", "justification": "  "}]}')
        with pytest.raises(BaselineError):
            Baseline.load(str(p))

    def test_parse_error_is_a_finding(self, tmp_path):
        (tmp_path / "bad.py").write_text("def broken(:\n")
        result = Analyzer([BlockingCallInAsync()],
                          root=str(tmp_path)).run([str(tmp_path / "bad.py")])
        assert [f.rule for f in result.findings] == ["AIL000"]

    def test_cli_exit_codes_and_json(self, tmp_path, capsys):
        from ai4e_tpu.analysis.cli import main
        (tmp_path / "m.py").write_text(
            "import time\nasync def h():\n    time.sleep(1)\n")
        assert main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                     "--select", "AIL001"]) == 1
        capsys.readouterr()
        assert main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                     "--select", "AIL004"]) == 0
        capsys.readouterr()
        assert main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                     "--select", "AIL001", "--json"]) == 1
        out = capsys.readouterr().out
        import json as _json
        data = _json.loads(out)
        assert data["findings"][0]["rule"] == "AIL001"

    def test_cli_write_baseline_then_requires_justification(self, tmp_path,
                                                            capsys):
        from ai4e_tpu.analysis.cli import main
        (tmp_path / "m.py").write_text(
            "import time\nasync def h():\n    time.sleep(1)\n")
        assert main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                     "--write-baseline"]) == 0
        # The freshly-seeded baseline has empty justifications: the gate
        # refuses it (exit 2) until a human writes them.
        assert main([str(tmp_path / "m.py"), "--root", str(tmp_path)]) == 2


# -- the repo gate ------------------------------------------------------------


class TestRepoClean:
    def test_ai4e_tpu_clean_modulo_baseline(self):
        """The same check CI runs: the production tree must be clean —
        every rule, empty-or-justified baseline."""
        baseline_path = os.path.join(REPO, "analysis_baseline.json")
        baseline = Baseline.load(baseline_path)
        analyzer = Analyzer([cls() for cls in ALL_RULES], root=REPO,
                            baseline=baseline)
        result = analyzer.run([os.path.join(REPO, "ai4e_tpu")])
        assert result.findings == [], "\n".join(
            f.render() for f in result.findings)
        assert result.stale_baseline == []
        assert result.files_scanned > 50


# -- behavioral regressions for defects the analyzer surfaced -----------------


class TestTerminalClobberFixes:
    """AIL003 true positives fixed in this PR, each with the scenario that
    used to corrupt task state."""

    def test_push_forward_suppresses_terminal_duplicate(self):
        """A RETRIED push event (attempts > 1, e.g. after a lost response)
        for a completed task must not re-execute, and must not clobber the
        completion (the queue side fixed this in PR 3; the push side was
        still open). The attempt ordinal rides X-AI4E-Event-Attempt."""
        from ai4e_tpu.broker.push import PushEvent, WebhookDispatcher
        from ai4e_tpu.service import LocalTaskManager
        from ai4e_tpu.taskstore import APITask, InMemoryTaskStore

        async def main():
            store = InMemoryTaskStore()
            wd = WebhookDispatcher(LocalTaskManager(store))
            wd.add_route("/v1/x", "http://127.0.0.1:1/v1/x")  # unreachable
            task = store.upsert(APITask(endpoint="/v1/x", body=b"{}"))
            store.update_status(task.task_id, "completed - 3 found")
            status = await wd._forward(PushEvent(
                id=task.task_id, subject="/v1/x", data=b"{}", attempts=2))
            assert status == 200  # acked, not retried
            assert store.get(task.task_id).status == "completed - 3 found"
            assert wd._forwarded.value(outcome="duplicate") == 1
            # First delivery (attempts <= 1) skips the probe — hot path
            # unchanged: the unreachable backend surfaces as a retryable
            # 429, and the completion still isn't clobbered (the
            # failure-path writes carry their own terminal guard).
            status = await wd._forward(PushEvent(
                id=task.task_id, subject="/v1/x", data=b"{}", attempts=1))
            assert status == 429
            assert store.get(task.task_id).status == "completed - 3 found"

        run_analysis(main())

    def test_push_event_attempt_rides_the_wire(self):
        """headers_for_attempt stamps the ordinal; from_headers restores
        it — the signal the webhook's duplicate suppression keys on."""
        from ai4e_tpu.broker.push import PushEvent

        ev = PushEvent(id="t1", subject="/v1/x", data=b"payload")
        headers = ev.headers_for_attempt(3)
        back = PushEvent.from_headers(headers, b"payload")
        assert back.attempts == 3 and back.id == "t1"
        assert PushEvent.from_headers(ev.to_headers(), b"x").attempts == 0

    def test_dispatcher_drop_expired_skips_terminal(self):
        """An expired redelivery of an already-completed task must not
        flip the completion to `expired` (dispatch-side AIL003)."""
        from ai4e_tpu.broker import InMemoryBroker
        from ai4e_tpu.broker.dispatcher import Dispatcher
        from ai4e_tpu.broker.queue import Message
        from ai4e_tpu.service import LocalTaskManager
        from ai4e_tpu.taskstore import APITask, InMemoryTaskStore

        async def main():
            store = InMemoryTaskStore()
            broker = InMemoryBroker()
            broker.register_queue("/v1/x")
            d = Dispatcher(broker, "/v1/x", "http://127.0.0.1:1/v1/x",
                           LocalTaskManager(store))
            task = store.upsert(APITask(endpoint="/v1/x", body=b"{}"))
            store.update_status(task.task_id, "completed - done")
            msg = Message(task_id=task.task_id, endpoint="/v1/x",
                          deadline_at=1.0, queue_name="/v1/x")
            assert await d._drop_expired(msg) is True
            assert store.get(task.task_id).status == "completed - done"

        run_analysis(main())

    def test_async_shell_suppresses_terminal_duplicate(self):
        """Service-shell adoption guard: a redelivered taskId whose task is
        already terminal acks without invoking the handler."""
        from aiohttp.test_utils import TestClient, TestServer
        from ai4e_tpu.service import APIService, LocalTaskManager
        from ai4e_tpu.taskstore import APITask, InMemoryTaskStore

        store = InMemoryTaskStore()
        svc = APIService("svc", prefix="v1/test",
                         task_manager=LocalTaskManager(store))
        calls = []

        @svc.api_async_func("/run")
        async def run_ep(taskId, body, content_type):
            calls.append(taskId)
            await svc.task_manager.complete_task(taskId, "completed - ran")

        async def main():
            task = store.upsert(APITask(endpoint="/v1/test/run", body=b""))
            store.update_status(task.task_id, "completed - first run")
            client = TestClient(TestServer(svc.app))
            await client.start_server()
            try:
                resp = await client.post("/v1/test/run", data=b"{}",
                                         headers={"taskId": task.task_id})
                assert resp.status == 200
                await svc.drain(timeout=2.0)
            finally:
                await client.close()
            assert calls == []  # handler never invoked
            assert store.get(task.task_id).status == "completed - first run"

        run_analysis(main())

    def test_handler_failure_after_completion_keeps_completion(self):
        """_execute_async must not stamp `failed` over a completion the
        handler already wrote (cleanup-error-after-complete)."""
        from aiohttp.test_utils import TestClient, TestServer
        from ai4e_tpu.service import APIService, LocalTaskManager
        from ai4e_tpu.taskstore import InMemoryTaskStore

        store = InMemoryTaskStore()
        svc = APIService("svc", prefix="v1/test",
                         task_manager=LocalTaskManager(store))

        @svc.api_async_func("/run")
        async def run_ep(taskId, body, content_type):
            await svc.task_manager.complete_task(taskId, "completed - ok")
            raise RuntimeError("cleanup exploded after completion")

        async def main():
            client = TestClient(TestServer(svc.app))
            await client.start_server()
            try:
                resp = await client.post("/v1/test/run", data=b"{}")
                task_id = (await resp.json())["TaskId"]
                await svc.drain(timeout=2.0)
            finally:
                await client.close()
            assert store.get(task_id).status == "completed - ok"

        run_analysis(main())


class TestFireAndForgetFix:
    def test_dead_letter_spawn_holds_strong_ref(self):
        """AIL004 fix: the assembly keeps strong refs to dead-letter
        transitions until done (the loop's weak ref alone permits GC
        mid-flight)."""
        from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig

        async def main():
            platform = LocalPlatform(PlatformConfig())
            loop = asyncio.get_running_loop()
            started = asyncio.Event()
            release = asyncio.Event()

            async def work():
                started.set()
                await release.wait()

            t = platform._spawn_bg(loop, work())
            await started.wait()
            assert t in platform._bg_tasks  # strong ref while running
            release.set()
            await t
            await asyncio.sleep(0)
            assert t not in platform._bg_tasks  # discarded when done

        run_analysis(main())


class TestRegistryLeakFixes:
    def test_span_metrics_land_in_component_registry(self):
        """AIL002 fix: gateway/dispatcher/webhook tracers observe
        ai4e_span_seconds into the assembly's registry, and an
        assembly-driven span leaves NO new series in DEFAULT_REGISTRY."""
        from ai4e_tpu.broker import InMemoryBroker
        from ai4e_tpu.broker.dispatcher import Dispatcher
        from ai4e_tpu.gateway import Gateway
        from ai4e_tpu.metrics import DEFAULT_REGISTRY, MetricsRegistry
        from ai4e_tpu.service import LocalTaskManager
        from ai4e_tpu.taskstore import InMemoryTaskStore

        before = set(DEFAULT_REGISTRY._metrics)
        reg = MetricsRegistry()
        store = InMemoryTaskStore()
        gw = Gateway(store, metrics=reg)
        broker = InMemoryBroker(metrics=reg)
        broker.register_queue("/v1/x")
        d = Dispatcher(broker, "/v1/x", "http://127.0.0.1:1/v1/x",
                       LocalTaskManager(store), metrics=reg)
        with gw.tracer.span("create_task"):
            pass
        with d.tracer.span("dispatch"):
            pass
        hist = reg.histogram("ai4e_span_seconds")
        assert hist.quantile(0.5, name="create_task",
                             service="gateway") >= 0
        assert hist.quantile(0.5, name="dispatch",
                             service="dispatcher") >= 0
        assert set(DEFAULT_REGISTRY._metrics) == before

    def test_replication_gauges_land_in_injected_registry(self, tmp_path):
        """AIL002 fix (satellite): replication gauges ride the injected
        registry — visible in the assembly's /metrics, absent from
        DEFAULT_REGISTRY."""
        from ai4e_tpu.metrics import DEFAULT_REGISTRY, MetricsRegistry
        from ai4e_tpu.taskstore.replication import JournalReplicator
        from ai4e_tpu.taskstore.store import FollowerTaskStore

        async def main():
            before = set(DEFAULT_REGISTRY._metrics)
            reg = MetricsRegistry()
            # The store takes the same injected registry (its
            # ai4e_journal_* family follows the identical AIL002 idiom
            # since the durability PR).
            store = FollowerTaskStore(str(tmp_path / "journal.jsonl"),
                                      metrics=reg)
            repl = JournalReplicator(store, "http://127.0.0.1:1",
                                     metrics=reg)
            assert "ai4e_replication_offset_bytes" in reg._metrics
            assert "ai4e_replication_lag_bytes" in reg._metrics
            assert "ai4e_journal_fsyncs_total" in reg._metrics
            assert set(DEFAULT_REGISTRY._metrics) == before
            await repl.aclose()

        run_analysis(main())


class TestConfigDriftFix:
    def test_out_of_band_namespaces_boot(self):
        """AIL006 fix: AI4E_FEED_*/AI4E_CHAOS_* are out-of-band namespaces
        — FrameworkConfig.from_env used to REJECT AI4E_FEED_ADVERTISE_IP,
        so a multihost deployment pinning its feed IP could not boot."""
        from ai4e_tpu.config import ConfigError, FrameworkConfig

        cfg = FrameworkConfig.from_env(env={
            "AI4E_FEED_ADVERTISE_IP": "10.0.0.7",
            "AI4E_CHAOS_SEED": "123",
            "AI4E_FAULT_FETCH_FAIL_NTHS": "2",
        })
        assert cfg.platform.transport == "queue"
        # Misspellings still fail loudly — the exemption is namespaces,
        # not a hole.
        with pytest.raises(ConfigError):
            FrameworkConfig.from_env(env={"AI4E_PLATFROM_TRANSPORT": "push"})


# -- AIL007 stale-read-across-await -------------------------------------------


class TestStaleReadAcrossAwait:
    def setup_method(self):
        from ai4e_tpu.analysis.rules.stale_read import StaleReadAcrossAwait
        self.rule = StaleReadAcrossAwait()

    def test_true_positive_suspension_between_guard_and_write(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            async def drop(tm, tid):
                if not await tm.is_terminal(tid):
                    await asyncio.sleep(1)
                    await tm.update_task_status(tid, "expired")
        """)
        assert [f.rule for f in findings] == ["AIL007"]
        assert "suspension" in findings[0].message

    def test_true_positive_exact_deadletter_shape(self, tmp_path):
        # The dispatcher._backpressure defect this PR's first run found:
        # entry guard, AWAITING write, backoff sleep, then the dead-letter
        # write acting on the entry guard.
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            async def backpressure(self, msg):
                if await self._suppress_duplicate(msg):
                    return
                await self._try_update(msg.task_id, "awaiting")
                await asyncio.sleep(5)
                if not self.broker.abandon(msg):
                    await self._try_update(msg.task_id, "dead-letter")
        """)
        assert len(findings) == 1
        assert "dead-letter" in findings[0].snippet

    def test_true_positive_guarded_state_attr_write(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            async def probe(breaker, session):
                if breaker.state == "open":
                    await session.post("http://b")
                    breaker.state = "half_open"
        """)
        assert [f.rule for f in findings] == ["AIL007"]

    def test_near_miss_probe_after_await_idiom(self, tmp_path):
        # The blessed shape: the probe IS the last suspension before the
        # write (the residual one-hop window is the documented contract).
        findings = run_rule(tmp_path, self.rule, """
            async def forward(tm, tid):
                if not await tm.is_terminal(tid):
                    await tm.update_task_status(tid, "awaiting")
        """)
        assert findings == []

    def test_near_miss_recheck_after_last_suspension(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            async def drop(tm, tid):
                if not await tm.is_terminal(tid):
                    await asyncio.sleep(1)
                    if not await tm.is_terminal(tid):
                        await tm.update_task_status(tid, "expired")
        """)
        assert findings == []

    def test_conditional_recheck_does_not_suppress(self, tmp_path):
        # A re-check nested inside `if cond:` leaves the cond-False path
        # acting on the stale guard — exists-path semantics: still flagged.
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            async def drop(tm, tid, cond):
                if not await tm.is_terminal(tid):
                    await asyncio.sleep(1)
                    if cond:
                        if await tm.is_terminal(tid):
                            return
                    await tm.update_task_status(tid, "expired")
        """)
        assert [f.rule for f in findings] == ["AIL007"]

    def test_near_miss_unguarded_write_is_ail003s_domain(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            async def blind(tm, tid):
                await asyncio.sleep(1)
                await tm.update_task_status(tid, "failed")
        """)
        assert findings == []

    def test_near_miss_guard_in_other_branch_does_not_count(self, tmp_path):
        # The guard inside an except handler does not dominate the write
        # on the success path — no guard, so no AIL007 (AIL003's domain).
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            async def deliver(tm, tid, session):
                try:
                    await session.post("http://b")
                except OSError:
                    if await tm.is_terminal(tid):
                        return
                    await asyncio.sleep(1)
                    return
                await tm.update_task_status(tid, "failed")
        """)
        assert findings == []

    def test_loop_back_edge_counts_as_suspension(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            async def retry_loop(tm, tid, session):
                if await tm.is_terminal(tid):
                    return
                while True:
                    resp = await session.post("http://b")
                    if resp == 200:
                        return
                    await tm.update_task_status(tid, "failed")
        """)
        assert len(findings) == 1

    def test_suppression(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            async def drop(tm, tid):
                if not await tm.is_terminal(tid):
                    await asyncio.sleep(1)
                    await tm.update_task_status(tid, "expired")  # ai4e: noqa[AIL007] — single-writer path, measured
        """)
        assert findings == []


# -- AIL008 lock-across-slow-await --------------------------------------------


class TestLockAcrossSlowAwait:
    def setup_method(self):
        from ai4e_tpu.analysis.rules.lock_await import LockAcrossSlowAwait
        self.rule = LockAcrossSlowAwait()

    def test_true_positive_post_under_lock(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            class C:
                async def deliver(self, session):
                    async with self._lock:
                        async with session.post("http://b") as resp:
                            await resp.read()
        """)
        assert findings and all(f.rule == "AIL008" for f in findings)
        assert "holding self._lock" in findings[0].message

    def test_true_positive_sleep_under_threading_lock(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            class C:
                async def wait(self):
                    with self._state_lock:
                        await asyncio.sleep(1)
        """)
        assert [f.rule for f in findings] == ["AIL008"]

    def test_near_miss_block_is_not_a_lock(self, tmp_path):
        # "block"/"backlog" contain the substring "lock" but hold none —
        # the name heuristic matches word segments, not substrings.
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            class C:
                async def run(self, session):
                    async with self._dispatch_block:
                        await session.post("http://b")
                    async with self._backlog_lock:
                        await asyncio.sleep(1)
        """)
        assert len(findings) == 1  # only the real lock fires
        assert "_backlog_lock" in findings[0].message

    def test_near_miss_fast_work_under_lock(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            class C:
                async def create(self):
                    async with self._create_lock:
                        self._session = object()
                async def reload(self):
                    async with self._reload_lock:
                        await asyncio.to_thread(self._swap)
        """)
        assert findings == []

    def test_near_miss_slow_await_outside_lock(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            class C:
                async def deliver(self, session):
                    with self._lock:
                        decision = self._decide()
                    await session.post("http://b")
        """)
        assert findings == []

    def test_lock_order_drift_flagged(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            class C:
                async def ab(self):
                    async with self._a_lock:
                        async with self._b_lock:
                            pass
                async def ba(self):
                    async with self._b_lock:
                        async with self._a_lock:
                            pass
        """)
        assert len(findings) == 1
        assert "opposite" in findings[0].message

    def test_lock_order_drift_via_multi_item_with(self, tmp_path):
        # `async with a, b:` enters left-to-right — it establishes a->b
        # exactly like nesting, and must conflict with a nested b->a.
        findings = run_rule(tmp_path, self.rule, """
            class C:
                async def ab(self):
                    async with self._a_lock, self._b_lock:
                        pass
                async def ba(self):
                    async with self._b_lock:
                        async with self._a_lock:
                            pass
        """)
        assert len(findings) == 1
        assert "opposite" in findings[0].message

    def test_consistent_lock_order_clean(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            class C:
                async def one(self):
                    async with self._a_lock:
                        async with self._b_lock:
                            pass
                async def two(self):
                    async with self._a_lock:
                        async with self._b_lock:
                            pass
        """)
        assert findings == []

    def test_suppression(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            class C:
                async def wait(self):
                    with self._lock:
                        await asyncio.sleep(0.001)  # ai4e: noqa[AIL008] — sub-ms tick under a private lock
        """)
        assert findings == []


# -- AIL009 nonatomic-read-modify-write ---------------------------------------


class TestNonatomicReadModifyWrite:
    def setup_method(self):
        from ai4e_tpu.analysis.rules.rmw import NonatomicReadModifyWrite
        self.rule = NonatomicReadModifyWrite()

    def test_true_positive_split_rmw(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            class C:
                async def bump(self):
                    n = self._busy
                    await asyncio.sleep(0)
                    self._busy = n + 1
                async def other(self):
                    self._busy = 0
        """)
        assert [f.rule for f in findings] == ["AIL009"]
        assert "self._busy" in findings[0].message

    def test_true_positive_one_statement_form(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            class C:
                async def bump(self):
                    self._busy = await self._next(self._busy)
                async def other(self):
                    self._busy = 0
        """)
        assert [f.rule for f in findings] == ["AIL009"]

    def test_near_miss_single_writer_attribute(self, tmp_path):
        # Only one method ever writes it: nobody to race with.
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            class C:
                async def bump(self):
                    n = self._busy
                    await asyncio.sleep(0)
                    self._busy = n + 1
        """)
        assert findings == []

    def test_near_miss_same_segment_rmw(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            class C:
                async def bump(self):
                    self._busy += 1
                    await asyncio.sleep(0)
                    self._busy -= 1
                async def other(self):
                    self._busy = 0
        """)
        assert findings == []

    def test_near_miss_reread_after_await(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            class C:
                async def bump(self):
                    n = self._busy
                    await asyncio.sleep(0)
                    n = self._busy
                    self._busy = n + 1
                async def other(self):
                    self._busy = 0
        """)
        assert findings == []

    def test_suppression(self, tmp_path):
        findings = run_rule(tmp_path, self.rule, """
            import asyncio
            class C:
                async def bump(self):
                    n = self._busy
                    await asyncio.sleep(0)
                    self._busy = n + 1  # ai4e: noqa[AIL009] — the await cannot interleave a writer (startup only)
                async def other(self):
                    self._busy = 0
        """)
        assert findings == []


# -- CLI satellites: unknown rule ids, JSON baseline authoring ----------------


class TestCliRuleIdValidation:
    def test_unknown_select_id_exits_2_and_names_it(self, tmp_path, capsys):
        from ai4e_tpu.analysis.cli import main
        (tmp_path / "m.py").write_text("x = 1\n")
        # The CI-job-typo scenario: before this PR, --select AIL999
        # silently filtered to an EMPTY rule list and exited 0 — a typo
        # could disable the whole gate.
        assert main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                     "--select", "AIL999"]) == 2
        err = capsys.readouterr().err
        assert "AIL999" in err and "--select" in err

    def test_unknown_ignore_id_exits_2(self, tmp_path, capsys):
        from ai4e_tpu.analysis.cli import main
        (tmp_path / "m.py").write_text("x = 1\n")
        assert main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                     "--ignore", "AIL001,AILOOPS"]) == 2
        assert "AILOOPS" in capsys.readouterr().err

    def test_known_ids_still_select(self, tmp_path, capsys):
        from ai4e_tpu.analysis.cli import main
        (tmp_path / "m.py").write_text(
            "import time\nasync def h():\n    time.sleep(1)\n")
        assert main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                     "--select", "ail001"]) == 1  # case-folded

    def test_list_rules_shows_the_concurrency_family(self, capsys):
        from ai4e_tpu.analysis.cli import main
        assert main(["--list-rules"]) == 0
        out = capsys.readouterr().out
        for rule_id in ("AIL007", "AIL008", "AIL009"):
            assert rule_id in out


class TestCliJsonBaselineAuthoring:
    def test_json_findings_carry_paste_ready_baseline_entries(
            self, tmp_path, capsys):
        import json as _json
        from ai4e_tpu.analysis.cli import main
        (tmp_path / "m.py").write_text(
            "import time\nasync def h():\n    time.sleep(1)\n")
        assert main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                     "--json"]) == 1
        data = _json.loads(capsys.readouterr().out)
        assert data["version"] == 1
        finding = data["findings"][0]
        assert finding["fingerprint"]
        entry = finding["baseline_entry"]
        # The paste-ready shape: exactly what Baseline.load consumes, with
        # the justification left for a human.
        assert entry["fingerprint"] == finding["fingerprint"]
        assert entry["justification"] == ""
        assert set(entry) == {"rule", "path", "symbol", "snippet",
                              "fingerprint", "justification"}
        # Round-trip: a baseline authored from the JSON (plus a written
        # justification) grandfathers the finding.
        entry["justification"] = "known blocking call, measured sub-ms"
        baseline_path = tmp_path / "analysis_baseline.json"
        baseline_path.write_text(_json.dumps(
            {"version": 1, "findings": [entry]}))
        capsys.readouterr()
        assert main([str(tmp_path / "m.py"), "--root", str(tmp_path)]) == 0


# -- behavioral regressions for the AIL007 dispatcher fixes -------------------


class TestStaleGuardFixes:
    """The three stale-guard windows AIL007's first run found in the
    dispatcher, fixed in this PR. The full interleaving regression suite
    lives in tests/test_race_regressions.py (every schedule in the budget);
    here: the single decisive interleaving per defect, as a plain unit
    test that needs no explorer."""

    def _fixture(self, **kw):
        import random as _random
        from ai4e_tpu.broker.dispatcher import Dispatcher
        from ai4e_tpu.broker.queue import InMemoryBroker
        from ai4e_tpu.metrics.registry import MetricsRegistry
        from ai4e_tpu.resilience.health import BackendHealth
        from ai4e_tpu.service.task_manager import LocalTaskManager
        from ai4e_tpu.taskstore import APITask, InMemoryTaskStore

        store = InMemoryTaskStore()
        broker = InMemoryBroker(max_delivery_count=1)
        broker.register_queue("/v1/q")
        d = Dispatcher(broker, "/v1/q", "http://b",
                       LocalTaskManager(store), retry_delay=0.0,
                       metrics=MetricsRegistry(), rng=_random.Random(0),
                       resilience=BackendHealth(metrics=MetricsRegistry()),
                       **kw)
        store.upsert(APITask(task_id="t1", endpoint="/v1/q/op",
                             body=b"x", publish=False))
        return store, broker, d

    def test_deadletter_write_rechecks_terminality(self):
        from ai4e_tpu.taskstore import TaskStatus

        async def main():
            store, broker, d = self._fixture()
            task = store.get("t1")
            broker.publish(task)
            msg = await broker.receive("/v1/q", timeout=1.0)
            # The lost-response completion lands during the backoff sleep:
            # emulated by completing after the AWAITING write via a store
            # listener hooked on that exact transition.
            def complete_on_awaiting(t):
                if t.task_id == "t1" and t.status == "Awaiting service availability":
                    store.update_status("t1", "completed",
                                        TaskStatus.COMPLETED)
            store.add_listener(complete_on_awaiting)
            await d._backpressure(msg, "b")
            assert store.get("t1").canonical_status == TaskStatus.COMPLETED
            assert d._dispatched.value(outcome="duplicate", queue="/v1/q",
                                       backend="b") == 1

        run_analysis(main())

    def test_failure_paths_tolerate_no_task_manager(self):
        """The new re-probes must not break the task_manager=None
        configuration the cache path documents: a 4xx permanent failure
        and a dead-letter exhaustion both finish without raising."""
        import random as _random
        from ai4e_tpu.broker.dispatcher import Dispatcher
        from ai4e_tpu.broker.queue import InMemoryBroker, Message
        from ai4e_tpu.metrics.registry import MetricsRegistry

        class FakeResp:
            status = 400
            headers = {}  # the dispatcher consults X-Draining
            async def read(self):
                return b""

        class FakePost:
            async def __aenter__(self):
                return FakeResp()
            async def __aexit__(self, *exc):
                return False

        class FakeSessions:
            async def get(self):
                return self
            def post(self, url, **kw):
                return FakePost()

        async def main():
            broker = InMemoryBroker(max_delivery_count=1)
            broker.register_queue("/v1/q")
            d = Dispatcher(broker, "/v1/q", "http://b", task_manager=None,
                           retry_delay=0.0, metrics=MetricsRegistry(),
                           rng=_random.Random(0))
            d._sessions = FakeSessions()
            msg = Message(task_id="t1", endpoint="/v1/q/op", body=b"x",
                          queue_name="/v1/q", seq=1)
            broker.queue("/v1/q").put(msg)
            popped = await broker.receive("/v1/q", timeout=1.0)
            await d._dispatch_one(popped)  # 4xx permanent-fail path
            assert d._dispatched.value(outcome="failed", queue="/v1/q",
                                       backend="b") == 1
            msg2 = Message(task_id="t2", endpoint="/v1/q/op", body=b"x",
                           queue_name="/v1/q", seq=2)
            broker.queue("/v1/q").put(msg2)
            popped2 = await broker.receive("/v1/q", timeout=1.0)
            await d._backpressure(popped2, "b")  # dead-letter path
            assert d._dispatched.value(outcome="dead_letter", queue="/v1/q",
                                       backend="b") == 1

        run_analysis(main())

    def test_cache_complete_rechecks_after_result_hop(self):
        from ai4e_tpu.metrics.registry import MetricsRegistry
        from ai4e_tpu.rescache.cache import ResultCache
        from ai4e_tpu.taskstore import TaskStatus

        class HopStore:
            def __init__(self, store, on_hop):
                self.store, self.on_hop = store, on_hop
            async def set_result(self, task_id, payload,
                                 content_type="application/json"):
                self.on_hop()
                self.store.set_result(task_id, payload,
                                      content_type=content_type)

        async def main():
            cache = ResultCache(metrics=MetricsRegistry())
            cache.put("/v1/q|k", b"r")
            store = None

            def fail_during_hop():
                store.update_status_if("t1", TaskStatus.RUNNING,
                                       "failed - no progress",
                                       backend_status=TaskStatus.FAILED)

            s, broker, d = self._fixture(
                result_cache=cache)
            store = s
            d.result_store = HopStore(store, fail_during_hop)
            store.update_status("t1", TaskStatus.RUNNING, TaskStatus.RUNNING)
            task = store.get("t1")
            broker.publish(task)
            msg = await broker.receive("/v1/q", timeout=1.0)
            msg.cache_key = "/v1/q|k"
            assert await d._complete_from_cache(msg) is True
            # The reaper's failure landed mid-hop and must survive.
            assert store.get("t1").canonical_status == TaskStatus.FAILED
            assert d._dispatched.value(outcome="duplicate", queue="/v1/q",
                                       backend="") == 1

        run_analysis(main())


# -- AIL010 metrics-drift -----------------------------------------------------


class TestMetricsDrift:
    def _project(self, tmp_path, doc_text, code=None):
        from ai4e_tpu.analysis.rules.metrics_drift import MetricsDrift
        (tmp_path / "docs").mkdir()
        (tmp_path / "docs" / "METRICS.md").write_text(doc_text)
        (tmp_path / "mod.py").write_text(code or textwrap.dedent("""
            class Svc:
                def __init__(self, metrics):
                    self._hits = metrics.counter(
                        "ai4e_demo_hits_total", "hits")
                    self._depth = metrics.gauge("ai4e_demo_depth", "d")
                    self._lat = metrics.histogram(
                        "ai4e_demo_seconds", "lat")
        """))
        return Analyzer([MetricsDrift()], root=str(tmp_path)).run(
            [str(tmp_path / "mod.py")]).findings

    def test_true_positive_undocumented_and_stale(self, tmp_path):
        findings = self._project(
            tmp_path,
            "| `ai4e_demo_hits_total` | counter |\n"
            "| `ai4e_demo_gone` | gauge |\n")
        undocumented = {f.message.split(" ", 2)[1] for f in findings
                        if "registered in code" in f.message}
        assert undocumented == {"ai4e_demo_depth", "ai4e_demo_seconds"}
        stale = [f for f in findings if "ai4e_demo_gone" in f.message]
        assert stale and stale[0].path == "docs/METRICS.md"
        assert stale[0].line == 2

    def test_near_miss_fully_documented(self, tmp_path):
        assert self._project(
            tmp_path,
            "| `ai4e_demo_hits_total` | `ai4e_demo_depth` |\n"
            "| `ai4e_demo_seconds` | histogram |\n") == []

    def test_starred_family_covers_code_names(self, tmp_path):
        assert self._project(
            tmp_path, "All `ai4e_demo_*` metrics are demo-only.\n") == []

    def test_unstarred_prefix_does_not_cover(self, tmp_path):
        findings = self._project(
            tmp_path, "The `ai4e_demo` family (no star) is mentioned.\n")
        assert any("ai4e_demo_hits_total" in f.message for f in findings)
        # The bare prefix itself is stale too (nothing registers it).
        assert any("documents ai4e_demo " in f.message for f in findings)

    def test_exposition_suffixes_and_paths_excluded(self, tmp_path):
        """Docs may spell a histogram's _bucket/_sum/_count exposition
        and name files under ai4e_tpu/ without tripping the rule."""
        assert self._project(
            tmp_path,
            "`ai4e_demo_seconds_bucket` and `ai4e_demo_seconds_count`\n"
            "rendered by `ai4e_tpu/metrics/registry.py`; see also\n"
            "`ai4e_demo_hits_total`, `ai4e_demo_depth`,\n"
            "`ai4e_demo_seconds`.\n") == []

    def test_dynamic_names_ignored(self, tmp_path):
        """Only literal first arguments register: a computed name cannot
        be matched against docs and must not crash the rule."""
        assert self._project(
            tmp_path, "nothing documented\n",
            code=textwrap.dedent("""
                def make(metrics, name):
                    return metrics.counter(name, "dyn")
                def other(metrics):
                    return metrics.counter("not_ai4e_prefixed", "x")
            """)) == []

    def test_whole_repo_in_sync(self):
        """The real tree: every registered ai4e_* metric documented in
        docs/METRICS.md and vice versa — the gate CI now enforces (the
        rule's first run found ai4e_trace_current documented but never
        registered; fixed in this PR)."""
        from ai4e_tpu.analysis.rules.metrics_drift import MetricsDrift
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        pkg = os.path.join(root, "ai4e_tpu")
        paths = []
        for dirpath, _dirs, files in os.walk(pkg):
            paths.extend(os.path.join(dirpath, f)
                         for f in files if f.endswith(".py"))
        result = Analyzer([MetricsDrift()], root=root).run(sorted(paths))
        assert [f.render() for f in result.findings] == []


# -- AIL011 ledger-vocabulary drift -------------------------------------------


class TestLedgerVocabularyDrift:
    DOC_OK = textwrap.dedent("""\
        # Observability

        <!-- ai4e:ledger-vocabulary -->
        | event | stamped by |
        |---|---|
        | `admitted` | gateway |
        | `h2d`, `execute` | device |
        <!-- /ai4e:ledger-vocabulary -->

        Prose mentioning `popped` outside the table never counts.

        <!-- ai4e:flight-reasons -->
        | reason | kept because |
        |---|---|
        | `failed` | terminal failed |
        | `sampled` | baseline stride |
        <!-- /ai4e:flight-reasons -->
        """)

    LEDGER_OK = textwrap.dedent("""\
        ADMITTED = "admitted"
        H2D = "h2d"
        EXECUTE = "execute"
        MAX_EVENTS = 128
        """)

    FLIGHT_OK = textwrap.dedent("""\
        REASON_FAILED = "failed"
        REASON_SAMPLED = "sampled"
        """)

    def _project(self, tmp_path, doc=None, ledger=None, flight=None,
                 extra=None):
        from ai4e_tpu.analysis.rules.ledger_vocab import \
            LedgerVocabularyDrift
        (tmp_path / "docs").mkdir()
        (tmp_path / "docs" / "observability.md").write_text(
            self.DOC_OK if doc is None else doc)
        obs = tmp_path / "observability"
        obs.mkdir()
        (obs / "ledger.py").write_text(
            self.LEDGER_OK if ledger is None else ledger)
        (obs / "flight.py").write_text(
            self.FLIGHT_OK if flight is None else flight)
        paths = [str(obs / "ledger.py"), str(obs / "flight.py")]
        if extra is not None:
            (tmp_path / "caller.py").write_text(extra)
            paths.append(str(tmp_path / "caller.py"))
        return Analyzer([LedgerVocabularyDrift()],
                        root=str(tmp_path)).run(sorted(paths)).findings

    def test_in_sync_project_is_clean(self, tmp_path):
        assert self._project(tmp_path) == []

    def test_undocumented_event_and_reason(self, tmp_path):
        findings = self._project(
            tmp_path,
            ledger=self.LEDGER_OK + 'POPPED = "popped"\n',
            flight=self.FLIGHT_OK + 'REASON_SLOW = "slow"\n')
        msgs = [f.message for f in findings]
        assert any("'popped'" in m and "absent from" in m for m in msgs)
        assert any("'slow'" in m and "absent from" in m for m in msgs)
        assert len(findings) == 2

    def test_stale_doc_rows_both_tables(self, tmp_path):
        doc = self.DOC_OK.replace("| `admitted` | gateway |",
                                  "| `admitted` | gateway |\n"
                                  "| `vanished` | nowhere |")
        doc = doc.replace("| `failed` | terminal failed |",
                          "| `failed` | terminal failed |\n"
                          "| `gone` | nothing |")
        findings = self._project(tmp_path, doc=doc)
        msgs = [f.message for f in findings]
        assert any("'vanished'" in m and "no code defines" in m
                   for m in msgs)
        assert any("'gone'" in m and "no code defines" in m for m in msgs)
        stale = [f for f in findings if "'vanished'" in f.message]
        assert stale[0].path == "docs/observability.md"

    def test_literal_stamp_outside_vocabulary(self, tmp_path):
        findings = self._project(tmp_path, extra=textwrap.dedent("""\
            from observability.ledger import ledger_event

            def f(buf, hub, tid, e):
                buf.stamp("admitted", "gateway")     # vocabulary: fine
                buf.stamp("typo_event", "gateway")   # NOT vocabulary
                ledger_event("execute", "device")    # fine
                hub.stamp(tid, e)                    # non-literal: fine
            """))
        assert len(findings) == 1
        assert "'typo_event'" in findings[0].message
        assert findings[0].path == "caller.py"

    def test_missing_marked_region_is_itself_a_finding(self, tmp_path):
        findings = self._project(
            tmp_path, doc="# Observability\n\nno markers at all\n")
        msgs = [f.message for f in findings]
        assert any("ai4e:ledger-vocabulary" in m and "no" in m
                   for m in msgs)
        assert any("ai4e:flight-reasons" in m for m in msgs)
        assert len(findings) == 2

    def test_prose_outside_markers_never_counts(self, tmp_path):
        # `popped` appears in prose — neither documented (code side
        # would flag it if the constant existed) nor stale (doc side
        # must not read it as a table row).
        assert self._project(tmp_path) == []

    def test_non_vocabulary_project_is_silent(self, tmp_path):
        from ai4e_tpu.analysis.rules.ledger_vocab import \
            LedgerVocabularyDrift
        (tmp_path / "plain.py").write_text("x = 1\n")
        findings = Analyzer([LedgerVocabularyDrift()],
                            root=str(tmp_path)).run(
            [str(tmp_path / "plain.py")]).findings
        assert findings == []

    def test_whole_repo_in_sync(self):
        """The real tree: the observability.md vocabulary tables and
        the ledger/flight constants agree both directions, and every
        literal stamp in the codebase uses a vocabulary event — the
        gate CI now enforces."""
        from ai4e_tpu.analysis.rules.ledger_vocab import \
            LedgerVocabularyDrift
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        pkg = os.path.join(root, "ai4e_tpu")
        paths = []
        for dirpath, _dirs, files in os.walk(pkg):
            paths.extend(os.path.join(dirpath, f)
                         for f in files if f.endswith(".py"))
        result = Analyzer([LedgerVocabularyDrift()],
                          root=root).run(sorted(paths))
        assert [f.render() for f in result.findings] == []


# -- AIL012 static-bucket-ladder ---------------------------------------------


class TestStaticBucketLadder:
    """A literal bucket/tile ladder under ``runtime/`` outside the
    deriver module is a finding — the static ladder PR 13 retired must
    not silently come back (docs/device_path.md)."""

    def _run(self, tmp_path, source, filename):
        from ai4e_tpu.analysis.rules.bucket_literal import \
            StaticBucketLadder
        return run_rule(tmp_path, StaticBucketLadder(), source,
                        filename=filename)

    def test_true_positive_in_runtime(self, tmp_path):
        findings = self._run(tmp_path, """
            BUCKETS = (1, 2, 4, 8)
        """, "ai4e_tpu/runtime/batcher2.py")
        assert [f.rule for f in findings] == ["AIL012"]
        assert "(1, 2, 4, 8)" in findings[0].message

    def test_trailing_inf_sentinel_does_not_exempt(self, tmp_path):
        # The exact pre-PR-13 exposition shape: int ladder + float("inf").
        findings = self._run(tmp_path, """
            hist = registry.histogram(
                "x", "", buckets=(1, 2, 4, 8, 16, float("inf")))
        """, "ai4e_tpu/runtime/metrics_shim.py")
        assert [f.rule for f in findings] == ["AIL012"]

    def test_list_literal_flagged_too(self, tmp_path):
        findings = self._run(tmp_path, """
            ladder = [1, 16, 64]
        """, "ai4e_tpu/runtime/worker_extra.py")
        assert [f.rule for f in findings] == ["AIL012"]

    def test_deriver_module_exempt(self, tmp_path):
        findings = self._run(tmp_path, """
            DEFAULT_BUCKETS = (1, 2, 4, 8)
            IMAGE_BUCKETS = (1, 16, 64)
        """, "ai4e_tpu/runtime/ladder.py")
        assert findings == []

    def test_outside_runtime_not_flagged(self, tmp_path):
        findings = self._run(tmp_path, """
            buckets = (1, 8, 32, 64)
        """, "ai4e_tpu/models/config.py")
        assert findings == []

    def test_shape_and_width_tuples_not_flagged(self, tmp_path):
        findings = self._run(tmp_path, """
            stage_sizes = (3, 4, 6, 3)      # not ascending
            widths = (32, 64, 128)          # does not start at 1
            pair = (1, 8)                   # too short to be a ladder
            shape = (1, 224, x)             # non-constant tail, run of 2
        """, "ai4e_tpu/runtime/families2.py")
        assert findings == []

    def test_suppression(self, tmp_path):
        findings = self._run(tmp_path, """
            LEGACY = (1, 2, 4)  # ai4e: noqa[AIL012] — fixture for the migration test
        """, "ai4e_tpu/runtime/fixture.py")
        assert findings == []


# -- AIL013 unbounded-metric-label -------------------------------------------


class TestUnboundedMetricLabel:
    """An identity-class metric label fed a dynamic value is a finding —
    caller identity must pass through a bounded-cardinality mapper
    (``TenantRegistry.tenant_label``, docs/tenancy.md) before it becomes
    a series dimension."""

    def _run(self, tmp_path, source, filename="ai4e_tpu/svc/mod.py"):
        from ai4e_tpu.analysis.rules.metric_label import \
            UnboundedMetricLabel
        return run_rule(tmp_path, UnboundedMetricLabel(), source,
                        filename=filename)

    def test_true_positive_raw_tenant_id(self, tmp_path):
        findings = self._run(tmp_path, """
            def note(counter, tenant_id):
                counter.inc(tenant=tenant_id)
        """)
        assert [f.rule for f in findings] == ["AIL013"]
        assert "tenant=" in findings[0].message

    def test_true_positive_header_read(self, tmp_path):
        # The nightmare shape: one rotated header per request = one fresh
        # series per request.
        findings = self._run(tmp_path, """
            def note(counter, request):
                counter.inc(api_key=request.headers.get("X-Api-Key"))
        """)
        assert [f.rule for f in findings] == ["AIL013"]

    def test_observe_and_set_flagged_too(self, tmp_path):
        findings = self._run(tmp_path, """
            def note(hist, gauge, caller_id):
                hist.observe(0.5, caller=caller_id)
                gauge.set(1.0, client_id=caller_id)
        """)
        assert sorted(f.rule for f in findings) == ["AIL013", "AIL013"]

    def test_blessed_inline_mapper_call(self, tmp_path):
        findings = self._run(tmp_path, """
            def note(counter, registry, tenant_id):
                counter.inc(tenant=registry.tenant_label(tenant_id))
        """)
        assert findings == []


# -- AIL014 unplaced-device-transfer ------------------------------------------


class TestUnplacedDeviceTransfer:
    """A device transfer under ``runtime/``/``parallel/`` that does not
    state its placement is a finding — PR 17 made placement declarative
    (NamedSharding batch axes, partition rules, the one blessed fetch
    helper in ``runtime/mesh/placement.py``; docs/mesh_serving.md)."""

    def _run(self, tmp_path, source,
             filename="ai4e_tpu/runtime/mod.py"):
        from ai4e_tpu.analysis.rules.unplaced import UnplacedDeviceTransfer
        return run_rule(tmp_path, UnplacedDeviceTransfer(), source,
                        filename=filename)

    def test_true_positive_bare_device_put(self, tmp_path):
        findings = self._run(tmp_path, """
            import jax
            def stage(batch):
                return jax.device_put(batch)
        """)
        assert [f.rule for f in findings] == ["AIL014"]
        assert "default device" in findings[0].message

    def test_true_positive_bare_device_get(self, tmp_path):
        findings = self._run(tmp_path, """
            import jax
            def fetch(out):
                return jax.device_get(out)
        """, filename="ai4e_tpu/parallel/mod.py")
        assert [f.rule for f in findings] == ["AIL014"]
        assert "fetch_to_host" in findings[0].message

    def test_from_import_alias_resolved(self, tmp_path):
        findings = self._run(tmp_path, """
            from jax import device_put as put
            def stage(batch):
                return put(batch)
        """)
        assert [f.rule for f in findings] == ["AIL014"]

    def test_positional_sharding_is_placed(self, tmp_path):
        findings = self._run(tmp_path, """
            import jax
            def stage(batch, sharding, device):
                a = jax.device_put(batch, sharding)
                b = jax.device_put(batch, device)
                return a, b
        """)
        assert findings == []

    def test_placement_kwargs_are_placed(self, tmp_path):
        findings = self._run(tmp_path, """
            import jax
            def stage(batch, s, d):
                a = jax.device_put(batch, sharding=s)
                b = jax.device_put(batch, device=d)
                return a, b
        """)
        assert findings == []

    def test_blessed_helper_module_exempt(self, tmp_path):
        findings = self._run(tmp_path, """
            import jax
            def fetch_to_host(out):
                return jax.device_get(out)
        """, filename="ai4e_tpu/runtime/mesh/placement.py")
        assert findings == []

    def test_outside_device_path_not_flagged(self, tmp_path):
        findings = self._run(tmp_path, """
            import jax
            def load(x):
                return jax.device_put(x)
        """, filename="ai4e_tpu/bench.py")
        assert findings == []

    def test_whole_repo_baseline_empty(self):
        """The real tree: every transfer on the serving path is placed
        (registry's fetches route through placement.fetch_to_host) —
        the gate CI enforces from this PR on."""
        from ai4e_tpu.analysis.rules.unplaced import UnplacedDeviceTransfer
        pkg = os.path.join(REPO, "ai4e_tpu")
        paths = []
        for dirpath, _dirs, files in os.walk(pkg):
            paths.extend(os.path.join(dirpath, f)
                         for f in files if f.endswith(".py"))
        result = Analyzer([UnplacedDeviceTransfer()],
                          root=REPO).run(sorted(paths))
        assert [f.render() for f in result.findings] == []

    def test_blessed_label_named_variable(self, tmp_path):
        # The two-line idiom: map first, label with the mapped value.
        findings = self._run(tmp_path, """
            def note(counter, registry, tenant_id):
                label = registry.tenant_label(tenant_id)
                counter.inc(tenant=label)
        """)
        assert findings == []

    def test_blessed_string_constant(self, tmp_path):
        findings = self._run(tmp_path, """
            def note(counter):
                counter.inc(tenant="other")
        """)
        assert findings == []

    def test_non_identity_kwarg_not_flagged(self, tmp_path):
        findings = self._run(tmp_path, """
            def note(counter, route_prefix):
                counter.inc(route=route_prefix, outcome="200")
        """)
        assert findings == []

    def test_suppression(self, tmp_path):
        findings = self._run(tmp_path, """
            def note(counter, tenant_id):
                counter.inc(tenant=tenant_id)  # ai4e: noqa[AIL013] — bounded upstream by construction
        """)
        assert findings == []

    def test_whole_repo_clean(self):
        """The real tree ships with zero findings — the tenancy layer was
        born using the bounded mapper (the gate CI now enforces)."""
        from ai4e_tpu.analysis.rules.metric_label import \
            UnboundedMetricLabel
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        pkg = os.path.join(root, "ai4e_tpu")
        paths = []
        for dirpath, _dirs, files in os.walk(pkg):
            paths.extend(os.path.join(dirpath, f)
                         for f in files if f.endswith(".py"))
        result = Analyzer([UnboundedMetricLabel()],
                          root=root).run(sorted(paths))
        assert [f.render() for f in result.findings] == []


# -- the wire family (AIL016-AIL018) ------------------------------------------
#
# Project-rule fixtures: each test writes a tiny multi-module project
# (server modules registering routes, client modules calling them, a
# docs/API.md carrying the two marked tables) and runs exactly one wire
# rule over it, so assertions never entangle the three rules' outputs.


WIRE_DOC_SHELL = """\
# API

<!-- ai4e:routes -->
| Method | Path | Registered in | Callers |
|---|---|---|---|
{routes}
<!-- /ai4e:routes -->

<!-- ai4e:headers -->
| Header | Emitted by | Read by |
|---|---|---|
{headers}
<!-- /ai4e:headers -->
"""


def wire_run(tmp_path, rule, files, routes="", headers="", doc=True):
    """Write a fixture project under ``tmp_path`` and run one wire rule.
    Returns the full AnalysisResult (tests need ``.suppressed`` too)."""
    pkg = tmp_path / "pkg"
    pkg.mkdir(exist_ok=True)
    for rel, src in files.items():
        f = tmp_path / rel
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_text(textwrap.dedent(src))
    if doc:
        d = tmp_path / "docs"
        d.mkdir(exist_ok=True)
        (d / "API.md").write_text(
            WIRE_DOC_SHELL.format(routes=routes, headers=headers))
    return Analyzer([rule], root=str(tmp_path)).run([str(pkg)])


_ROUTES_SERVER = """
    from aiohttp import web

    async def upsert(request):
        return web.json_response({})

    async def ping(request):
        return web.json_response({})

    def attach(app):
        app.router.add_post("/v1/store/upsert", upsert)
        app.router.add_get("/v1/store/ping", ping)
"""

_ROUTES_CLIENT = """
    async def save(session, body):
        resp = await session.post("/v1/store/upsert", json=body)
        return await resp.json()

    async def check(session):
        resp = await session.get("/v1/store/ping")
        return resp.status
"""

_ROUTES_ROWS = (
    "| `POST` | `/v1/store/upsert` | `pkg/server.py` | `pkg/client.py` |\n"
    "| `GET` | `/v1/store/ping` | `pkg/server.py` | `pkg/client.py` |")

_TYPO_CLIENT = _ROUTES_CLIENT + """
    async def doomed(session):
        resp = await session.post("/v1/store/upsrt")
        return resp.status
"""

_SUPPRESSED_TYPO_CLIENT = _ROUTES_CLIENT + """
    async def doomed(session):
        resp = await session.post("/v1/store/upsrt")  # ai4e: noqa[AIL016] — exercised here as the rule's own fixture
        return resp.status
"""

_PURGE_SERVER = _ROUTES_SERVER + """
    async def purge(request):
        return web.json_response({})

    def attach_admin(app):
        app.router.add_post("/v1/store/purge", purge)
"""


class TestClientRouteDrift:
    def _rule(self):
        from ai4e_tpu.analysis.rules.wire import ClientRouteDrift
        return ClientRouteDrift()

    def test_in_sync_surface_is_clean(self, tmp_path):
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": _ROUTES_SERVER,
                           "pkg/client.py": _ROUTES_CLIENT},
                          routes=_ROUTES_ROWS)
        assert [f.render() for f in result.findings] == []

    def test_typoed_client_path_can_only_404(self, tmp_path):
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": _ROUTES_SERVER,
                           "pkg/client.py": _TYPO_CLIENT},
                          routes=_ROUTES_ROWS)
        assert len(result.findings) == 1
        f = result.findings[0]
        assert "no registered route matches" in f.message
        assert f.fingerprint_key == "AIL016|client|POST /v1/store/upsrt"
        assert f.symbol == "doomed"

    def test_dead_route_without_external_row(self, tmp_path):
        rows = _ROUTES_ROWS + (
            "\n| `POST` | `/v1/store/purge` | `pkg/server.py` | — |")
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": _PURGE_SERVER,
                           "pkg/client.py": _ROUTES_CLIENT},
                          routes=rows)
        assert len(result.findings) == 1
        f = result.findings[0]
        assert "no client call site" in f.message
        assert f.fingerprint_key == "AIL016|dead-route|POST /v1/store/purge"

    def test_external_caller_row_vouches_for_the_route(self, tmp_path):
        rows = _ROUTES_ROWS + ("\n| `POST` | `/v1/store/purge` | "
                               "`pkg/server.py` | external — operator "
                               "runbook verb |")
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": _PURGE_SERVER,
                           "pkg/client.py": _ROUTES_CLIENT},
                          routes=rows)
        assert [f.render() for f in result.findings] == []

    def test_registered_route_absent_from_doc_table(self, tmp_path):
        # ping is called (no dead-route) but its row is missing.
        rows = "| `POST` | `/v1/store/upsert` | `pkg/server.py` | `pkg/client.py` |"
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": _ROUTES_SERVER,
                           "pkg/client.py": _ROUTES_CLIENT},
                          routes=rows)
        assert len(result.findings) == 1
        f = result.findings[0]
        assert "absent from docs/API.md" in f.message
        assert f.fingerprint_key == "AIL016|undocumented|GET /v1/store/ping"

    def test_doc_row_nothing_registers_is_stale(self, tmp_path):
        rows = _ROUTES_ROWS + (
            "\n| `DELETE` | `/v1/store/gone` | `pkg/server.py` | — |")
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": _ROUTES_SERVER,
                           "pkg/client.py": _ROUTES_CLIENT},
                          routes=rows)
        assert len(result.findings) == 1
        f = result.findings[0]
        assert f.path == "docs/API.md"
        assert "nothing registers it" in f.message
        assert f.fingerprint_key == "AIL016|stale-doc|DELETE /v1/store/gone"

    def test_missing_table_is_one_finding_not_noise(self, tmp_path):
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": _ROUTES_SERVER,
                           "pkg/client.py": _ROUTES_CLIENT},
                          doc=False)
        assert [f.fingerprint_key for f in result.findings] == [
            "AIL016|no-table"]
        assert "--dump-wire" in result.findings[0].message

    def test_prefix_registration_matches_full_path_client(self, tmp_path):
        # ``self.prefix + "/models/reload"`` registers as /{**}/models/
        # reload; a client posting base + "/v1/svc/models/reload" must
        # match it (the PR 18 reload verb is wired exactly like this).
        server = """
            from aiohttp import web

            async def reload_weights(request):
                return web.json_response({})

            class Svc:
                def __init__(self, prefix):
                    self.prefix = prefix

                def attach(self, app):
                    app.router.add_post(self.prefix + "/models/reload",
                                        reload_weights)
        """
        client = """
            async def trigger(session, base):
                resp = await session.post(base + "/v1/svc/models/reload")
                return await resp.json()
        """
        rows = ("| `POST` | `/{**}/models/reload` | `pkg/server.py` | "
                "`pkg/client.py` |")
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": server, "pkg/client.py": client},
                          routes=rows)
        assert [f.render() for f in result.findings] == []

    def test_suppression_marker_counts_as_suppressed(self, tmp_path):
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": _ROUTES_SERVER,
                           "pkg/client.py": _SUPPRESSED_TYPO_CLIENT},
                          routes=_ROUTES_ROWS)
        assert result.findings == []
        assert result.suppressed == 1

    def test_fingerprint_stable_when_registration_moves_files(self, tmp_path):
        # The contract fingerprint names the CONTRACT, not the file: the
        # same dead route registered from a different module must carry
        # the SAME fingerprint, so refactors don't churn the baseline.
        server = """
            from aiohttp import web

            async def purge(request):
                return web.json_response({})

            def attach(app):
                app.router.add_post("/v1/store/purge", purge)
        """
        rows = "| `POST` | `/v1/store/purge` | `pkg/server.py` | — |"
        a = tmp_path / "a"
        a.mkdir()
        before = wire_run(a, self._rule(), {"pkg/server.py": server},
                          routes=rows)
        b = tmp_path / "b"
        b.mkdir()
        after = wire_run(b, self._rule(), {"pkg/registry.py": server},
                         routes=rows)
        assert len(before.findings) == len(after.findings) == 1
        assert before.findings[0].path != after.findings[0].path
        assert before.findings[0].fingerprint == after.findings[0].fingerprint


_HDR_EMIT = """
    from aiohttp import web

    async def shed(request):
        return web.json_response(
            {}, status=503, headers={"X-Shed-Reason": "quota"})
"""

_HDR_READ = """
    async def watch(session):
        resp = await session.get("http://svc/v1/x")
        return resp.headers.get("X-Shed-Reason")
"""

_HDR_ROWS = "| `X-Shed-Reason` | `pkg/emit.py` | `pkg/read.py` |"


class TestHeaderVocabularyDrift:
    def _rule(self):
        from ai4e_tpu.analysis.rules.wire import HeaderVocabularyDrift
        return HeaderVocabularyDrift()

    def test_round_tripped_header_is_clean(self, tmp_path):
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/emit.py": _HDR_EMIT,
                           "pkg/read.py": _HDR_READ},
                          headers=_HDR_ROWS)
        assert [f.render() for f in result.findings] == []

    def test_header_outside_vocabulary_is_typo_minted(self, tmp_path):
        # Emitted AND read in code (so only the vocabulary check can
        # fire) but absent from the table: the typo-minted shape.
        emit = """
            async def shed(request, web):
                return web.json_response(
                    {}, status=503, headers={"X-Shed-Reasn": "quota"})
        """
        read = """
            async def watch(session):
                resp = await session.get("http://svc/v1/x")
                return resp.headers.get("X-Shed-Reasn")
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/emit.py": emit, "pkg/read.py": read},
                          headers=_HDR_ROWS.replace(
                              "X-Shed-Reason", "X-Other"))
        keys = [f.fingerprint_key for f in result.findings]
        assert "AIL017|vocab|X-Shed-Reasn" in keys
        assert any("typo-minted" in f.message for f in result.findings)

    def test_emit_without_reader_and_no_external_row(self, tmp_path):
        rows = _HDR_ROWS + "\n| `X-Cost-Tier` | `pkg/price.py` | — |"
        price = """
            async def price(request, resp):
                resp.headers["X-Cost-Tier"] = "batch"
                return resp
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/emit.py": _HDR_EMIT, "pkg/read.py": _HDR_READ,
                           "pkg/price.py": price},
                          headers=rows)
        assert [f.fingerprint_key for f in result.findings] == [
            "AIL017|emit-no-reader|X-Cost-Tier"]

    def test_documented_external_reader_vouches(self, tmp_path):
        rows = _HDR_ROWS + ("\n| `X-Cost-Tier` | `pkg/price.py` | "
                            "external — billing scraper |")
        price = """
            async def price(request, resp):
                resp.headers["X-Cost-Tier"] = "batch"
                return resp
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/emit.py": _HDR_EMIT, "pkg/read.py": _HDR_READ,
                           "pkg/price.py": price},
                          headers=rows)
        assert [f.render() for f in result.findings] == []

    def test_read_without_emitter_and_no_external_row(self, tmp_path):
        rows = _HDR_ROWS + "\n| `X-Deadline-Ms` | — | `pkg/budget.py` |"
        budget = """
            async def deadline(request):
                return request.headers.get("X-Deadline-Ms")
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/emit.py": _HDR_EMIT, "pkg/read.py": _HDR_READ,
                           "pkg/budget.py": budget},
                          headers=rows)
        assert [f.fingerprint_key for f in result.findings] == [
            "AIL017|read-no-emitter|X-Deadline-Ms"]

    def test_documented_external_emitter_vouches(self, tmp_path):
        rows = _HDR_ROWS + ("\n| `X-Deadline-Ms` | external — load "
                            "clients set the budget | `pkg/budget.py` |")
        budget = """
            async def deadline(request):
                return request.headers.get("X-Deadline-Ms")
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/emit.py": _HDR_EMIT, "pkg/read.py": _HDR_READ,
                           "pkg/budget.py": budget},
                          headers=rows)
        assert [f.render() for f in result.findings] == []

    def test_doc_row_nothing_uses_is_stale(self, tmp_path):
        rows = _HDR_ROWS + "\n| `X-Gone` | `pkg/emit.py` | `pkg/read.py` |"
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/emit.py": _HDR_EMIT,
                           "pkg/read.py": _HDR_READ},
                          headers=rows)
        assert [f.fingerprint_key for f in result.findings] == [
            "AIL017|stale-doc|X-Gone"]
        assert result.findings[0].path == "docs/API.md"

    def test_constant_resolved_emit_round_trips(self, tmp_path):
        # ``resp.headers[SHED_HEADER] = …`` resolves through the
        # *_HEADER constant map; the defining assignment itself is a
        # mention, not an emit obligation.
        emit = """
            SHED_HEADER = "X-Shed-Reason"

            async def shed(request, resp):
                resp.headers[SHED_HEADER] = "quota"
                return resp
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/emit.py": emit, "pkg/read.py": _HDR_READ},
                          headers=_HDR_ROWS)
        assert [f.render() for f in result.findings] == []

    def test_suppression_marker_counts_as_suppressed(self, tmp_path):
        price = """
            async def price(request, resp):
                resp.headers["X-Cost-Tier"] = "batch"  # ai4e: noqa[AIL017] — fixture for this very test
                return resp
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/emit.py": _HDR_EMIT, "pkg/read.py": _HDR_READ,
                           "pkg/price.py": price},
                          headers=_HDR_ROWS)
        assert result.findings == []
        assert result.suppressed >= 1


_REFUSE_SERVER = """
    from aiohttp import web

    def _refuse():
        return web.json_response({"error": "busy"}, status=503)

    async def upsert(request):
        if request.content_length and request.content_length > 1024:
            return _refuse()
        return web.json_response({})

    def attach(app):
        app.router.add_post("/v1/store/upsert", upsert)
"""


class TestUnhandledRefusalStatus:
    def _rule(self):
        from ai4e_tpu.analysis.rules.wire import UnhandledRefusalStatus
        return UnhandledRefusalStatus()

    def test_unbranched_503_is_a_finding(self, tmp_path):
        client = """
            async def save(session, body):
                resp = await session.post("/v1/store/upsert", json=body)
                if resp.status != 200:
                    raise RuntimeError("save failed")
                body = await resp.json()
                return body
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": _REFUSE_SERVER,
                           "pkg/client.py": client})
        assert len(result.findings) == 1
        f = result.findings[0]
        assert "503" in f.message and "backpressure" in f.message
        assert f.fingerprint_key == "AIL018|POST /v1/store/upsert|503|save"

    def test_branching_on_the_status_is_clean(self, tmp_path):
        client = """
            async def save(session, body):
                resp = await session.post("/v1/store/upsert", json=body)
                if resp.status in (429, 503):
                    raise TimeoutError("store shed the write; retry later")
                if resp.status != 200:
                    raise RuntimeError("save failed")
                body = await resp.json()
                return body
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": _REFUSE_SERVER,
                           "pkg/client.py": client})
        assert [f.render() for f in result.findings] == []

    def test_module_helper_one_hop_counts_as_handled(self, tmp_path):
        # The fix idiom this PR applied everywhere: a module-level
        # ``_raise_refusal(resp)`` the response is passed to. Its
        # compares count for the caller (one hop, symmetric with the
        # server-side handler hop).
        client = """
            def _raise_refusal(resp):
                if resp.status == 503:
                    raise TimeoutError("store refused; retry later")

            async def save(session, body):
                resp = await session.post("/v1/store/upsert", json=body)
                _raise_refusal(resp)
                if resp.status != 200:
                    raise RuntimeError("save failed")
                body = await resp.json()
                return body
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": _REFUSE_SERVER,
                           "pkg/client.py": client})
        assert [f.render() for f in result.findings] == []

    def test_raise_for_status_does_not_distinguish(self, tmp_path):
        # ``resp.raise_for_status()`` is generic failure, not a branch on
        # the refusal contract — the exact bug class the first run caught
        # in service/task_manager.py.
        client = """
            async def save(session, body):
                resp = await session.post("/v1/store/upsert", json=body)
                resp.raise_for_status()
                body = await resp.json()
                return body
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": _REFUSE_SERVER,
                           "pkg/client.py": client})
        assert [f.fingerprint_key for f in result.findings] == [
            "AIL018|POST /v1/store/upsert|503|save"]

    def test_propagating_transport_helper_is_exempt(self, tmp_path):
        client = """
            async def _request(session, body):
                resp = await session.post("/v1/store/upsert", json=body)
                return resp
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": _REFUSE_SERVER,
                           "pkg/client.py": client})
        assert [f.render() for f in result.findings] == []

    def test_http_conflict_constructor_counts_as_409(self, tmp_path):
        server = """
            from aiohttp import web

            async def reload_weights(request):
                if request.app.get("draining"):
                    raise web.HTTPConflict(text="draining")
                return web.json_response({})

            def attach(app):
                app.router.add_post("/v1/models/reload", reload_weights)
        """
        client = """
            async def trigger(session):
                resp = await session.post("/v1/models/reload")
                if resp.status != 200:
                    raise RuntimeError("reload failed")
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": server,
                           "pkg/client.py": client})
        assert len(result.findings) == 1
        assert "409" in result.findings[0].message
        assert "conflict" in result.findings[0].message

    def test_undistinguished_statuses_carry_no_obligation(self, tmp_path):
        # 404 is not part of the refusal contract: no caller obligation.
        server = """
            from aiohttp import web

            async def fetch(request):
                if not request.query.get("id"):
                    return web.json_response({}, status=404)
                return web.json_response({})

            def attach(app):
                app.router.add_get("/v1/store/task", fetch)
        """
        client = """
            async def load(session):
                resp = await session.get("/v1/store/task")
                body = await resp.json()
                return body
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": server,
                           "pkg/client.py": client})
        assert [f.render() for f in result.findings] == []

    def test_suppression_marker_counts_as_suppressed(self, tmp_path):
        client = """
            async def save(session, body):
                resp = await session.post("/v1/store/upsert", json=body)  # ai4e: noqa[AIL018] — fixture for this very test
                if resp.status != 200:
                    raise RuntimeError("save failed")
                body = await resp.json()
                return body
        """
        result = wire_run(tmp_path, self._rule(),
                          {"pkg/server.py": _REFUSE_SERVER,
                           "pkg/client.py": client})
        assert result.findings == []
        assert result.suppressed == 1


# -- AIL019 unused-suppression ------------------------------------------------


class TestUnusedSuppression:
    def _run(self, tmp_path, source, rules):
        f = tmp_path / "m.py"
        f.write_text(textwrap.dedent(source))
        return Analyzer(rules, root=str(tmp_path)).run([str(f)])

    def _rules(self):
        from ai4e_tpu.analysis.rules.unused_noqa import UnusedSuppression
        return [BlockingCallInAsync(), UnusedSuppression()]

    def test_stale_marker_is_a_finding(self, tmp_path):
        result = self._run(tmp_path, """
            x = 1  # ai4e: noqa[AIL001] — the sleep this blessed is long gone
        """, self._rules())
        assert [f.rule for f in result.findings] == ["AIL019"]
        assert "AIL001" in result.findings[0].message
        assert "does not fire on" in result.findings[0].message

    def test_live_marker_suppresses_and_is_not_flagged(self, tmp_path):
        result = self._run(tmp_path, """
            import time
            async def h():
                time.sleep(1)  # ai4e: noqa[AIL001] — fixture: rule genuinely fires here
        """, self._rules())
        assert result.findings == []
        assert result.suppressed == 1

    def test_marker_for_inactive_rule_is_unproven_not_unused(self, tmp_path):
        # Under --select the suppressed rule never ran: flagging the
        # marker as unused would be a lie.
        from ai4e_tpu.analysis.rules.unused_noqa import UnusedSuppression
        result = self._run(tmp_path, """
            x = 1  # ai4e: noqa[AIL001] — AIL001 is not in this run
        """, [UnusedSuppression()])
        assert result.findings == []

    def test_justified_keep_via_ail019_in_the_marker(self, tmp_path):
        result = self._run(tmp_path, """
            x = 1  # ai4e: noqa[AIL001,AIL019] — fires only under the py3.12 parser
        """, self._rules())
        assert result.findings == []
        assert result.suppressed == 1


# -- --sarif / --stats / --dump-wire / --list-rules ---------------------------


class TestSarifOutput:
    def test_findings_emit_sarif_with_matching_fingerprints(self, tmp_path,
                                                            capsys):
        import json as _json
        from ai4e_tpu.analysis.cli import main
        (tmp_path / "m.py").write_text(
            "import time\nasync def h():\n    time.sleep(1)\n")
        base = [str(tmp_path / "m.py"), "--root", str(tmp_path),
                "--select", "AIL001"]
        assert main(base + ["--json"]) == 1
        fp = _json.loads(capsys.readouterr().out)["findings"][0]["fingerprint"]
        assert main(base + ["--sarif"]) == 1
        doc = _json.loads(capsys.readouterr().out)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        driver = run["tool"]["driver"]
        assert driver["name"] == "ai4e-lint"
        assert any(r["id"] == "AIL001" for r in driver["rules"])
        res = run["results"][0]
        assert res["ruleId"] == "AIL001"
        assert res["level"] == "error"
        loc = res["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "m.py"
        assert loc["region"]["startLine"] == 3
        # Same identity as the baseline fingerprint: annotations survive
        # pushes that merely move the finding, exactly like the baseline.
        assert res["partialFingerprints"]["ai4eFingerprint/v1"] == fp

    def test_clean_tree_exits_zero_with_empty_results(self, tmp_path, capsys):
        import json as _json
        from ai4e_tpu.analysis.cli import main
        (tmp_path / "m.py").write_text("x = 1\n")
        assert main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                     "--select", "AIL001", "--sarif"]) == 0
        doc = _json.loads(capsys.readouterr().out)
        assert doc["runs"][0]["results"] == []


class TestStatsAndParseCache:
    def test_stats_json_carries_per_rule_seconds(self, tmp_path, capsys):
        import json as _json
        from ai4e_tpu.analysis.cli import main
        (tmp_path / "m.py").write_text("x = 1\n")
        assert main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                     "--select", "AIL001", "--json", "--stats"]) == 0
        stats = _json.loads(capsys.readouterr().out)["stats"]
        assert set(stats) == {"parse_seconds", "total_seconds",
                              "rule_seconds"}
        assert "AIL001" in stats["rule_seconds"]
        assert stats["total_seconds"] >= stats["parse_seconds"] >= 0

    def test_stats_text_total_line_matches_the_lint_sh_scrape(self, tmp_path,
                                                              capsys):
        # scripts/lint.sh extracts the total with
        # ``sed -n 's/^stats: .*total \([0-9][0-9]*\) ms$/\1/p'`` — the
        # stderr format is load-bearing.
        import re
        from ai4e_tpu.analysis.cli import main
        (tmp_path / "m.py").write_text("x = 1\n")
        assert main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                     "--select", "AIL001", "--stats"]) == 0
        err = capsys.readouterr().err
        assert re.search(r"(?m)^stats: .*total \d+ ms$", err)
        assert re.search(r"(?m)^stats: AIL001\s+[\d.]+ ms$", err)

    def test_parse_cache_reuses_tree_until_content_changes(self, tmp_path):
        from ai4e_tpu.analysis.core import parse_module
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        m1 = parse_module(str(p), "m.py")
        m2 = parse_module(str(p), "m.py")
        assert m2.tree is m1.tree and m2.source is m1.source
        p.write_text("y = 22222\n")
        st = os.stat(p)
        os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns + 10**9))
        m3 = parse_module(str(p), "m.py")
        assert m3.tree is not m1.tree
        assert "y = 22222" in m3.source

    def test_parse_cache_invalidates_on_mtime_alone(self, tmp_path):
        # Same byte length, newer mtime: the cache must re-read (size
        # alone is not identity).
        from ai4e_tpu.analysis.core import parse_module
        p = tmp_path / "m.py"
        p.write_text("x = 1\n")
        m1 = parse_module(str(p), "m.py")
        p.write_text("x = 2\n")
        st = os.stat(p)
        os.utime(p, ns=(st.st_atime_ns, st.st_mtime_ns + 10**9))
        m3 = parse_module(str(p), "m.py")
        assert "x = 2" in m3.source


class TestDumpWire:
    def test_prints_both_marked_tables_from_the_surface(self, tmp_path,
                                                        capsys):
        from ai4e_tpu.analysis.cli import main
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "server.py").write_text(textwrap.dedent(_ROUTES_SERVER))
        (pkg / "client.py").write_text(textwrap.dedent(_ROUTES_CLIENT))
        (pkg / "emit.py").write_text(textwrap.dedent(_HDR_EMIT))
        (pkg / "read.py").write_text(textwrap.dedent(_HDR_READ))
        assert main([str(pkg), "--root", str(tmp_path), "--dump-wire"]) == 0
        out = capsys.readouterr().out
        assert "<!-- ai4e:routes -->" in out and "<!-- /ai4e:routes -->" in out
        assert "<!-- ai4e:headers -->" in out
        assert "`/v1/store/upsert`" in out
        assert "`X-Shed-Reason`" in out


class TestListRulesFamilies:
    def test_wire_family_is_grouped_and_banners_dodge_the_grep(self, capsys):
        from ai4e_tpu.analysis.cli import main
        assert main(["--list-rules"]) == 0
        lines = capsys.readouterr().out.splitlines()
        banners = [l for l in lines if l.startswith("#")]
        assert "# wire contracts (cross-process)" in banners
        # scripts/lint.sh counts rules with `grep -c '^AIL'`: exactly one
        # line per registered rule, banners excluded.
        ail_lines = [l for l in lines if l.startswith("AIL")]
        assert len(ail_lines) == len(ALL_RULES)
        wire_at = lines.index("# wire contracts (cross-process)")
        first_wire = next(i for i, l in enumerate(lines)
                          if l.startswith("AIL016"))
        assert wire_at < first_wire


# -- the wire gate ships armed ------------------------------------------------


class TestWireGateRegistration:
    def test_wire_and_hygiene_rules_are_registered(self):
        ids = {cls.rule_id for cls in ALL_RULES}
        assert {"AIL016", "AIL017", "AIL018", "AIL019"} <= ids
        assert len(ids) >= 19

    def test_checked_in_baseline_is_empty(self):
        """ISSUE 19 acceptance: the wire family's first-run findings were
        all FIXED in this PR, not baselined — the baseline ships empty."""
        import json as _json
        with open(os.path.join(REPO, "analysis_baseline.json")) as fh:
            data = _json.load(fh)
        assert data["findings"] == []


# -- behavioral regressions for the refusal-contract fixes --------------------


class _FakeResp:
    def __init__(self, status, headers=None):
        self.status = status
        self.headers = headers or {}


class TestTypedRefusalFixes:
    """AIL018's first run flagged every store-client write path for
    swallowing the 503 backpressure / fence-409 refusals; the fix routes
    them through typed module helpers. Pin the helpers' contract."""

    def test_task_manager_types_503_with_retry_after(self):
        from ai4e_tpu.service.task_manager import (StoreRefusalError,
                                                   _raise_refusal)
        with pytest.raises(StoreRefusalError) as ei:
            _raise_refusal(_FakeResp(503, {"Retry-After": "3",
                                           "X-Shed-Reason": "journal-degraded"}))
        assert ei.value.status == 503
        assert ei.value.retry_after == "3"
        assert "journal-degraded" in str(ei.value)

    def test_task_manager_types_fence_409_only(self):
        from ai4e_tpu.service.task_manager import (StoreRefusalError,
                                                   _raise_refusal)
        with pytest.raises(StoreRefusalError) as ei:
            _raise_refusal(_FakeResp(409, {"X-Not-Owner": "1"}))
        assert ei.value.status == 409
        # A bare 409 is the conditional-update precondition branch, not
        # the ring fence: it must pass through untyped.
        _raise_refusal(_FakeResp(409))
        _raise_refusal(_FakeResp(200))
        _raise_refusal(_FakeResp(404))

    def test_store_refusal_rides_the_not_primary_handling(self):
        # The gateway's standby handling (503 + Retry-After) catches
        # NotPrimaryError; the typed refusal must be a subclass so store
        # refusals surface as retryable refusals, not 500s.
        from ai4e_tpu.service.task_manager import StoreRefusalError
        from ai4e_tpu.taskstore import NotPrimaryError
        assert issubclass(StoreRefusalError, NotPrimaryError)

    def test_rig_wire_refusal_helper(self):
        from ai4e_tpu.rig.wire import _raise_refusal
        from ai4e_tpu.taskstore import NotPrimaryError
        with pytest.raises(NotPrimaryError) as ei:
            _raise_refusal(_FakeResp(503, {"Retry-After": "2"}))
        assert "retry after 2s" in str(ei.value)
        with pytest.raises(NotPrimaryError):
            _raise_refusal(_FakeResp(409, {"X-Not-Owner": "1"}))
        _raise_refusal(_FakeResp(409))
        _raise_refusal(_FakeResp(200))


# -- the balance family (AIL020-AIL022) ---------------------------------------
#
# AIL020 per-rule fixtures follow the repo convention: at least one true
# positive per escape class (return, raise, end, suspension-abandonment),
# one near-miss per blessed idiom (finally, context manager,
# close-before-reraise, guard-if, ownership handoff, callback handoff),
# and one suppression case. The engine lives in analysis/balance.py; the
# pair table is PAIR_SPECS (limiter-slot and gauge-updown carry the
# fixtures — no anchor, no receiver constraint).


def balance_run(tmp_path, source, filename="mod.py"):
    from ai4e_tpu.analysis.rules.balance import UnbalancedPairedEffect
    f = tmp_path / filename
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return Analyzer([UnbalancedPairedEffect()],
                    root=str(tmp_path)).run([str(f)])


class TestUnbalancedPairedEffect:
    def test_true_positive_return_escape(self, tmp_path):
        result = balance_run(tmp_path, """
            class C:
                async def h(self, ok):
                    self.limiter.acquire()
                    if ok:
                        return 1
                    self.limiter.release()
        """)
        assert [f.rule for f in result.findings] == ["AIL020"]
        f = result.findings[0]
        assert "limiter-slot" in f.message and "return path" in f.message
        assert f.symbol == "C.h"

    def test_true_positive_raise_escape_missing_close_before_reraise(
            self, tmp_path):
        result = balance_run(tmp_path, """
            class C:
                def h(self):
                    self.limiter.acquire()
                    try:
                        work()
                    except Exception:
                        raise
                    self.limiter.release()
        """)
        assert [f.rule for f in result.findings] == ["AIL020"]
        assert "raise path" in result.findings[0].message

    def test_true_positive_end_escape(self, tmp_path):
        result = balance_run(tmp_path, """
            class C:
                def h(self, ok):
                    self.limiter.acquire()
                    if ok:
                        self.limiter.release()
        """)
        assert [f.rule for f in result.findings] == ["AIL020"]
        assert "unconditional close" in result.findings[0].message

    def test_true_positive_suspension_abandonment(self, tmp_path):
        """Every textual path closes — but the await between open and
        close abandons the frame on cancellation. The leak mode reviews
        miss; the reason finally/CM are the only full protections."""
        result = balance_run(tmp_path, """
            import asyncio
            class C:
                async def h(self):
                    self.limiter.acquire()
                    await asyncio.sleep(0)
                    self.limiter.release()
        """)
        assert [f.rule for f in result.findings] == ["AIL020"]
        assert "cancelled await" in result.findings[0].message

    def test_near_miss_no_await_in_span_is_clean(self, tmp_path):
        result = balance_run(tmp_path, """
            class C:
                async def h(self):
                    self.limiter.acquire()
                    x = compute()
                    self.limiter.release()
                    await publish(x)
        """)
        assert result.findings == []

    def test_near_miss_finally_blessed(self, tmp_path):
        result = balance_run(tmp_path, """
            class C:
                async def h(self):
                    self.limiter.acquire()
                    try:
                        await work()
                    finally:
                        self.limiter.release()
        """)
        assert result.findings == []

    def test_near_miss_guard_if_shape(self, tmp_path):
        """The pervasive production shape: a conditional open paired
        with an identically-guarded close in the finally (dispatcher /
        router orchestration accounting)."""
        result = balance_run(tmp_path, """
            async def h(orch):
                if orch is not None:
                    orch.acquire()
                try:
                    await work()
                finally:
                    if orch is not None:
                        orch.release()
        """)
        assert result.findings == []

    def test_near_miss_close_before_reraise(self, tmp_path):
        result = balance_run(tmp_path, """
            class C:
                def h(self):
                    self.limiter.acquire()
                    try:
                        work()
                    except Exception:
                        self.limiter.release()
                        raise
                    self.limiter.release()
        """)
        assert result.findings == []

    def test_near_miss_context_manager_blessed(self, tmp_path):
        result = balance_run(tmp_path, """
            class C:
                async def h(self, ok):
                    with self.pool.acquire() as conn:
                        if ok:
                            return conn
                    slot = self.pool.acquire()
                    try:
                        await work(slot)
                    finally:
                        self.pool.release(slot)
        """)
        assert result.findings == []

    def test_near_miss_ownership_handoff(self, tmp_path):
        """decode.py's _admit shape: the open's result is stored into a
        container — the effect has a new owner with its own lifecycle."""
        result = balance_run(tmp_path, """
            class C:
                def h(self, busy):
                    slot = self.pool.acquire()
                    if busy:
                        self.pool.release(slot)
                        return None
                    self._active[slot] = slot
        """)
        assert result.findings == []

    def test_near_miss_callback_handoff(self, tmp_path):
        """batcher.py's window shape: the close rides the task's done
        callback, not this frame."""
        result = balance_run(tmp_path, """
            class C:
                async def h(self, loop):
                    await self._window.acquire()
                    task = loop.create_task(run())
                    def _done(t):
                        self._window.release()
                    task.add_done_callback(_done)
        """)
        assert result.findings == []

    def test_near_miss_open_without_close_is_cross_function(self, tmp_path):
        """An open whose close lives in a different function is a
        protocol endpoint — out of scope, never flagged."""
        result = balance_run(tmp_path, """
            class C:
                def prologue(self):
                    self._gate._reserve()
                    return True
        """)
        assert result.findings == []

    def test_gauge_requires_same_receiver(self, tmp_path):
        """gauge-updown is same_receiver: another gauge's dec() does not
        close this gauge's inc()."""
        result = balance_run(tmp_path, """
            class C:
                def h(self, ok):
                    self._pending.inc()
                    if ok:
                        return 1
                    self._pending.dec()
        """)
        assert [f.rule for f in result.findings] == ["AIL020"]
        assert "gauge-updown" in result.findings[0].message

    def test_suppression(self, tmp_path):
        result = balance_run(tmp_path, """
            class C:
                def h(self, ok):
                    self.limiter.acquire()  # ai4e: noqa[AIL020] — fixture for this very test
                    if ok:
                        return 1
                    self.limiter.release()
        """)
        assert result.findings == []
        assert result.suppressed == 1

    def test_fingerprint_stable_under_file_move(self, tmp_path):
        """The effect-identity fingerprint is pair name + enclosing
        symbol + escape kind + open snippet — moving the file must not
        churn the baseline."""
        src = """
            class C:
                def h(self, ok):
                    self.limiter.acquire()
                    if ok:
                        return 1
                    self.limiter.release()
        """
        a = balance_run(tmp_path, src, filename="a.py").findings
        b = balance_run(tmp_path, src, filename="moved/deep/b.py").findings
        assert len(a) == len(b) == 1
        assert a[0].path != b[0].path
        assert a[0].fingerprint == b[0].fingerprint


class TestVerbatimRevertCaught:
    """ISSUE 20 acceptance: a verbatim pre-fix revert of a real,
    hand-fixed production bug must be CAUGHT by AIL020. The PR 8 class:
    the worker's DrainingError handler stamps RETRY into the request's
    hop-ledger buffer and must flush before redelivering — deleting the
    flush loses the draining timeline of exactly the retried task."""

    WORKER = os.path.join(REPO, "ai4e_tpu", "runtime", "worker.py")

    def _sources(self):
        with open(self.WORKER) as fh:
            src = fh.read()
        anchor = src.index('reason="draining"')
        cut = src.index("await self._flush_ledger", anchor)
        line_start = src.rindex("\n", 0, cut)
        line_end = src.index("\n", cut)
        broken = src[:line_start] + src[line_end:]
        assert broken != src
        import ast as _ast
        _ast.parse(broken)  # the surgery must leave valid syntax
        return src, broken

    def test_pristine_worker_is_clean(self, tmp_path):
        src, _ = self._sources()
        f = tmp_path / "worker.py"
        f.write_text(src)
        from ai4e_tpu.analysis.rules.balance import UnbalancedPairedEffect
        result = Analyzer([UnbalancedPairedEffect()],
                          root=str(tmp_path)).run([str(f)])
        assert result.findings == []

    def test_deleted_drain_flush_is_caught(self, tmp_path):
        _, broken = self._sources()
        f = tmp_path / "worker.py"
        f.write_text(broken)
        from ai4e_tpu.analysis.rules.balance import UnbalancedPairedEffect
        result = Analyzer([UnbalancedPairedEffect()],
                          root=str(tmp_path)).run([str(f)])
        hits = [x for x in result.findings
                if "ledger-buffer-flush" in x.message]
        assert hits, "\n".join(x.render() for x in result.findings)
        assert 'buf.stamp' in hits[0].snippet


# -- AIL021 journal-replay-round-trip -----------------------------------------


_STORE_CLEAN = """
    class Store:
        def __init__(self):
            self._lines = []
            self._results = {}

        def _append(self, rec):
            self._lines.append(rec)

        def finish(self, task_id, status):
            self._append({"taskId": task_id, "result": True,
                          "status": status})

        def evict(self, task_id):
            self._append({"taskId": task_id, "evict": True,
                          "status": "evicted"})

        def _apply_replay_record(self, rec):
            if rec.get("result"):
                self._results[rec["taskId"]] = rec["status"]
            if rec.get("evict"):
                self._results.pop(rec["taskId"], None)
"""


def journal_run(tmp_path, source):
    from ai4e_tpu.analysis.rules.balance import JournalReplayRoundTrip
    f = tmp_path / "pkg" / "taskstore" / "store.py"
    f.parent.mkdir(parents=True, exist_ok=True)
    f.write_text(textwrap.dedent(source))
    return Analyzer([JournalReplayRoundTrip()],
                    root=str(tmp_path)).run([str(tmp_path / "pkg")])


class TestJournalReplayRoundTrip:
    def test_clean_round_trip(self, tmp_path):
        result = journal_run(tmp_path, _STORE_CLEAN)
        assert result.findings == []

    def test_writer_without_replay_branch(self, tmp_path):
        """A record marker written but never consulted at replay: that
        record type silently drops durable state at restart."""
        src = _STORE_CLEAN.replace(
            '            if rec.get("evict"):\n'
            '                self._results.pop(rec["taskId"], None)\n', "")
        assert src != _STORE_CLEAN
        result = journal_run(tmp_path, src)
        assert [f.rule for f in result.findings] == ["AIL021"]
        f = result.findings[0]
        assert "'evict' is written" in f.message
        assert f.fingerprint_key == "AIL021|writer-without-replay|evict"
        assert f.symbol == "Store.evict"

    def test_replay_branch_without_writer(self, tmp_path):
        src = _STORE_CLEAN + """
        def _apply_ghost(self):
            pass
"""
        src = src.replace(
            'if rec.get("result"):',
            'if rec.get("ghost"):\n'
            '                pass\n'
            '            if rec.get("result"):')
        result = journal_run(tmp_path, src)
        assert [f.rule for f in result.findings] == ["AIL021"]
        f = result.findings[0]
        assert "consults 'ghost'" in f.message
        assert f.fingerprint_key == "AIL021|replay-without-writer|ghost"

    def test_arming_no_replay_entrypoint(self, tmp_path):
        """The self-honesty arm: renaming _apply_replay_record away must
        fire, not silently disarm the round-trip check."""
        src = _STORE_CLEAN.replace("_apply_replay_record", "_renamed_away")
        result = journal_run(tmp_path, src)
        assert [f.rule for f in result.findings] == ["AIL021"]
        assert "no _apply_replay_record()" in result.findings[0].message

    def test_arming_no_writer_surface(self, tmp_path):
        src = _STORE_CLEAN.replace("self._append(", "self._renamed(")
        result = journal_run(tmp_path, src)
        assert [f.rule for f in result.findings] == ["AIL021"]
        assert "no journal writer calls" in result.findings[0].message

    def test_payload_keys_are_not_protocol(self, tmp_path):
        """taskId/status are payload (not True-valued, dict > 2 keys):
        consulting them outside a test is fine, and NOT consulting a
        payload key is fine too — only markers select replay arms."""
        src = _STORE_CLEAN.replace('"status": status})',
                                   '"status": status, "extra": 1})')
        result = journal_run(tmp_path, src)
        assert result.findings == []

    def test_real_store_round_trip_is_clean(self):
        """The production journal protocol (Slim/Result/Offloaded/Evict/
        KeepBlobs/Epoch) round-trips — the same surface AIL021 audits in
        the repo gate."""
        from ai4e_tpu.analysis.rules.balance import JournalReplayRoundTrip
        result = Analyzer([JournalReplayRoundTrip()], root=REPO).run(
            [os.path.join(REPO, "ai4e_tpu", "taskstore")])
        assert result.findings == []


# -- AIL022 pair-spec drift ---------------------------------------------------


class TestPairSpecDrift:
    def test_missing_close_symbol_fires(self, tmp_path):
        """The anchor module is in the scan but a declared close no
        longer resolves anywhere: the rename that would silently disarm
        AIL020's probe-slot conservation."""
        from ai4e_tpu.analysis.rules.balance import PairSpecDrift
        f = tmp_path / "pkg" / "resilience" / "breaker.py"
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_text(textwrap.dedent("""
            class CircuitBreaker:
                def begin_probe(self):
                    pass
                def record_success(self):
                    pass
                def record_failure(self):
                    pass
        """))
        result = Analyzer([PairSpecDrift()],
                          root=str(tmp_path)).run([str(tmp_path / "pkg")])
        assert [f.rule for f in result.findings] == ["AIL022"]
        f0 = result.findings[0]
        assert "'record_neutral'" in f0.message
        assert f0.fingerprint_key == "AIL022|probe-slot|record_neutral"

    def test_all_symbols_resolve_is_clean(self, tmp_path):
        from ai4e_tpu.analysis.rules.balance import PairSpecDrift
        f = tmp_path / "pkg" / "resilience" / "breaker.py"
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_text(textwrap.dedent("""
            class CircuitBreaker:
                def begin_probe(self):
                    pass
                def record_success(self):
                    pass
                def record_failure(self):
                    pass
                def record_neutral(self):
                    pass
        """))
        result = Analyzer([PairSpecDrift()],
                          root=str(tmp_path)).run([str(tmp_path / "pkg")])
        assert result.findings == []

    def test_anchor_not_in_scan_is_skipped(self, tmp_path):
        """Scanning a slice that doesn't include the pair's home surface
        must not produce drift noise (the --changed-only case is handled
        separately: project rules are skipped entirely there)."""
        from ai4e_tpu.analysis.rules.balance import PairSpecDrift
        f = tmp_path / "pkg" / "other.py"
        f.parent.mkdir(parents=True, exist_ok=True)
        f.write_text("x = 1\n")
        result = Analyzer([PairSpecDrift()],
                          root=str(tmp_path)).run([str(tmp_path / "pkg")])
        assert result.findings == []


# -- balance-family registration + CLI satellites -----------------------------


class TestBalanceGateRegistration:
    def test_balance_rules_are_registered(self):
        ids = {cls.rule_id for cls in ALL_RULES}
        assert {"AIL020", "AIL021", "AIL022"} <= ids
        assert len(ids) >= 22

    def test_list_rules_shows_balance_family(self, capsys):
        from ai4e_tpu.analysis.cli import main
        assert main(["--list-rules"]) == 0
        lines = capsys.readouterr().out.splitlines()
        assert "# paired-effect conservation" in lines
        fam_at = lines.index("# paired-effect conservation")
        first = next(i for i, l in enumerate(lines)
                     if l.startswith("AIL020"))
        assert fam_at < first

    def test_checked_in_baseline_still_empty(self):
        """ISSUE 20 acceptance: everything the balance family's first
        run found was fixed (or was a blessed idiom the engine now
        models), not baselined — the baseline ships empty."""
        import json as _json
        with open(os.path.join(REPO, "analysis_baseline.json")) as fh:
            data = _json.load(fh)
        assert data.get("findings", data if isinstance(data, list)
                        else []) == []


class TestChangedOnly:
    def _git(self, cwd, *args):
        import subprocess
        subprocess.run(
            ["git", "-c", "user.email=t@t", "-c", "user.name=t", *args],
            cwd=cwd, check=True, capture_output=True)

    def test_scopes_to_changed_files_and_skips_project_rules(
            self, tmp_path, capsys):
        from ai4e_tpu.analysis.cli import main
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "clean.py").write_text(
            "import time\nasync def old():\n    time.sleep(1)\n")
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "add", ".")
        self._git(tmp_path, "commit", "-qm", "seed")
        (pkg / "fresh.py").write_text(
            "import time\nasync def h():\n    time.sleep(2)\n")
        rc = main([str(pkg), "--root", str(tmp_path), "--no-baseline",
                   "--changed-only", "HEAD"])
        out = capsys.readouterr().out
        # Only the changed file is scanned: the committed TP in clean.py
        # does not gate the pre-commit loop (CI's full run still does).
        assert rc == 1
        assert "1 file(s)" in out
        assert "fresh.py" in out and "clean.py" not in out

    def test_no_changes_is_a_clean_pass(self, tmp_path, capsys):
        from ai4e_tpu.analysis.cli import main
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "clean.py").write_text("x = 1\n")
        self._git(tmp_path, "init", "-q")
        self._git(tmp_path, "add", ".")
        self._git(tmp_path, "commit", "-qm", "seed")
        rc = main([str(pkg), "--root", str(tmp_path), "--no-baseline",
                   "--changed-only", "HEAD"])
        assert rc == 0
        assert "nothing to scan" in capsys.readouterr().out

    def test_bad_ref_is_a_loud_config_error(self, tmp_path, capsys):
        from ai4e_tpu.analysis.cli import main
        pkg = tmp_path / "pkg"
        pkg.mkdir()
        (pkg / "m.py").write_text("x = 1\n")
        self._git(tmp_path, "init", "-q")
        rc = main([str(pkg), "--root", str(tmp_path), "--no-baseline",
                   "--changed-only", "no-such-ref"])
        assert rc == 2
        assert "git" in capsys.readouterr().err


class TestBudgetMs:
    def test_over_budget_exits_4(self, tmp_path, capsys):
        from ai4e_tpu.analysis.cli import main
        (tmp_path / "m.py").write_text("x = 1\n")
        rc = main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                   "--no-baseline", "--budget-ms", "0"])
        assert rc == 4
        assert "exceeds --budget-ms" in capsys.readouterr().err

    def test_within_budget_keeps_findings_exit(self, tmp_path, capsys):
        from ai4e_tpu.analysis.cli import main
        (tmp_path / "m.py").write_text(
            "import time\nasync def h():\n    time.sleep(1)\n")
        rc = main([str(tmp_path / "m.py"), "--root", str(tmp_path),
                   "--no-baseline", "--budget-ms", "600000"])
        assert rc == 1
        assert "exceeds" not in capsys.readouterr().err
