"""Autoscaler tests — the HPA decision rule (tolerance dead-band,
proportional scaling, scale-down stabilization, ``autoscaler.yaml:11-21``
semantics) and the live dispatcher fan-out actuator."""

import asyncio

from aiohttp import web
from aiohttp.test_utils import TestServer

from ai4e_tpu.platform_assembly import LocalPlatform, PlatformConfig
from ai4e_tpu.scaling import AutoscalePolicy, HPADecider


class FakeClock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


class TestHPADecider:
    def make(self, **kw):
        defaults = dict(min_replicas=1, max_replicas=10,
                        target_per_replica=1.0, tolerance=0.1,
                        stabilization_seconds=30.0)
        defaults.update(kw)
        clock = FakeClock()
        return HPADecider(AutoscalePolicy(**defaults), clock=clock), clock

    def test_proportional_scale_up(self):
        decider, _ = self.make()
        # 1 replica, queue depth 6, target 1/replica → 6 replicas.
        assert decider.desired(1, 6.0) == 6

    def test_clamped_to_max(self):
        decider, _ = self.make(max_replicas=4)
        assert decider.desired(1, 100.0) == 4

    def test_tolerance_dead_band_holds_steady(self):
        decider, _ = self.make()
        # 5 replicas at metric 5.4: ratio 1.08 within 10% tolerance.
        assert decider.desired(5, 5.4) == 5

    def test_scale_down_waits_for_stabilization(self):
        decider, clock = self.make(stabilization_seconds=30.0)
        assert decider.desired(1, 8.0) == 8
        # Queue instantly drains — recommendation says 1, but the window
        # still contains the 8.
        clock.t = 5.0
        assert decider.desired(8, 0.0) == 8
        # After the window passes, the low recommendation wins.
        clock.t = 40.0
        assert decider.desired(8, 0.0) == 1

    def test_scale_down_never_overshoots_current(self):
        decider, clock = self.make(stabilization_seconds=10.0)
        decider.desired(2, 20.0)  # recommends 10 (clamped) but not applied
        clock.t = 1.0
        # current stayed 2; stabilization max (10) must not force an
        # *increase* through the scale-down path.
        assert decider.desired(2, 0.1) == 2

    def test_respects_min_replicas(self):
        decider, clock = self.make(min_replicas=2, stabilization_seconds=0.0)
        clock.t = 1.0
        assert decider.desired(5, 0.0) == 2


class TestAutoscaleE2E:
    def test_dispatcher_fanout_scales_with_queue_depth(self):
        async def main():
            platform = LocalPlatform(PlatformConfig(retry_delay=0.05))

            inflight = 0
            peak = 0
            release = asyncio.Event()

            async def slow_backend(request):
                nonlocal inflight, peak
                inflight += 1
                peak = max(peak, inflight)
                try:
                    await release.wait()
                finally:
                    inflight -= 1
                task_id = request.headers.get("taskId")
                await platform.task_manager.complete_task(task_id)
                return web.Response(text="ok")

            app = web.Application()
            app.router.add_post("/v1/slow", slow_backend)
            server = TestServer(app)
            await server.start_server()
            backend = f"http://127.0.0.1:{server.port}/v1/slow"

            policy = AutoscalePolicy(min_replicas=1, max_replicas=6,
                                     target_per_replica=1.0,
                                     stabilization_seconds=0.2)
            platform.publish_async_api("/v1/slow", backend,
                                       concurrency=1, autoscale=policy,
                                       autoscale_interval=0.05)
            controller = platform.autoscalers[0]
            dispatcher = controller.target.dispatcher
            await platform.start()
            try:
                # Flood 12 tasks while the backend blocks: depth builds,
                # controller must fan the dispatcher out to max.
                for i in range(12):
                    await platform.task_manager.add_task(
                        backend, body=b"x", publish=True)
                # Generous poll: a loaded 1-core CI host can stall the
                # event loop well past the controller's nominal cadence.
                for _ in range(600):
                    if dispatcher.concurrency >= 6:
                        break
                    await asyncio.sleep(0.02)
                assert dispatcher.concurrency == 6, dispatcher.concurrency
                # concurrency == 6 only says the loops were SPAWNED;
                # whether their POSTs have reached the backend yet is an
                # event-loop photo finish (create_task → receive → connect
                # → handler entry, several hops behind the attribute
                # write). Releasing on the attribute alone raced that, and
                # the race flips with unrelated scheduling shifts — wait
                # for concurrent delivery to actually be OBSERVED first.
                for _ in range(600):
                    if peak > 1:
                        break
                    await asyncio.sleep(0.02)

                # Unblock; queue drains; after stabilization it scales back
                # to min.
                release.set()
                for _ in range(1000):
                    if dispatcher.concurrency == 1 and inflight == 0:
                        break
                    await asyncio.sleep(0.02)
                assert dispatcher.concurrency == 1, dispatcher.concurrency
                assert peak > 1  # fan-out actually delivered concurrently
            finally:
                await platform.stop()
                await server.close()

        asyncio.run(main())
