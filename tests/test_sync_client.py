"""SyncTaskManager tests — the blocking (worker-thread) task client, parity
with the reference's synchronous manager
(``Containers/Common/task_management/distributed_api_task.py:12-86``)."""

import asyncio

from aiohttp.test_utils import TestClient, TestServer

from ai4e_tpu.service import SyncTaskManager
from ai4e_tpu.taskstore import InMemoryTaskStore
from ai4e_tpu.taskstore.http import make_app


def run(coro):
    return asyncio.run(coro)


async def serve_store(store):
    client = TestClient(TestServer(make_app(store)))
    await client.start_server()
    return client


async def in_thread(fn, *args, **kwargs):
    """Run the blocking client call off-loop so the server (on this loop)
    can answer it — how user model code calls it from worker threads."""
    loop = asyncio.get_running_loop()
    return await loop.run_in_executor(None, lambda: fn(*args, **kwargs))


class TestSyncTaskManager:
    def test_lifecycle(self):
        async def main():
            store = InMemoryTaskStore()
            http = await serve_store(store)
            tm = SyncTaskManager(str(http.make_url("/")))
            try:
                created = await in_thread(tm.add_task, "/v1/org/api",
                                          b"PAYLOAD")
                tid = created["TaskId"]
                assert created["Status"] == "created"

                updated = await in_thread(tm.update_task_status, tid,
                                          "running - 50%")
                assert updated["Status"] == "running - 50%"

                done = await in_thread(tm.complete_task, tid,
                                       "completed - scored")
                assert done["BackendStatus"] == "completed"
                assert (await in_thread(tm.get_task_status, tid)
                        )["Status"] == "completed - scored"
            finally:
                await http.close()

        run(main())

    def test_add_task_reuses_dispatcher_task_id(self):
        # taskId header present → fetch, don't create (api_task.py:12-20).
        async def main():
            store = InMemoryTaskStore()
            http = await serve_store(store)
            tm = SyncTaskManager(str(http.make_url("/")))
            try:
                first = await in_thread(tm.add_task, "/v1/a", b"x")
                again = await in_thread(tm.add_task, "/v1/a", b"y",
                                        first["TaskId"])
                assert again["TaskId"] == first["TaskId"]
                assert len(list(store.snapshot())) == 1
            finally:
                await http.close()

        run(main())

    def test_pipeline_and_results(self):
        async def main():
            store = InMemoryTaskStore()
            http = await serve_store(store)
            tm = SyncTaskManager(str(http.make_url("/")))
            try:
                created = await in_thread(tm.add_task, "/v1/det", b"IMG")
                tid = created["TaskId"]
                handed = await in_thread(tm.add_pipeline_task, tid, "/v1/cls")
                assert handed["TaskId"] == tid
                # Empty pipeline body → original replayed by the store.
                assert store.get(tid).body == b"IMG"

                await in_thread(tm.set_result, tid, b'{"species": "lynx"}')
                got = await in_thread(tm.get_result, tid)
                assert got == b'{"species": "lynx"}'

                await in_thread(tm.set_result, tid, b"crops",
                                "application/octet-stream", "detector")
                assert (await in_thread(tm.get_result, tid, "detector")
                        ) == b"crops"
            finally:
                await http.close()

        run(main())

    def test_unknown_task_errors(self):
        async def main():
            store = InMemoryTaskStore()
            http = await serve_store(store)
            tm = SyncTaskManager(str(http.make_url("/")))
            try:
                assert (await in_thread(tm.get_task_status, "ghost")) is None
                try:
                    await in_thread(tm.update_task_status, "ghost", "running")
                    raise AssertionError("expected KeyError")
                except KeyError:
                    pass
            finally:
                await http.close()

        run(main())
